"""Round-batch construction (SURVEY.md §7 "static shapes vs heterogeneous clients").

XLA traces one round program with fixed shapes; real clients have
heterogeneous example counts. The resolution: every client-round is
padded to the same ``[steps, batch]`` grid of example *indices*, with
the true example counts riding along for the FedAvg weighted sum. The
index tensors are tiny (int32), generated on host with NumPy, and
gathered **on device** against the HBM-resident example arrays — the
host never moves example bytes during training.

Two r7 changes to the padding story:

- **The validity mask is not shipped.** Padding is contiguous per epoch
  (each epoch block holds its ``n`` real indices first, zeros after),
  so the full ``[K, steps, batch]`` float32 mask is derivable from a
  tiny ``[K, 2]`` int32 *spec* — ``(examples_per_epoch, valid_steps)``
  — via ``iota < n`` comparisons. The engines rebuild the identical
  mask on device (round_engine ``on_device_mask``); the host ships only
  the spec, roughly halving round-input wire bytes.
- **Step buckets** (``run.shape_buckets``): the grid's step count can
  be quantized per round onto a small geometric ladder sized by the
  *sampled cohort's* max requirement instead of the federation max —
  padded steps are exact algebraic no-ops, so trimming them is bitwise
  neutral (pinned by tests/test_shape_buckets.py) while skipping the
  mask-zeroed scan iterations entirely. :func:`bucket_ladder` /
  :func:`pick_bucket` hold the ladder math.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

from colearn_federated_learning_tpu.config import ClientConfig, DataConfig
from colearn_federated_learning_tpu.data.core import FederatedData


@dataclass(frozen=True)
class RoundShape:
    """Static shape of one client-round. Changing any field retraces XLA."""

    local_epochs: int
    steps_per_epoch: int
    batch_size: int
    cap: int  # max examples a client contributes per epoch

    @property
    def steps(self) -> int:
        return self.local_epochs * self.steps_per_epoch


def compute_round_shape(
    fed: FederatedData, client_cfg: ClientConfig, data_cfg: DataConfig
) -> RoundShape:
    sizes = fed.client_sizes()
    cap = data_cfg.max_examples_per_client or int(sizes.max())
    cap = min(cap, int(sizes.max()))
    steps_per_epoch = max(1, math.ceil(cap / client_cfg.batch_size))
    return RoundShape(
        local_epochs=client_cfg.local_epochs,
        steps_per_epoch=steps_per_epoch,
        batch_size=client_cfg.batch_size,
        cap=cap,
    )


# ---------------------------------------------------------------------------
# step-bucket ladder (run.shape_buckets)
# ---------------------------------------------------------------------------


def bucket_ladder(steps_per_epoch: int, base: float, count: int) -> List[int]:
    """Geometric ladder of steps-per-epoch bucket values, ascending.

    The top rung is always the federation-max ``steps_per_epoch`` (the
    legacy full shape, so every cohort fits); lower rungs divide it by
    ``base`` repeatedly, floored at 1 and deduplicated. The ladder size
    bounds the compile budget: one round executable per *realized* rung.
    """
    if steps_per_epoch < 1:
        raise ValueError(f"steps_per_epoch must be >= 1, got {steps_per_epoch}")
    if base <= 1.0:
        raise ValueError(f"shape_buckets.base must be > 1, got {base}")
    if count < 1:
        raise ValueError(f"shape_buckets.count must be >= 1, got {count}")
    rungs = {
        max(1, math.ceil(steps_per_epoch / base**i)) for i in range(count)
    }
    rungs.add(steps_per_epoch)
    return sorted(rungs)


def pick_bucket(needed_steps_per_epoch: int, ladder: Sequence[int]) -> int:
    """Smallest ladder rung covering the cohort's step requirement."""
    for rung in ladder:
        if rung >= needed_steps_per_epoch:
            return rung
    raise ValueError(
        f"no ladder rung covers steps_per_epoch={needed_steps_per_epoch} "
        f"(ladder {list(ladder)}) — the top rung must be the full shape"
    )


# ---------------------------------------------------------------------------
# round-index construction
# ---------------------------------------------------------------------------


def _round_draws(rng: np.random.Generator, k: int, max_len: int,
                 cap_eff: int, local_epochs: int):
    """The round's host randomness, drawn as two dense blocks so the
    vectorized builder and the per-row reference consume the stream
    identically: ``sel`` keys order each client's shard (cap
    subsampling = the first ``cap`` of that order), ``perm`` keys order
    each epoch's selected subset."""
    sel = rng.random((k, max_len))
    perm = rng.random((k, local_epochs, cap_eff))
    return sel, perm


def make_round_spec(
    fed: FederatedData,
    cohort_ids: Sequence[int],
    shape: RoundShape,
    rng: np.random.Generator,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Build (idx, spec, n_examples) for one round's cohort.

    idx:        [K, steps, batch] int32 — gather indices into train_x/
                train_y; padding positions point at index 0. Real
                indices pack CONTIGUOUSLY at the head of each epoch
                block — the invariant that makes the mask derivable.
    spec:       [K, 2] int32 — (examples per epoch, valid steps). The
                full float32 validity mask is ``mask_from_spec(spec,
                shape)``; the engines rebuild it on device instead of
                shipping the [K, steps, batch] slab.
    n_examples: [K] float32 — real examples processed this round (the
                FedAvg weight; proportional to |D_i| at equal epochs).

    Fully vectorized over (clients × epochs): one argsort-ordered key
    block replaces the O(K·E) per-row ``rng.permutation`` loop that
    used to sit on the round loop's host hot path. The per-row
    reference twin (``_make_round_spec_loop``) consumes the identical
    draws; equality is pinned by tests/test_loader.py. The random
    draws depend only on the cohort's shard lengths and the cap —
    never on the grid shape — so a bucketed (smaller-``steps``) grid
    packs the *same* example order as the full grid.

    LOAD-BEARING for the client store (data/store.py): selection and
    ordering happen by POSITION within each shard (argsort over keys
    that depend only on lengths), and the shard's index *values* only
    flow through as opaque gather targets. A store-backed federation
    renumbers global example ids (client-contiguous) but maps every
    (client, position) to the same example bytes — so store-backed
    runs pack byte-identical examples into identical grid slots and
    stay BITWISE-equal to the in-memory runs they were converted from
    (tests/test_store.py pins this across engines and fuse_rounds).
    Any future change that makes draws or ordering depend on index
    VALUES breaks that contract.
    """
    k = len(cohort_ids)
    steps, batch = shape.steps, shape.batch_size
    epochs, per_epoch = shape.local_epochs, shape.steps_per_epoch * batch
    if k == 0:
        return (
            np.zeros((0, steps, batch), np.int32),
            np.zeros((0, 2), np.int32),
            np.zeros((0,), np.float32),
        )
    shards = [np.asarray(fed.client_indices[c]) for c in cohort_ids]
    lens = np.array([len(s) for s in shards], np.int64)
    max_len = int(lens.max()) if k else 0
    take = np.minimum(lens, shape.cap)
    cap_eff = int(take.max())
    if cap_eff > per_epoch:
        raise ValueError(
            f"round grid holds {per_epoch} examples/epoch but the cohort "
            f"max is {cap_eff} — steps_per_epoch={shape.steps_per_epoch} "
            f"is too small for this cohort"
        )
    sel_keys, perm_keys = _round_draws(rng, k, max_len, cap_eff, epochs)

    # padded [K, max_len] shard matrix; rows shorter than max_len carry
    # +inf selection keys so their tail never sorts into the head
    row_pos = np.arange(max_len)[None, :]
    in_shard = row_pos < lens[:, None]
    padded = np.zeros((k, max_len), np.int64)
    if max_len:
        padded[in_shard] = np.concatenate(shards)
        sel_keys = np.where(in_shard, sel_keys, np.inf)
    order = np.argsort(sel_keys, axis=1, kind="stable")
    # chosen[i, :take[i]] is a uniform random subset (and order) of the
    # shard — cap subsampling and full-shard selection in one expression
    chosen = np.take_along_axis(padded, order, axis=1)[:, :cap_eff]

    # per-epoch permutation of each client's selected subset
    sel_pos = np.arange(cap_eff)[None, None, :]
    keyed = np.where(sel_pos < take[:, None, None], perm_keys, np.inf)
    ep_order = np.argsort(keyed, axis=2, kind="stable")
    perm = np.take_along_axis(
        np.broadcast_to(chosen[:, None, :], (k, epochs, cap_eff)),
        ep_order, axis=2,
    )

    # pack: epoch block e of row i holds perm[i, e, :take[i]] first,
    # zeros after (contiguous padding — the mask-spec invariant)
    idx = np.zeros((k, epochs, per_epoch), np.int32)
    valid = np.broadcast_to(sel_pos < take[:, None, None], perm.shape)
    idx[:, :, :cap_eff][valid] = perm[valid].astype(np.int32)
    spec = np.stack(
        [take.astype(np.int64), np.full(k, steps, np.int64)], axis=1
    ).astype(np.int32)
    n_examples = (take * epochs).astype(np.float32)
    return idx.reshape(k, steps, batch), spec, n_examples


def _make_round_spec_loop(fed, cohort_ids, shape: RoundShape,
                          rng: np.random.Generator):
    """Per-row/per-epoch reference twin of :func:`make_round_spec`:
    identical draws (``_round_draws``), straightforward Python loops for
    the ordering and packing. Exists so the vectorized builder's argsort
    and scatter algebra is pinned against an obviously-correct loop
    (tests/test_loader.py)."""
    k = len(cohort_ids)
    steps, batch = shape.steps, shape.batch_size
    epochs, per_epoch = shape.local_epochs, shape.steps_per_epoch * batch
    shards = [np.asarray(fed.client_indices[c]) for c in cohort_ids]
    lens = [len(s) for s in shards]
    max_len = max(lens) if k else 0
    take = [min(n, shape.cap) for n in lens]
    cap_eff = max(take) if k else 0
    sel_keys, perm_keys = _round_draws(rng, k, max_len, cap_eff, epochs)
    idx = np.zeros((k, steps * batch), np.int32)
    spec = np.zeros((k, 2), np.int32)
    n_examples = np.zeros((k,), np.float32)
    for i in range(k):
        order = np.argsort(sel_keys[i, : lens[i]], kind="stable")
        chosen = shards[i][order][: take[i]]
        for e in range(epochs):
            ep = np.argsort(perm_keys[i, e, : take[i]], kind="stable")
            off = e * per_epoch
            idx[i, off : off + take[i]] = chosen[ep].astype(np.int32)
        spec[i] = (take[i], steps)
        n_examples[i] = take[i] * epochs
    return idx.reshape(k, steps, batch), spec, n_examples


def mask_from_spec(spec: np.ndarray, shape: RoundShape) -> np.ndarray:
    """Expand a ``[K, 2]`` spec into the full ``[K, steps, batch]``
    float32 validity mask — the NumPy twin of the engines' on-device
    reconstruction (round_engine ``_mask_from_spec``); both must equal
    the legacy shipped mask bit-for-bit (0.0/1.0 exactly)."""
    return expand_mask_spec(
        np.asarray(spec), shape.steps, shape.batch_size, shape.local_epochs
    )


def expand_mask_spec(spec: np.ndarray, steps: int, batch: int,
                     local_epochs: int) -> np.ndarray:
    """Shape-parameter form of :func:`mask_from_spec` (the engines know
    the grid dims, not a RoundShape). A position is valid iff its flat
    offset within its epoch block is below the client's per-epoch
    example count AND its step is below the client's valid-step bound
    (straggler truncation sets the latter)."""
    if steps % local_epochs:
        raise ValueError(
            f"steps={steps} not a multiple of local_epochs={local_epochs}"
        )
    spe = steps // local_epochs
    s = np.arange(steps)[None, :, None]
    b = np.arange(batch)[None, None, :]
    pos = (s % spe) * batch + b
    n_ep = spec[:, 0][:, None, None]
    vsteps = spec[:, 1][:, None, None]
    return ((pos < n_ep) & (s < vsteps)).astype(np.float32)


def spec_examples(spec: np.ndarray, shape: RoundShape) -> np.ndarray:
    """Closed-form ``mask_from_spec(spec, shape).sum((1, 2))`` — the
    real example count per client under the spec's per-epoch count and
    valid-step bound (exact integer math, cast to the f32 the FedAvg
    weights ride)."""
    spe, batch = shape.steps_per_epoch, shape.batch_size
    n = spec[:, 0].astype(np.int64)
    vsteps = spec[:, 1].astype(np.int64)
    total = np.zeros(len(spec), np.int64)
    for e in range(shape.local_epochs):
        avail = np.clip(vsteps - e * spe, 0, spe)
        total += np.minimum(n, avail * batch)
    return total.astype(np.float32)


def make_round_indices(
    fed: FederatedData,
    cohort_ids: Sequence[int],
    shape: RoundShape,
    rng: np.random.Generator,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Build (idx, mask, n_examples) for one round's cohort.

    The legacy full-mask interface: ``mask`` is the [K, steps, batch]
    float32 validity mask, expanded host-side from the compact spec.
    The driver's engines no longer consume this form (they rebuild the
    mask on device from the spec — :func:`make_round_spec`); the gossip
    and fedbuff paths, and shape-level tests, still do.
    """
    idx, spec, n_examples = make_round_spec(fed, cohort_ids, shape, rng)
    return idx, mask_from_spec(spec, shape), n_examples


def iter_client_slabs(train_x, train_y, client_indices, client_ids,
                      buffer_bytes: int):
    """Stream per-client example arrays for eval, store-aware.

    Yields ``(cid, x, y)`` for every client in ``client_ids`` (order
    preserved). Store-backed federations expose ``client_indices`` as a
    lazy :class:`~colearn_federated_learning_tpu.data.store
    .ClientIndexView` whose ``starts`` make every client a contiguous
    global-id range — so instead of materializing a transient per-client
    arange and issuing one random-access gather per client, consecutive
    requested clients are COALESCED into one contiguous multi-client
    range gather (shard-by-shard sequential reads through the mmap),
    bounded by ``buffer_bytes`` of reassembly buffer, and the
    per-client views are sliced out of that slab. The bytes handed to
    the caller are identical either way — store-backed eval stays
    bitwise-equal to its in-memory twin (test-pinned).

    In-memory federations (plain index lists) take the classic
    per-client fancy-index path unchanged."""
    starts = getattr(client_indices, "starts", None)
    if starts is None or not hasattr(train_x, "gather"):
        for cid in client_ids:
            ids = np.asarray(client_indices[cid])
            yield cid, train_x[ids], train_y[ids]
        return
    rec_bytes = (
        int(np.prod(train_x.shape[1:]) or 1) * train_x.dtype.itemsize
        + int(np.prod(train_y.shape[1:]) or 1) * train_y.dtype.itemsize
    )
    max_rows = max(1, int(buffer_bytes) // max(rec_bytes, 1))
    group: list = []
    rows = 0

    def flush(group):
        lo = int(starts[group[0]])
        hi = int(starts[group[-1] + 1])
        slab_x = train_x[lo:hi]
        slab_y = train_y[lo:hi]
        for cid in group:
            a, b = int(starts[cid]) - lo, int(starts[cid + 1]) - lo
            yield cid, slab_x[a:b], slab_y[a:b]

    for cid in client_ids:
        cid = int(cid)
        n = int(starts[cid + 1] - starts[cid])
        contiguous = bool(group) and cid == group[-1] + 1
        if group and (not contiguous or rows + n > max_rows):
            yield from flush(group)
            group, rows = [], 0
        group.append(cid)
        rows += n
    if group:
        yield from flush(group)


def eval_batches(x: np.ndarray, y: np.ndarray, batch_size: int):
    """Pad the test set to a whole number of fixed-size batches.

    Returns (x_batches [B, batch, ...], y_batches, mask [B, batch]) so the
    jitted eval loop sees one static shape.
    """
    n = len(x)
    if n == 0:
        # padding repeats x[:1]; an empty shard has no row to repeat —
        # fail with the real cause instead of a bare IndexError deep in
        # np.repeat (empty silo shards reach here via federated eval)
        raise ValueError(
            "eval_batches requires at least one example; got an empty "
            "array (empty client shard or empty test split)"
        )
    n_batches = max(1, math.ceil(n / batch_size))
    total = n_batches * batch_size
    pad = total - n
    xp = np.concatenate([x, np.repeat(x[:1], pad, axis=0)]) if pad else x
    yp = np.concatenate([y, np.repeat(y[:1], pad, axis=0)]) if pad else y
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    return (
        xp.reshape((n_batches, batch_size) + x.shape[1:]),
        yp.reshape((n_batches, batch_size) + y.shape[1:]),
        mask.reshape(n_batches, batch_size),
    )
