"""Federated partitioners (SURVEY.md §2 C11).

Capability parity (BASELINE.json:8-11): IID, Dirichlet(α) label-skew
non-IID, LEAF natural per-writer splits, and cross-silo equal splits.
All partitioners are pure NumPy on index arrays — they produce the
federation *structure*; bytes stay in the flat example arrays.

Invariants (pinned by tests, SURVEY.md §4.1):
- the client shards partition the example index set (disjoint, complete,
  up to the documented Dirichlet remainder handling);
- Dirichlet: α→∞ approaches IID class mixtures, α→0 approaches
  label-pure clients;
- determinism: same seed ⇒ identical shards.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def iid_partition(n: int, num_clients: int, seed: int) -> List[np.ndarray]:
    if num_clients > n:
        # array_split would silently hand back empty shards that only
        # surface rounds later as an opaque eval/np.repeat error — name
        # both numbers at partition time instead
        raise ValueError(
            f"iid_partition: {num_clients} clients over {n} examples "
            f"would leave {num_clients - n} client shard(s) empty — "
            f"reduce data.num_clients or provide more examples"
        )
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    return [np.sort(s) for s in np.array_split(perm, num_clients)]


def dirichlet_partition(
    labels: np.ndarray, num_clients: int, num_classes: int, alpha: float, seed: int,
    min_size: int = 1, info: Optional[dict] = None,
) -> List[np.ndarray]:
    """Label-skew non-IID: for each class, split its examples across clients
    by proportions drawn from Dirichlet(α)·𝟙. Standard FL recipe (Hsu et al.).

    Re-draws until every client has ≥ ``min_size`` examples, which mirrors
    the usual implementation and keeps downstream static shapes sane. At
    extreme α (near-label-pure splits) redraws can keep failing — e.g.
    α≈0.05, 2 classes, 10 clients leaves most clients empty on every
    draw — so after the retry budget a deterministic REPAIR bulk-moves
    examples from the largest shards to the starved ones instead of
    raising; the result is still a partition and still extremely
    label-skewed, and stays deterministic in ``seed``. The repair
    changes the effective label-skew distribution, so it is SURFACED:
    when ``info`` is passed, ``info["repair_used"]`` /
    ``info["repair_moved"]`` record whether and how many examples were
    relocated (threaded into ``FederatedData.meta`` and the run log by
    data/core.py)."""
    rng = np.random.default_rng(seed)
    n = len(labels)
    if n < num_clients * min_size:
        raise ValueError(
            f"dirichlet_partition: {n} examples cannot give {num_clients} "
            f"clients ≥ {min_size} each"
        )
    shards: List[List[int]] = []
    for _attempt in range(100):
        shards = [[] for _ in range(num_clients)]
        for c in range(num_classes):
            idx_c = np.flatnonzero(labels == c)
            rng.shuffle(idx_c)
            props = rng.dirichlet(np.full(num_clients, alpha))
            # cumulative split points over this class's examples
            cuts = (np.cumsum(props)[:-1] * len(idx_c)).astype(int)
            for shard, part in zip(shards, np.split(idx_c, cuts)):
                shard.extend(part.tolist())
        sizes = [len(s) for s in shards]
        if min(sizes) >= min_size:
            if info is not None:
                info["repair_used"] = False
                info["repair_moved"] = 0
            return [np.sort(np.array(s, np.int64)) for s in shards]
    # Repair the final draw: feed starved shards from the largest ones.
    # Each starved shard's deficit is computed once and filled with bulk
    # slices from the current largest donors (donors never drop below
    # min_size, so repairs can't cascade) — O(num_clients·log) instead of
    # one argmin/argmax pass per moved example, which matters at extreme
    # α on large datasets where the total deficit can be tens of
    # thousands of examples.
    sizes = np.array([len(s) for s in shards])
    moved = 0
    for needy in np.flatnonzero(sizes < min_size):
        deficit = min_size - int(sizes[needy])
        while deficit > 0:
            donor = int(sizes.argmax())
            take = min(deficit, int(sizes[donor]) - min_size)
            shards[needy].extend(shards[donor][-take:])
            del shards[donor][-take:]
            sizes[donor] -= take
            sizes[needy] += take
            deficit -= take
            moved += take
    if info is not None:
        info["repair_used"] = True
        info["repair_moved"] = moved
        # the α actually drawn from — the 'natural' fallback calls this
        # with a hardcoded α, not the config field
        info["repair_alpha"] = alpha
    return [np.sort(np.array(s, np.int64)) for s in shards]


def natural_partition(
    groups: Sequence[np.ndarray], num_clients: int, seed: int
) -> List[np.ndarray]:
    """LEAF-style natural split: each group is one writer/character's
    examples. If there are more groups than clients, groups are merged
    round-robin by size (largest first) to balance; fewer groups than
    clients is an error (natural splits can't be subdivided)."""
    if len(groups) < num_clients:
        raise ValueError(
            f"natural_partition: {len(groups)} natural groups < {num_clients} clients"
        )
    order = np.argsort([-len(g) for g in groups])
    rng = np.random.default_rng(seed)
    assign = [[] for _ in range(num_clients)]
    sizes = np.zeros(num_clients, np.int64)
    for gi in order:
        # place largest remaining group on the currently smallest client
        tgt = int(np.argmin(sizes))
        assign[tgt].append(gi)
        sizes[tgt] += len(groups[gi])
    del rng  # reserved for future randomized tie-breaking
    return [
        np.sort(np.concatenate([np.asarray(groups[gi], np.int64) for gi in gis]))
        for gis in assign
    ]


def silo_partition(n: int, num_clients: int, seed: int) -> List[np.ndarray]:
    """Cross-silo: equal random split (silos are institutions with big,
    roughly-IID shards — BASELINE.json:11's 32-silo ImageNet config)."""
    return iid_partition(n, num_clients, seed)


def partition(
    kind: str,
    labels: np.ndarray,
    num_clients: int,
    num_classes: int,
    alpha: float,
    seed: int,
    natural_groups: Optional[Sequence[np.ndarray]] = None,
    info: Optional[dict] = None,
) -> List[np.ndarray]:
    n = len(labels)
    if kind == "iid":
        return iid_partition(n, num_clients, seed)
    if kind == "dirichlet":
        return dirichlet_partition(labels, num_clients, num_classes, alpha, seed,
                                   info=info)
    if kind == "natural":
        if natural_groups is None:
            # Synthetic stand-in for a LEAF natural split: heavy label skew +
            # heterogeneous sizes, which is what "natural" delivers in practice.
            return dirichlet_partition(labels, num_clients, num_classes,
                                       alpha=0.3, seed=seed, info=info)
        return natural_partition(natural_groups, num_clients, seed)
    if kind == "silo":
        if natural_groups is not None:
            # real cross-silo data: one institution == one natural group
            return natural_partition(natural_groups, num_clients, seed)
        return silo_partition(n, num_clients, seed)
    raise ValueError(f"unknown partition kind {kind!r}")
