"""Data layer (SURVEY.md §2 C10/C11, layer L1).

Dataset registry + federated partitioners + the static-shape round-batch
index builder. The design splits "bytes" from "structure": example
arrays live once in HBM (device-resident), while per-round client
batches are tiny int32 index tensors generated on host — the host never
touches example data inside the round loop.
"""

from colearn_federated_learning_tpu.data.core import (  # noqa: F401
    FederatedData,
    build_federated_data,
    dataset_registry,
)
