"""Data layer (SURVEY.md §2 C10/C11, layer L1).

Dataset registry + federated partitioners + the static-shape round-batch
index builder. The design splits "bytes" from "structure": example
arrays live once in HBM (device-resident), while per-round client
batches are tiny int32 index tensors generated on host — the host never
touches example data inside the round loop. At million-client scale the
bytes move to an on-disk mmap client store (`data/store.py`,
``data.store.dir``) and only the sampled cohort's records ever become
host-resident.
"""

from colearn_federated_learning_tpu.data.core import (  # noqa: F401
    FederatedData,
    build_federated_data,
    dataset_registry,
)
from colearn_federated_learning_tpu.data.store import (  # noqa: F401
    build_synthetic_store,
    open_store,
    write_store,
)
