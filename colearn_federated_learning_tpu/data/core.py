"""Dataset registry and loaders (SURVEY.md §2 C10).

Capability parity targets (BASELINE.json:7-11): MNIST, CIFAR-10, LEAF
FEMNIST, LEAF Shakespeare, federated ImageNet.

Each loader first looks for real data files under ``data_dir`` (the
formats a user would naturally drop in: keras-style ``mnist.npz``,
CIFAR-10 python pickles, LEAF ``all_data.json``); this sandbox has zero
egress so when files are absent and ``synthetic_fallback`` is enabled a
**deterministic, learnable synthetic stand-in** with identical shapes,
dtypes and class structure is generated instead — class-template images
(or a fixed Markov chain for text) plus noise, so convergence tests are
meaningful, not vacuous. The provenance is recorded in ``meta.source``
so benchmarks/logs can never silently confuse the two.
"""

from __future__ import annotations

import os
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List

import numpy as np

from colearn_federated_learning_tpu.config import DataConfig
from colearn_federated_learning_tpu.data import partition as partition_lib
from colearn_federated_learning_tpu.utils.registry import Registry

dataset_registry = Registry("dataset")


@dataclass
class FederatedData:
    """A dataset plus its federated structure.

    ``train_x``/``train_y`` are flat example arrays; the federation is the
    ``client_indices`` list (one int array of example ids per client) —
    partitioning is metadata, the bytes are stored once.

    task: "classify" (y: [N] int labels) or "lm" (x: [N,T] tokens,
    y: [N,T] next-token targets).
    """

    train_x: np.ndarray
    train_y: np.ndarray
    test_x: np.ndarray
    test_y: np.ndarray
    client_indices: List[np.ndarray]
    num_classes: int
    task: str = "classify"
    meta: Dict[str, Any] = field(default_factory=dict)

    @property
    def num_clients(self) -> int:
        return len(self.client_indices)

    def client_sizes(self) -> np.ndarray:
        # store-backed federations (data/store.py ClientIndexView) carry
        # the per-client counts directly — the O(num_clients)-aranges
        # loop below would materialize what the lazy view exists to avoid
        sizes = getattr(self.client_indices, "sizes", None)
        if sizes is not None:
            return np.asarray(sizes, np.int64)
        return np.array([len(ix) for ix in self.client_indices], np.int64)


# ---------------------------------------------------------------------------
# synthetic generators (deterministic, learnable)
# ---------------------------------------------------------------------------


def _synthetic_images(rng: np.random.Generator, n: int, templates: np.ndarray,
                      template_weight: float = 0.7):
    """Class-template images + noise: x = w·template[y] + (1−w)·noise
    with w = ``template_weight`` (DataConfig.synthetic_template_weight).

    The SAME templates generate train and test (only noise and label draws
    differ), so the task is learnable by a small convnet in a handful of
    rounds — what the convergence smoke tests (SURVEY.md §4.2) need. The
    default w=0.7 saturates (acc → 1.0); the convergence REGRESSION
    (tests/test_convergence.py) lowers w so the task plateaus strictly
    below 1.0 and a pinned mid-curve band can detect subtle aggregation
    math drift, not just outright breakage (VERDICT r3 weak-#3).

    Stored as RAW uint8 (like the real datasets' on-disk form): 4× less
    HBM and 4× less host→device transfer than f32; the [0,1] scaling is
    fused on device (client/trainer.py ``normalize_input``).
    """
    num_classes, shape = templates.shape[0], templates.shape[1:]
    w = float(template_weight)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    noise = rng.uniform(0.0, 1.0, size=(n,) + tuple(shape)).astype(np.float32)
    x = w * templates[y] + (1.0 - w) * noise
    return np.clip(np.rint(x * 255.0), 0, 255).astype(np.uint8), y


def _synthetic_template_pair(rng: np.random.Generator, n: int,
                             templates: np.ndarray, w: float,
                             label_noise: float = 0.0):
    """Second synthetic task family (VERDICT r4 weak-#4): each image
    superposes TWO class templates, x = w·(T_a + T_b)/2 + (1−w)·noise
    with a ≠ b, and the label is y = (a + b) mod C. Any LINEAR pixel
    score decomposes additively over the two strokes (s·x ≈ (s·T_a +
    s·T_b)/2), but the modular-sum label is not additively separable —
    a linear model is capped far below the ceiling (measured: linear
    probe ~0.2) while a convnet that detects the strokes and learns the
    nonlinear readout is not. Unlike a random-pixel teacher (measured:
    unlearnable by a small convnet — no spatial structure), the strokes
    keep the task inside what the model family can actually fit, so the
    regression band stays tight. Label noise sets a strict ceiling.

    Same template sharing as the first family: train and test differ
    only in draws, never in templates."""
    num_classes = templates.shape[0]
    a = rng.integers(0, num_classes, n)
    b = (a + rng.integers(1, num_classes, n)) % num_classes
    noise = rng.uniform(0.0, 1.0,
                        size=(n,) + templates.shape[1:]).astype(np.float32)
    x = w * (templates[a] + templates[b]) / 2.0 + (1.0 - w) * noise
    x_u8 = np.clip(np.rint(x * 255.0), 0, 255).astype(np.uint8)
    y = ((a + b) % num_classes).astype(np.int32)
    if label_noise > 0.0:
        flip = rng.random(n) < label_noise
        y[flip] = rng.integers(0, num_classes, flip.sum()).astype(np.int32)
    return x_u8, y


def _synthetic_text(rng: np.random.Generator, n: int, seq_len: int, vocab: int,
                    successors: np.ndarray):
    """Sequences from a fixed sparse Markov chain → next-token prediction is
    learnable well above chance (each symbol has ~4 plausible successors).

    ``successors`` is REQUIRED (no convenient default): the caller draws
    the transition table ONCE and shares it between the train and test
    calls — drawing it per call (the pre-r5 behavior) gave the two
    splits DIFFERENT chains, so eval accuracy sat at chance (with
    worse-than-uniform loss) no matter how well the model learned the
    train chain."""
    seqs = np.empty((n, seq_len + 1), np.int32)
    state = rng.integers(0, vocab, size=n)
    seqs[:, 0] = state
    for t in range(1, seq_len + 1):
        choice = rng.integers(0, 4, size=n)
        state = successors[seqs[:, t - 1], choice]
        seqs[:, t] = state
    return seqs[:, :-1].copy(), seqs[:, 1:].copy()


# ---------------------------------------------------------------------------
# loaders — real files when present, synthetic stand-in otherwise
# ---------------------------------------------------------------------------


def _stable_seed(name: str) -> int:
    # abs(hash()) is salted per-process; datasets must be reproducible
    return int.from_bytes(name.encode(), "little") % (2**31)


def _scaled_train_size(cfg: DataConfig) -> int:
    """Synthetic corpora must be big enough to partition: ≥32 examples per
    client on average, or the Dirichlet/natural min_size retry can't succeed
    (e.g. 500 FEMNIST clients over the 2048-example default)."""
    return max(cfg.synthetic_train_size, cfg.num_clients * 32)


def _image_loader(name: str, shape, num_classes: int, real_fn, size_kwarg=None):
    def load(cfg: DataConfig, **kwargs):
        # Geometry-flexible datasets (federated ImageNet) take their edge
        # size from the model kwargs so the config and the executed shapes
        # always agree — a config saying image_size=224 runs 224, real or
        # synthetic.
        shp = tuple(shape)
        if size_kwarg is not None and kwargs.get(size_kwarg):
            s = int(kwargs[size_kwarg])
            shp = (s, s, shape[-1])
        data_dir = os.path.expanduser(cfg.data_dir)
        real = real_fn(data_dir) if real_fn else None
        extra_meta = {}
        if real is not None:
            if len(real) == 5:  # loader supplies meta (e.g. natural_groups)
                tx, ty, ex, ey, extra_meta = real
            else:
                tx, ty, ex, ey = real
            source = "real"
            shp = tuple(tx.shape[1:])
        elif cfg.synthetic_fallback:
            rng = np.random.default_rng(_stable_seed(name))
            n_train = _scaled_train_size(cfg)
            if cfg.synthetic_task == "template_pair":
                templates = rng.uniform(
                    0.0, 1.0, size=(num_classes,) + shp
                ).astype(np.float32)
                w = cfg.synthetic_template_weight
                tx, ty = _synthetic_template_pair(
                    rng, n_train, templates, w,
                    label_noise=cfg.synthetic_label_noise,
                )
                ex, ey = _synthetic_template_pair(
                    rng, cfg.synthetic_test_size, templates, w,
                    label_noise=cfg.synthetic_label_noise,
                )
            else:
                templates = rng.uniform(
                    0.0, 1.0, size=(num_classes,) + shp
                ).astype(np.float32)
                w = cfg.synthetic_template_weight
                tx, ty = _synthetic_images(rng, n_train, templates, w)
                ex, ey = _synthetic_images(
                    rng, cfg.synthetic_test_size, templates, w
                )
            source = "synthetic"
        else:
            raise FileNotFoundError(
                f"{name}: no data under {data_dir} and synthetic_fallback=False"
            )
        meta = {"source": source, "input_shape": shp, **extra_meta}
        return tx, ty, ex, ey, meta, num_classes, "classify"

    return load


def _try_mnist_real(data_dir: str):
    path = os.path.join(data_dir, "mnist.npz")
    if not os.path.exists(path):
        return None
    with np.load(path) as d:
        # kept as raw uint8 — normalization happens on device
        tx = d["x_train"].astype(np.uint8)[..., None]
        ex = d["x_test"].astype(np.uint8)[..., None]
        return tx, d["y_train"].astype(np.int32), ex, d["y_test"].astype(np.int32)


def _try_cifar10_real(data_dir: str):
    base = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(base):
        return None
    def read(fname):
        with open(os.path.join(base, fname), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        # raw uint8 — normalization happens on device
        return np.ascontiguousarray(x), np.array(d[b"labels"], np.int32)
    xs, ys = zip(*[read(f"data_batch_{i}") for i in range(1, 6)])
    tx, ty = np.concatenate(xs), np.concatenate(ys)
    ex, ey = read("test_batch")
    return tx, ty, ex, ey


def _try_femnist_real(data_dir: str):
    if not os.path.isdir(os.path.join(data_dir, "femnist")):
        return None
    from colearn_federated_learning_tpu.data.leaf import load_femnist

    return load_femnist(data_dir)


def _try_imagenet_real(data_dir: str, test_fraction: float = 0.05):
    """Federated ImageNet, directory-of-silos layout: ``data_dir/
    imagenet_federated/silo_*.npz`` (each an institution's shard with
    ``x`` [n,H,W,3] uint8/float and ``y`` [n] labels) plus an optional
    ``test.npz``; without one, the last ~5% of each silo is held out.
    Silo membership is returned as ``natural_groups`` so the ``silo``
    partitioner preserves real institutional boundaries.
    """
    base = os.path.join(data_dir, "imagenet_federated")
    if not os.path.isdir(base):
        return None
    silo_files = sorted(
        f for f in os.listdir(base) if f.startswith("silo_") and f.endswith(".npz")
    )
    if not silo_files:
        return None

    def to_float(x):
        # uint8 silos stay raw (normalized on device); float silos are
        # assumed pre-normalized by the institution and pass through
        return x if x.dtype == np.uint8 else x.astype(np.float32)

    test_path = os.path.join(base, "test.npz")
    has_test = os.path.exists(test_path)
    xs, ys, groups, test_xs, test_ys = [], [], [], [], []
    offset = 0
    for fname in silo_files:
        with np.load(os.path.join(base, fname)) as d:
            x, y = to_float(d["x"]), d["y"].astype(np.int32)
        if not has_test and len(x) > 1:
            n_test = max(1, int(len(x) * test_fraction))
            test_xs.append(x[-n_test:])
            test_ys.append(y[-n_test:])
            x, y = x[:-n_test], y[:-n_test]
        xs.append(x)
        ys.append(y)
        groups.append(np.arange(offset, offset + len(x), dtype=np.int64))
        offset += len(x)
    if has_test:
        with np.load(test_path) as d:
            ex, ey = to_float(d["x"]), d["y"].astype(np.int32)
    else:
        ex, ey = np.concatenate(test_xs), np.concatenate(test_ys)
    return (
        np.concatenate(xs), np.concatenate(ys), ex, ey,
        {"natural_groups": groups},
    )


dataset_registry.register("mnist")(_image_loader("mnist", (28, 28, 1), 10, _try_mnist_real))
dataset_registry.register("cifar10")(_image_loader("cifar10", (32, 32, 3), 10, _try_cifar10_real))
dataset_registry.register("femnist")(
    _image_loader("femnist", (28, 28, 1), 62, _try_femnist_real)
)
# Federated ImageNet (cross-silo): geometry follows model.kwargs.image_size
# (default 64 keeps the sandbox light); real silo files override everything.
dataset_registry.register("imagenet_federated")(
    _image_loader(
        "imagenet_federated", (64, 64, 3), 1000, _try_imagenet_real,
        size_kwarg="image_size",
    )
)


@dataset_registry.register("shakespeare")
def _load_shakespeare(cfg: DataConfig, vocab_size: int = 90, seq_len: int = 80, **kwargs):
    data_dir = os.path.expanduser(cfg.data_dir)
    txt = os.path.join(data_dir, "shakespeare.txt")
    if os.path.exists(txt):
        from colearn_federated_learning_tpu.data.leaf import load_shakespeare_text
        tx, ty, ex, ey, meta = load_shakespeare_text(txt, vocab_size, seq_len)
        return tx, ty, ex, ey, meta, vocab_size, "lm"
    if not cfg.synthetic_fallback:
        raise FileNotFoundError(f"shakespeare: no data under {data_dir}")
    rng = np.random.default_rng(1207)
    successors = rng.integers(0, vocab_size, size=(vocab_size, 4))
    tx, ty = _synthetic_text(rng, _scaled_train_size(cfg), seq_len, vocab_size,
                             successors)
    ex, ey = _synthetic_text(rng, cfg.synthetic_test_size, seq_len, vocab_size,
                             successors)
    return tx, ty, ex, ey, {"source": "synthetic", "input_shape": (seq_len,)}, vocab_size, "lm"


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def build_federated_data(cfg: DataConfig, seed: int = 0, **model_kwargs) -> FederatedData:
    """Load a dataset and partition it into ``cfg.num_clients`` shards.

    With ``cfg.store.dir`` set the corpus comes from an on-disk client
    store instead (data/store.py): example bytes stay memory-mapped, the
    partition IS the store's per-client index (loader/partition config
    fields are ignored — they were baked in at ``colearn store build``
    time), and only the sampled cohort's records ever touch host RAM.
    """
    if cfg.store.dir:
        from colearn_federated_learning_tpu.data.store import open_store

        return open_store(
            cfg.store.dir, gather_workers=cfg.store.gather_workers
        ).as_federated_data(
            expected_clients=cfg.num_clients,
            materialize=cfg.store.materialize,
        )
    loader = dataset_registry.get(cfg.name)
    tx, ty, ex, ey, meta, num_classes, task = loader(cfg, **model_kwargs)
    labels_for_partition = ty if task == "classify" else ty[:, 0]
    part_info: dict = {}
    client_indices = partition_lib.partition(
        cfg.partition,
        labels=labels_for_partition,
        num_clients=cfg.num_clients,
        num_classes=num_classes if task == "classify" else int(labels_for_partition.max()) + 1,
        alpha=cfg.dirichlet_alpha,
        seed=seed,
        natural_groups=meta.get("natural_groups"),
        info=part_info,
    )
    meta = dict(meta, partition=cfg.partition, **part_info)
    if part_info.get("repair_used"):
        # the deterministic extreme-α repair changed the effective
        # label-skew distribution — say so where the user will see it
        import logging

        logging.getLogger(__name__).warning(
            "%s partition (dirichlet alpha=%s) needed deterministic repair: "
            "%d example(s) moved from the largest shards to starved ones; "
            "the realized label skew is milder than the drawn one",
            cfg.partition, part_info.get("repair_alpha"),
            part_info.get("repair_moved", 0),
        )
    return FederatedData(
        train_x=tx, train_y=ty, test_x=ex, test_y=ey,
        client_indices=client_indices, num_classes=num_classes, task=task, meta=meta,
    )
