"""Sequence/context parallelism: long-context support over a device mesh.

Long-context is first-class in this framework even though the reference's
own models are short-sequence (SURVEY.md §5 records that): the token axis
is sharded over a ``"seq"`` mesh axis, pointwise layers (embed, LayerNorm,
MLP) run locally on each shard, and exact attention is computed by
``ops.ring_attention`` — k/v blocks rotate around the ring via
``ppermute`` riding the ICI, an online-softmax accumulating exact results
with O(T/n) memory per device (Liu et al. 2023's recipe, independently
implemented).

Two entry points:

- :func:`make_ring_attention_fn` — an attention callable for model
  builders (``build_model("bert_tiny", attention="ring")``); only valid
  when the model is applied inside a ``shard_map`` whose mesh carries the
  sequence axis.
- :func:`make_seq_parallel_lm_forward` — the full seq-sharded transformer
  forward: wraps ``model.apply`` in ``shard_map`` with tokens sharded
  ``[B, T/n]`` per device and global position offsets threaded through,
  so callers just hand it ``(params, tokens)`` like the plain forward.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

from colearn_federated_learning_tpu.ops.ring_attention import ring_attention

SEQ_AXIS = "seq"


def build_seq_mesh(n_devices: int = 0, devices=None, axis: str = SEQ_AXIS) -> Mesh:
    import numpy as np

    devices = list(devices if devices is not None else jax.devices())
    if n_devices <= 0:
        n_devices = len(devices)
    return Mesh(np.array(devices[:n_devices]), (axis,))


def make_ring_attention_fn(axis: str = SEQ_AXIS, causal: bool = True):
    """Attention callable ``(q, k, v, heads) → out`` running the ring
    protocol over mesh axis ``axis``. Must execute inside a shard_map
    that carries that axis with the token dim sharded over it."""
    return partial(ring_attention, axis_name=axis, causal=causal)


def make_seq_parallel_lm_forward(model, mesh: Mesh, axis: str = SEQ_AXIS,
                                 train: bool = False):
    """Jitted seq-sharded forward: ``(params, tokens [B, T]) → logits``.

    ``model`` must (a) use ring attention on ``axis`` (build with
    ``attention="ring"``) and (b) accept a ``pos_offset`` argument so
    each shard indexes its *global* positions into the positional table.
    ``T`` must be divisible by the mesh's seq-axis size.
    """
    n = mesh.shape[axis]

    def shard_fwd(params, tokens):
        # tokens: [B, T/n] — this device's token block
        offset = jax.lax.axis_index(axis) * tokens.shape[1]
        return model.apply({"params": params}, tokens, train=train,
                           pos_offset=offset)

    sharded = jax.shard_map(
        shard_fwd,
        mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis, None),
    )

    @jax.jit
    def forward(params, tokens):
        if tokens.shape[1] % n:
            raise ValueError(
                f"seq len {tokens.shape[1]} not divisible by {n} seq lanes"
            )
        return sharded(params, tokens)

    return forward
