"""Multi-host distributed bring-up (SURVEY.md §3.5, §5 "distributed
communication backend").

The reference's NCCL process group becomes JAX's multi-controller
runtime: every host runs the same program, ``jax.distributed.initialize``
wires them into one cluster over gRPC, and after that ``jax.devices()``
is the *global* device list — the client mesh (parallel/mesh.py) spans
hosts transparently and the round engine's psums ride ICI within a slice
and DCN across slices. There is no server/rank asymmetry to port: the
"server" is the replicated psum result on every host.

Bring-up paths:

- **TPU pods**: ``jax.distributed.initialize()`` with no arguments —
  coordinator/process count/ids come from the TPU runtime metadata.
- **Explicit / loopback** (CI, CPU clusters): set
  ``COLEARN_COORDINATOR=host:port``, ``COLEARN_NUM_PROCESSES``,
  ``COLEARN_PROCESS_ID`` (or call :func:`initialize` yourself). The
  ``multihost``-marked loopback test drives a real 2-process × 4-device
  cluster this way on one machine.

Host-local input rule: the driver feeds per-round index tensors via
:func:`host_local_array` so each process materializes only its
addressable shards; replicated arrays (params, dataset bytes) use plain
``device_put`` which every process executes identically.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or form) the multi-controller cluster.

    No-args on a TPU pod; explicit coordinator/count/id elsewhere.
    Idempotent: repeated calls after a successful bring-up are no-ops.
    """
    # Must not touch the backend (jax.process_count() would initialize
    # it); inspect the distributed client state directly for idempotency.
    already = getattr(jax.distributed, "is_initialized", None)
    if already is not None:
        if already():
            return
    elif getattr(
        getattr(jax.distributed, "global_state", None), "client", None
    ) is not None:
        # older jax: no is_initialized(); probe the client directly.
        # jax builds exposing NEITHER accessor fall through to
        # initialize() (a repeated call then raises there — loud,
        # instead of an AttributeError here masking the real state)
        return
    kwargs = {}
    if coordinator is not None:
        kwargs = dict(
            coordinator_address=coordinator,
            num_processes=int(num_processes),
            process_id=int(process_id),
        )
    jax.distributed.initialize(**kwargs)


def maybe_initialize_from_env(env=None) -> bool:
    """CLI hook: bring up the cluster when COLEARN_COORDINATOR is set.

    Returns True iff distributed initialization ran. Must be called
    before anything touches the JAX backend.
    """
    env = os.environ if env is None else env
    coordinator = env.get("COLEARN_COORDINATOR")
    if not coordinator:
        return False
    missing = [k for k in ("COLEARN_NUM_PROCESSES", "COLEARN_PROCESS_ID")
               if k not in env]
    if missing:
        raise RuntimeError(
            f"COLEARN_COORDINATOR is set but {', '.join(missing)} "
            f"is missing; explicit bring-up needs all three variables"
        )
    initialize(
        coordinator,
        env["COLEARN_NUM_PROCESSES"],
        env["COLEARN_PROCESS_ID"],
    )
    return True


def host_local_array(tree, sharding):
    """Assemble global jax.Arrays from host-replicated NumPy data
    (works on a single array or a whole pytree).

    Every process holds the same data (index tensors are deterministic
    functions of (seed, round), so all hosts compute identical copies)
    and uploads exactly its addressable shards — no cross-host data
    movement.
    """

    def one(a):
        a = np.asarray(a)
        return jax.make_array_from_callback(a.shape, sharding, lambda idx: a[idx])

    return jax.tree.map(one, tree)
