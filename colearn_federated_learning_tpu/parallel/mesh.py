"""Device-mesh construction for client-parallel FL simulation.

One chip == one virtual-client lane (the north star, BASELINE.json:5).
With cohort_size K and L lanes, each lane trains K/L clients
sequentially per round under ``lax.scan``; the weighted aggregation is a
``psum`` over the ``"clients"`` mesh axis.

All code is size-agnostic (SURVEY.md §7 "hard parts"): the same mesh
builds over 1 real TPU chip, 8 fake CPU devices, or a v4-32 pod slice.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENT_AXIS = "clients"


def largest_lane_count(cohort_size: int, n_devices: int) -> int:
    """Largest divisor of cohort_size that is ≤ n_devices.

    The cohort must split evenly over lanes (static shapes); an 11-client
    cohort on 8 chips runs on 1 lane rather than silently padding.
    """
    for lanes in range(min(cohort_size, n_devices), 0, -1):
        if cohort_size % lanes == 0:
            return lanes
    return 1


def build_client_mesh(num_lanes: int = 0, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if num_lanes <= 0:
        num_lanes = len(devices)
    if num_lanes > len(devices):
        raise ValueError(f"num_lanes {num_lanes} > visible devices {len(devices)}")
    return Mesh(np.array(devices[:num_lanes]), (CLIENT_AXIS,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh) -> NamedSharding:
    """Shard leading (cohort) axis across lanes."""
    return NamedSharding(mesh, P(CLIENT_AXIS))
