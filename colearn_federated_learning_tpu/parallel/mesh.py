"""Device-mesh construction for client-parallel FL simulation.

One chip == one virtual-client lane (the north star, BASELINE.json:5).
With cohort_size K and L lanes, each lane owns K_local = K/L cohort
members (:func:`lane_client_count`); the weighted aggregation is a
``psum`` over the ``"clients"`` mesh axis either way. HOW a lane trains
its K_local clients is the cohort layout (``run.cohort_layout``,
parallel/round_engine.py): ``spatial`` scans/vmaps them in
``client_vmap_width`` blocks — every per-chip GEMM capped at one
client's batch — while ``megabatch`` collapses the lane's whole client
chunk into the GEMM batch (shared-weight first step at
``[K_local·batch]`` rows, lane-local vmap for the diverged steps) so
the MXU sees production-sized matmuls. The layout changes nothing
about the mesh or the sharding rules below: cohort tensors stay
``P(clients, ...)``, params/metrics stay replicated.

All code is size-agnostic (SURVEY.md §7 "hard parts"): the same mesh
builds over 1 real TPU chip, 8 fake CPU devices, or a v4-32 pod slice.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENT_AXIS = "clients"
BATCH_AXIS = "batch"


def largest_lane_count(cohort_size: int, n_devices: int) -> int:
    """Largest divisor of cohort_size that is ≤ n_devices.

    The cohort must split evenly over lanes (static shapes); an 11-client
    cohort on 8 chips runs on 1 lane rather than silently padding.
    """
    for lanes in range(min(cohort_size, n_devices), 0, -1):
        if cohort_size % lanes == 0:
            return lanes
    return 1


def build_client_mesh(num_lanes: int = 0, devices=None, batch_shards: int = 1) -> Mesh:
    """``batch_shards > 1`` adds the second mesh axis (SURVEY.md §2
    "parallelism strategies" axis 2): each virtual-client lane spans
    ``batch_shards`` chips that data-parallel one client's minibatch —
    for silo models whose per-client step outgrows a single chip."""
    devices = list(devices if devices is not None else jax.devices())
    if num_lanes <= 0:
        num_lanes = len(devices) // batch_shards
    need = num_lanes * batch_shards
    if need > len(devices):
        raise ValueError(
            f"{num_lanes} lanes × {batch_shards} batch shards > visible devices "
            f"{len(devices)}"
        )
    if batch_shards == 1:
        return Mesh(np.array(devices[:need]), (CLIENT_AXIS,))
    return Mesh(
        np.array(devices[:need]).reshape(num_lanes, batch_shards),
        (CLIENT_AXIS, BATCH_AXIS),
    )


def lane_client_count(cohort_size: int, mesh: Mesh) -> int:
    """K_local: how many cohort members one lane owns under this mesh —
    the megabatch layout's block size (and the spatial layout's maximum
    ``client_vmap_width``). The cohort must split evenly over lanes
    (static shapes; the engine enforces the same rule)."""
    lanes = int(mesh.shape[CLIENT_AXIS])
    if cohort_size % lanes:
        raise ValueError(
            f"cohort {cohort_size} not divisible by {lanes} lanes"
        )
    return cohort_size // lanes


def has_batch_axis(mesh: Mesh) -> bool:
    return BATCH_AXIS in mesh.shape


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh) -> NamedSharding:
    """Shard leading (cohort) axis across lanes; replicate over batch shards."""
    return NamedSharding(mesh, P(CLIENT_AXIS))


def cohort_sharded(mesh: Mesh) -> NamedSharding:
    """Sharding for the [K, steps, batch] index/mask tensors: cohort over
    lanes and, when present, the batch dim over batch shards."""
    if has_batch_axis(mesh):
        return NamedSharding(mesh, P(CLIENT_AXIS, None, BATCH_AXIS))
    return NamedSharding(mesh, P(CLIENT_AXIS))


def fused_cohort_sharded(mesh: Mesh) -> NamedSharding:
    """Sharding for the fused [F, K, steps, batch] index/mask slabs
    (run.fuse_rounds > 1): the leading fuse dim is replicated (every
    lane scans all F rounds), the cohort dim shards over lanes exactly
    like :func:`cohort_sharded`. Placing the stacked slabs through this
    sharding (instead of host-side jnp.stack of per-round arrays) is
    what makes the fused path multi-process capable: each host uploads
    only its addressable shards via ``host_local_array``."""
    if has_batch_axis(mesh):
        return NamedSharding(mesh, P(None, CLIENT_AXIS, None, BATCH_AXIS))
    return NamedSharding(mesh, P(None, CLIENT_AXIS))


def fused_client_sharded(mesh: Mesh) -> NamedSharding:
    """Sharding for fused [F, K] per-client vectors (n_ex, byzantine
    masks): fuse dim replicated, cohort dim over lanes."""
    return NamedSharding(mesh, P(None, CLIENT_AXIS))
