"""The TPU-native FL round engine (SURVEY.md §2 C8, §3.1; the north star).

One federated round == ONE compiled XLA program::

    jit(
      shard_map over Mesh(("clients",)):
        lane: lax.scan over its cohort chunk:
                 client local training (lax.scan over steps)
              → Σ nᵢ·Δᵢ, Σ nᵢ, Σ nᵢ·lossᵢ   (per-lane partial sums)
        psum over "clients"                  (the NCCL-allreduce analogue)
      → server optimizer applies Δ̄
    )

What the reference does with a process group + NCCL allreduce
(BASELINE.json:5) is here a single ``jax.lax.psum`` riding the ICI; the
params broadcast disappears entirely because the psum result is already
replicated. Host involvement per round: feeding the int32 index/mask
tensors and one ``device_get`` of scalar metrics.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from colearn_federated_learning_tpu.client.trainer import make_local_train_fn
from colearn_federated_learning_tpu.parallel.mesh import (
    BATCH_AXIS,
    CLIENT_AXIS,
    has_batch_axis,
)
from colearn_federated_learning_tpu.utils import trees


def _pcast_varying(tree):
    def cast(x):
        if CLIENT_AXIS in getattr(jax.typeof(x), "vma", frozenset()):
            return x  # already device-varying
        return jax.lax.pcast(x, (CLIENT_AXIS,), to="varying")

    return jax.tree.map(cast, tree)


class RoundMetrics(NamedTuple):
    train_loss: jnp.ndarray  # cohort example-weighted mean local loss
    examples: jnp.ndarray  # total real examples processed


def _decay_scale(decay: float, server_opt_state):
    """lr multiplier decay^round from the server state's round counter."""
    r = server_opt_state["round"].astype(jnp.float32)
    return jnp.power(jnp.float32(decay), r)


def make_sharded_round_fn(model, client_cfg, dp_cfg, task, mesh, server_update,
                          cohort_size: int, donate: bool = True,
                          client_vmap_width: int = 1, local_dtype=None,
                          agg: str = "examples"):
    """Build the jitted one-program round function.

    Signature of the returned fn::

        (params, server_opt_state, train_x, train_y,
         idx [K,steps,batch], mask [K,steps,batch], n_ex [K], rng)
        → (new_params, new_server_opt_state, RoundMetrics)

    ``n_ex`` are the per-client example counts; simulated client dropout
    (SURVEY.md §5) is upstream zeroing of entries — exact math, no
    control-flow divergence.

    ``agg`` selects the FedAvg weights: ``"examples"`` (wᵢ = nᵢ, the
    classic example-weighted mean, correct under UNIFORM cohort
    sampling) or ``"uniform"`` (wᵢ = 1 for participants — the unbiased
    pairing for size-proportional ``server.sampling="weighted"``, where
    example-weighting would count shard size twice). Dropped clients
    (nᵢ = 0) carry zero weight in both modes; the ``examples`` metric
    always reports Σnᵢ.

    ``client_vmap_width``: how many of a lane's clients train as one
    ``vmap`` block (effective conv/matmul batch = width × batch_size —
    what keeps the MXU fed when per-client batches are small). 1 = pure
    sequential ``lax.scan`` (minimum memory); 0 = the whole lane in one
    vmap; any other value must exactly divide the lane's client count
    (raises otherwise — never silently rewritten). Peak memory scales
    with width (one activation set per vmapped client), so big-model
    configs keep it low.
    """
    batch_sharded = has_batch_axis(mesh)
    if batch_sharded and client_cfg.batch_size % mesh.shape[BATCH_AXIS]:
        raise ValueError(
            f"batch_size {client_cfg.batch_size} not divisible by "
            f"{mesh.shape[BATCH_AXIS]} batch shards"
        )
    local_train = make_local_train_fn(
        model, client_cfg, dp_cfg, task,
        batch_axis=BATCH_AXIS if batch_sharded else None,
        local_dtype=local_dtype,
    )
    n_lanes = mesh.shape[CLIENT_AXIS]
    if cohort_size % n_lanes != 0:
        raise ValueError(f"cohort {cohort_size} not divisible by lanes {n_lanes}")
    clients_per_lane = cohort_size // n_lanes
    width = client_vmap_width if client_vmap_width > 0 else clients_per_lane
    if width > clients_per_lane or clients_per_lane % width != 0:
        raise ValueError(
            f"client_vmap_width {width} must divide the {clients_per_lane} "
            f"clients per lane (cohort {cohort_size} / {n_lanes} lanes); "
            f"use 0 for the full lane"
        )

    if agg not in ("examples", "uniform"):
        raise ValueError(f"unknown aggregation mode {agg!r}")
    use_decay = client_cfg.lr_decay != 1.0

    def lane_fn(params, train_x, train_y, idx, mask, n_ex, keys, *rest):
        # idx/mask: [C, steps, batch] — this lane's chunk of the cohort
        # Mark params as device-varying so scan carries (which mix in
        # per-lane data) type-check under shard_map's vma system.
        lr_scale = rest[0] if rest else None
        params = _pcast_varying(params)

        def per_block(acc, inp):
            b_idx, b_mask, b_n, b_keys = inp  # leading axis: width (vmapped)
            extra = () if lr_scale is None else (lr_scale,)
            w_b, m_b = jax.vmap(
                local_train,
                in_axes=(None, None, None, 0, 0, 0) + (None,) * len(extra),
            )(params, train_x, train_y, b_idx, b_mask, b_keys, *extra)
            # FedAvg weight per client: example count, or participation
            # (n>0) under "uniform" — dropout zeroing propagates either way
            b_w = b_n if agg == "examples" else (b_n > 0).astype(b_n.dtype)
            d_acc, w_acc, n_acc, l_acc = acc
            # Σ over the block of w_i·(Δ_i), fused as one contraction;
            # delta math in the ACCUMULATOR dtype (f32 server params):
            # bf16 local weights upcast here, so client-side mixed
            # precision never degrades the aggregation
            d_acc = jax.tree.map(
                lambda a, w, p: a + jnp.einsum(
                    "c,c...->...",
                    b_w.astype(a.dtype),
                    (w.astype(a.dtype) - p[None].astype(a.dtype)),
                ).astype(a.dtype),
                d_acc, w_b, params,
            )
            return (d_acc, w_acc + b_w.sum(), n_acc + b_n.sum(),
                    l_acc + (b_w * m_b.loss).sum()), None

        n_blocks = idx.shape[0] // width
        blocked = jax.tree.map(
            lambda a: a.reshape((n_blocks, width) + a.shape[1:]),
            (idx, mask, n_ex, keys),
        )
        acc0 = _pcast_varying(
            (trees.tree_zeros_like(params),
             jnp.zeros(()), jnp.zeros(()), jnp.zeros(()))
        )
        (d_sum, w_sum, n_sum, l_sum), _ = jax.lax.scan(per_block, acc0, blocked)
        # The aggregation collective — the reference's NCCL allreduce
        # (BASELINE.json:5) as a single XLA psum over the ICI.
        d_sum = jax.lax.psum(d_sum, CLIENT_AXIS)
        w_sum = jax.lax.psum(w_sum, CLIENT_AXIS)
        n_sum = jax.lax.psum(n_sum, CLIENT_AXIS)
        l_sum = jax.lax.psum(l_sum, CLIENT_AXIS)
        denom = jnp.maximum(w_sum, 1.0)
        mean_delta = trees.tree_scale(d_sum, 1.0 / denom)
        return mean_delta, n_sum, l_sum / denom

    # [K, steps, batch] index/mask tensors additionally shard the batch
    # dim over the batch axis when present; n_ex/keys stay per-client.
    cohort_spec = (
        P(CLIENT_AXIS, None, BATCH_AXIS) if batch_sharded else P(CLIENT_AXIS)
    )
    in_specs = (P(), P(), P(), cohort_spec, cohort_spec, P(CLIENT_AXIS), P(CLIENT_AXIS))
    if use_decay:
        in_specs += (P(),)  # lr_scale scalar, replicated
    sharded_lane = jax.shard_map(
        lane_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P(), P()),
    )

    @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
    def round_fn(params, server_opt_state, train_x, train_y, idx, mask, n_ex, rng):
        keys = jax.random.split(rng, idx.shape[0])
        extra = ()
        if use_decay:
            # round-indexed client LR decay, derived inside the program
            # from the server state's round counter (aggregation.py)
            extra = (_decay_scale(client_cfg.lr_decay, server_opt_state),)
        mean_delta, n_total, mean_loss = sharded_lane(
            params, train_x, train_y, idx, mask, n_ex, keys, *extra
        )
        new_params, new_opt_state = server_update(params, server_opt_state, mean_delta)
        return new_params, new_opt_state, RoundMetrics(mean_loss, n_total)

    return round_fn


def make_sequential_round_fn(model, client_cfg, dp_cfg, task, server_update,
                             local_dtype=None, agg: str = "examples"):
    """Reference-semantics engine: python loop over the cohort, jitted
    per-client local training, host-side weighted mean. Used for
    single-device debugging and as the parity oracle the shard_map
    engine is tested against (SURVEY.md §4.3)."""
    if agg not in ("examples", "uniform"):
        raise ValueError(f"unknown aggregation mode {agg!r}")
    local_train = jax.jit(make_local_train_fn(model, client_cfg, dp_cfg, task,
                                              local_dtype=local_dtype))
    update = jax.jit(server_update)

    use_decay = client_cfg.lr_decay != 1.0

    def round_fn(params, server_opt_state, train_x, train_y, idx, mask, n_ex, rng):
        k = idx.shape[0]
        keys = jax.random.split(rng, k)
        extra = (
            (_decay_scale(client_cfg.lr_decay, server_opt_state),)
            if use_decay else ()
        )
        deltas, weights, losses = [], [], []
        for c in range(k):
            w_i, m_i = local_train(params, train_x, train_y, idx[c], mask[c],
                                   keys[c], *extra)
            deltas.append(trees.tree_sub(w_i, params))
            n_c = jnp.asarray(n_ex[c])
            weights.append(n_c if agg == "examples" else (n_c > 0).astype(n_c.dtype))
            losses.append(m_i.loss)
        n_total = jnp.asarray(n_ex).sum()
        denom = jnp.maximum(jnp.sum(jnp.stack(weights)), 1.0)
        acc = trees.tree_zeros_like(params)
        for d, w in zip(deltas, weights):
            acc = trees.tree_axpy(w, d, acc)
        mean_delta = trees.tree_scale(acc, 1.0 / denom)
        mean_loss = sum(w * l for w, l in zip(weights, losses)) / denom
        new_params, new_opt_state = update(params, server_opt_state, mean_delta)
        return new_params, new_opt_state, RoundMetrics(mean_loss, n_total)

    return round_fn
