"""The TPU-native FL round engine (SURVEY.md §2 C8, §3.1; the north star).

One federated round == ONE compiled XLA program::

    jit(
      shard_map over Mesh(("clients",)):
        lane: lax.scan over its cohort chunk:
                 client local training (lax.scan over steps)
              → Σ nᵢ·Δᵢ, Σ nᵢ, Σ nᵢ·lossᵢ   (per-lane partial sums)
        psum over "clients"                  (the NCCL-allreduce analogue)
      → server optimizer applies Δ̄
    )

What the reference does with a process group + NCCL allreduce
(BASELINE.json:5) is here a single ``jax.lax.psum`` riding the ICI; the
params broadcast disappears entirely because the psum result is already
replicated. Host involvement per round: feeding the int32 index/mask
tensors and one ``device_get`` of scalar metrics.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from colearn_federated_learning_tpu.client.trainer import make_local_train_fn
from colearn_federated_learning_tpu.obs.executables import instrument
from colearn_federated_learning_tpu.parallel.mesh import (
    BATCH_AXIS,
    CLIENT_AXIS,
    has_batch_axis,
)
from colearn_federated_learning_tpu.utils import trees


def _pcast_varying(tree):
    def cast(x):
        if CLIENT_AXIS in getattr(jax.typeof(x), "vma", frozenset()):
            return x  # already device-varying
        return jax.lax.pcast(x, (CLIENT_AXIS,), to="varying")

    return jax.tree.map(cast, tree)


class RoundMetrics(NamedTuple):
    train_loss: jnp.ndarray  # cohort example-weighted mean local loss
    examples: jnp.ndarray  # total real examples processed


def apply_store_shard_ownership(fed, replica_fallback: bool = True):
    """Multi-host shard ownership for store-backed federations (the
    weak-scaling page-cache rule): mark on the mmap record arrays the
    store shards whose clients land on this process's lanes, so each
    host's gathers fault only its own shards' pages in steady state.

    The lane→client rule mirrors the engines' host-input contract:
    cohort rows shard over the mesh's client axis in contiguous lane
    blocks, and processes own contiguous client-id blocks
    ``[floor(p·C/P), floor((p+1)·C/P))`` — with the store's
    client-contiguous global ids, the owned shard set is then a pure
    function of the shard start offsets (``owned_shard_range``), no
    index scan. Off-block touches (a sampled cohort is never perfectly
    lane-aligned) fall back to READ REPLICAS — correct everywhere,
    counted in ``gather_stats()['replica_rows']``.

    No-op (returns None) on single-process runs and non-store
    federations."""
    if jax.process_count() <= 1:
        return None
    starts = getattr(fed.client_indices, "starts", None)
    if starts is None or not hasattr(fed.train_x, "set_shard_ownership"):
        return None
    p, n = jax.process_index(), jax.process_count()
    c = fed.num_clients
    lo, hi = (p * c) // n, ((p + 1) * c) // n
    ex_lo, ex_hi = int(starts[lo]), int(starts[hi])
    owned = fed.train_x.owned_shard_range(ex_lo, ex_hi)
    for arr in (fed.train_x, fed.train_y):
        arr.set_shard_ownership(owned, replica_fallback=replica_fallback)
    return {
        "process_index": int(p),
        "process_count": int(n),
        "clients": [int(lo), int(hi)],
        "owned_shards": [owned.start, owned.stop],
    }


def _mask_from_spec(spec, steps: int, batch_local: int, local_epochs: int,
                    batch_total: int, batch_offset):
    """Rebuild the ``[C, steps, batch]`` float32 validity mask from the
    ``[C, 2]`` int32 ``(examples_per_epoch, valid_steps)`` spec.

    Padding is contiguous per epoch (data/loader.py packs each epoch's
    real indices first), so a position is valid iff its flat offset
    within its epoch block sits below the client's per-epoch example
    count and its step below the valid-step bound (straggler
    truncation). Produces EXACTLY the 0.0/1.0 float32 values the host
    used to ship — the engines' bitwise contracts are unchanged; only
    the host→device bytes are (a [K, 2] spec instead of the
    [K, steps, batch] slab). Under a batch-sharded mesh each shard
    rebuilds its own columns: ``batch_offset`` is the shard's global
    column origin, so the flat offsets agree with the unsharded mask.
    """
    if steps % local_epochs:
        raise ValueError(
            f"steps={steps} not a multiple of local_epochs={local_epochs}"
        )
    spe = steps // local_epochs
    s = jax.lax.broadcasted_iota(jnp.int32, (steps, batch_local), 0)
    b = jax.lax.broadcasted_iota(jnp.int32, (steps, batch_local), 1)
    pos = (s % spe) * batch_total + b + batch_offset
    n_ep = spec[:, 0][:, None, None]
    vsteps = spec[:, 1][:, None, None]
    return ((pos[None] < n_ep) & (s[None] < vsteps)).astype(jnp.float32)


def _decay_scale(decay: float, server_opt_state):
    """lr multiplier decay^round from the server state's round counter."""
    r = server_opt_state["round"].astype(jnp.float32)
    return jnp.power(jnp.float32(decay), r)


def _clip_block(delta_b, clip: float):
    """Clip each client's whole-tree delta to L2 norm ≤ clip.

    ``delta_b`` leaves are ``[width, ...]``; the norm is per CLIENT over
    all leaves jointly (the DP-SGD clipping geometry), shared by both
    engines. Applied BEFORE compression — a real client clips as part of
    its update rule, then compresses the wire format."""
    sq = sum(
        (d.reshape(d.shape[0], -1) ** 2).sum(-1) for d in jax.tree.leaves(delta_b)
    )
    scale = jnp.minimum(1.0, clip / jnp.maximum(jnp.sqrt(sq), 1e-30))  # [width]
    return jax.tree.map(
        lambda d: d * scale.reshape((d.shape[0],) + (1,) * (d.ndim - 1)), delta_b
    )


def _scaffold_c_update(b_c, c_global, params, w_b, k_valid, lr_i, part):
    """SCAFFOLD option-II control-variate update over a client block.

    ``cᵢ⁺ = cᵢ + (w₀ − w_K)/(Kᵢ·lr) − c`` for participants, ``cᵢ`` for
    non-participants — the participation gate ``part`` folds into the
    per-client scales so the non-participant case is exact. All leaves
    ``[width, ...]``; ``k_valid``/``part`` are ``[width]`` vectors;
    SHARED by the sharded lane and the sequential oracle so the two
    engines stay definitionally identical. Math in f32 regardless of
    the local-training dtype."""
    inv = part / (jnp.maximum(k_valid, 1.0) * lr_i)

    def leaf(ci, cg, w0, wk):
        bshape = (ci.shape[0],) + (1,) * (ci.ndim - 1)
        return (
            ci
            + (w0[None].astype(jnp.float32) - wk.astype(jnp.float32))
            * inv.reshape(bshape)
            - part.reshape(bshape) * cg
        )

    return jax.tree.map(leaf, b_c, c_global, params, w_b)


def _fused_stack_inputs(stacked, n_ex, trust, aggregator: str, agg: str,
                        byzantine_f: int, cohort_size: int):
    """(stack, combined ``[K]`` weights) feeding the fused reduce-apply
    kernel (``server.fused_apply``, ops/pallas_apply.py) — ONE shared
    implementation for the sharded program and the sequential oracle,
    so the fused path's cross-engine parity holds by construction:

    - ``weighted_mean``: the FedAvg weight (examples or participation)
      × reputation trust, divided by the guarded weight sum — exactly
      ``stack_weighted_mean``'s arithmetic, pre-folded so the kernel's
      contraction is the finished mean.
    - ``krum``: trust scales the stack first (the same soft suppression
      as the unfused path), then the winner's one-hot row IS the
      reduction — selection as a degenerate weighted sum. ``m == 0``
      (full dropout) zeroes the row, preserving robust_reduce's
      zero-update semantics.
    """
    if aggregator == "krum":
        from colearn_federated_learning_tpu.server.aggregation import (
            krum_select,
            scale_deltas_by_trust,
        )

        if trust is not None:
            stacked = scale_deltas_by_trust(stacked, trust)
        winner, m = krum_select(stacked, n_ex > 0, byzantine_f)
        w = jax.nn.one_hot(winner, cohort_size, dtype=jnp.float32)
        return stacked, w * (m > 0)
    w = (
        n_ex.astype(jnp.float32) if agg == "examples"
        else (n_ex > 0).astype(jnp.float32)
    )
    if trust is not None:
        w = w * trust.astype(jnp.float32)
    w_sum = w.sum()
    denom = jnp.where(w_sum > 0, w_sum, 1.0)
    return stacked, w / denom


def _check_engine_compat(scaffold, aggregator, compression, clip_delta_norm,
                         secagg=False, feddyn=False, client_dp=0.0,
                         downlink="", secagg_quant_step=0.0,
                         error_feedback=False, attack="",
                         client_ledger=False, reputation=False,
                         fused_apply=False, cohort_layout="spatial",
                         example_dp=False, hierarchy=False):
    """Engine-level mirror of config.validate()'s pairing rejections,
    SHARED by both engine factories so a direct ``make_*_round_fn``
    caller can't build an unsound combination that the config layer
    would have refused (e.g. a scaffold+median engine whose c_global
    update silently stays a plain poisonable mean). FedDyn's
    prox_mu injection (and a belt-and-braces copy of its pairing
    guard) lives in ``_feddyn_prepare``.

    ``example_dp`` is ``dp_cfg.enabled`` as the factories see it — the
    ``colearn check`` capability extractor (analysis/capability.py)
    surfaced that the mirror accepted scaffold/feddyn/attack engines
    built directly with example-level DP while ``validate()`` rejects
    all three pairings; the flag closes that drift."""
    robust = aggregator != "weighted_mean"
    if feddyn and (robust or compression or clip_delta_norm > 0.0):
        # params would move by the modified deltas while gᵢ/h track the
        # raw trajectory. Historically guarded only in _feddyn_prepare;
        # lifted into the shared mirror so the capability extractor's
        # validate()↔mirror comparison sees one contract surface
        # (_feddyn_prepare keeps its own guard for direct callers).
        raise ValueError(
            "feddyn is incompatible with robust aggregators, "
            "compression, or delta clipping (the g/h recursion tracks "
            "raw deltas)"
        )
    if example_dp and (scaffold or feddyn):
        # mirror config.validate(): DP-SGD noise in the local steps
        # would leak into the persistent c/h state the control-variate
        # identities assume is a pure function of the deltas
        raise ValueError(
            "example-level DP is incompatible with stateful algorithms "
            "(DP noise would enter the persistent c/h state)"
        )
    if example_dp and attack:
        # mirror config.validate(): the example-level accountant
        # assumes every client runs the DP-SGD mechanism, which a
        # Byzantine client does not — the reported epsilon would lie
        raise ValueError(
            "attack simulation is incompatible with example-level DP "
            "(a Byzantine client does not run the DP-SGD mechanism)"
        )
    if scaffold and (robust or compression or clip_delta_norm > 0.0):
        # the c update (c += Σδc/N) has no robust equivalent and the
        # modified deltas would desynchronize params from the c
        # trajectory — same reasoning as config.validate()
        raise ValueError(
            "scaffold is incompatible with robust aggregators, "
            "compression, or delta clipping"
        )
    if compression == "topk" and robust:
        # sparse deltas make coordinate-wise order statistics run over
        # mostly-zero coordinates — statistically meaningless
        raise ValueError(
            "compression='topk' (sparse) breaks robust aggregation"
        )
    if secagg:
        if robust or scaffold or feddyn or compression:
            # masking needs the plain weighted-mean path (see
            # ServerConfig.secure_aggregation)
            raise ValueError(
                "secure aggregation requires the plain weighted-mean "
                "path (no robust aggregator, stateful algorithm, or "
                "compression)"
            )
        if clip_delta_norm <= 0.0:
            # without a clip bound the fixed-point values are unbounded
            # and quantized uploads can exceed int32 range, silently
            # corrupting the mod-2^32 aggregate
            raise ValueError(
                "secure aggregation requires clip_delta_norm > 0"
            )
        if secagg_quant_step > 0 and clip_delta_norm / secagg_quant_step >= 2**24:
            # f32 integer-exactness floor for the quantizer, checked
            # here so DIRECT engine callers get it too; this covers the
            # uniform-weight case exactly — under example weights the
            # driver's resolved-cap check (round_driver.
            # _check_secagg_bounds) is the authoritative, tighter bound
            import logging

            logging.getLogger(__name__).warning(
                "secagg clip/quant_step = %.3g >= 2^24: f32 rounding in "
                "the fixed-point quantizer can lose integer exactness "
                "for clients near the clip bound",
                clip_delta_norm / secagg_quant_step,
            )
    if client_dp > 0.0:
        # mirror config.validate(): the sensitivity analysis holds for
        # the clipped uniform mean with a fixed denominator only
        # (ServerConfig.dp_client_noise_multiplier)
        if robust or scaffold or feddyn or compression:
            raise ValueError(
                "client-level DP requires the plain weighted-mean path"
            )
        if clip_delta_norm <= 0.0:
            raise ValueError("client-level DP requires clip_delta_norm > 0")
    if downlink and (scaffold or feddyn):
        # state recursions track exact params (config.validate mirror)
        raise ValueError(
            "downlink compression supports fedavg/fedprox only"
        )
    if error_feedback:
        if not compression:
            # EF's whole job is to accumulate what the compressor
            # dropped; without a compressor the memory is identically 0
            raise ValueError("error_feedback requires compression")
        if scaffold or feddyn:
            # one per-client state store per run — the control-variate
            # algorithms already own it, and their validate() rules
            # reject compression anyway
            raise ValueError(
                "error_feedback is incompatible with stateful algorithms"
            )
        if robust:
            # EF uploads are history-dependent (this round's message
            # includes PAST rounds' residuals), so the cohort's messages
            # mix different effective timescales — coordinate-wise order
            # statistics over them have no robustness interpretation,
            # and a Byzantine client's memory is unbounded hidden state
            raise ValueError(
                "error_feedback is incompatible with robust aggregators"
            )
        if secagg or client_dp > 0.0:
            # both rely on a per-round norm bound on the upload
            # (clip_delta_norm); EF uploads C(delta + e) where the
            # memory e is NOT norm-bounded across rounds, so the
            # fixed-point range / DP sensitivity analyses don't hold
            raise ValueError(
                "error_feedback breaks the per-round upload norm bound "
                "secure aggregation / client-level DP require"
            )
    if attack:
        # mirror config.validate()'s attack pairing rejections so a
        # direct engine caller can't build an unsound adversary
        # simulation (see AttackConfig)
        from colearn_federated_learning_tpu.server.attacks import (
            UPLOAD_ATTACKS,
        )

        if attack not in UPLOAD_ATTACKS:
            raise ValueError(
                f"unknown upload attack {attack!r} "
                f"(label_flip is host-side and never reaches the engine)"
            )
        if secagg:
            raise ValueError(
                "attack simulation is incompatible with secure "
                "aggregation (masking hides the uploads the attack "
                "transform acts on)"
            )
        if client_dp > 0.0:
            raise ValueError(
                "attack simulation is incompatible with client-level DP "
                "(a Byzantine upload voids the sensitivity analysis)"
            )
        if scaffold or feddyn:
            raise ValueError(
                "attack simulation is incompatible with stateful "
                "algorithms (poisoned uploads enter the persistent c/h "
                "state through an undefendable plain mean)"
            )
        if error_feedback:
            raise ValueError(
                "attack simulation is incompatible with error_feedback "
                "(a Byzantine residual memory is unbounded hidden state)"
            )
    if client_ledger:
        # mirror config.validate()'s client_ledger pairing rejections
        # so a direct engine caller can't build a forensic ledger over
        # uploads the protocol hides (or a DP release it would void)
        if secagg:
            raise ValueError(
                "client_ledger is incompatible with secure aggregation "
                "(per-client upload statistics are what masking hides)"
            )
        if client_dp > 0.0:
            raise ValueError(
                "client_ledger is incompatible with client-level DP "
                "(a per-client statistics channel voids the release)"
            )
        if scaffold or feddyn:
            raise ValueError(
                "client_ledger is not supported with stateful "
                "algorithms (they own the per-client state path)"
            )
    if fused_apply and (scaffold or feddyn):
        # mirror config.validate(): the stateful algorithms interleave
        # their c/h recursions with the apply (feddyn bypasses the
        # server optimizer entirely) — there is no plain delta-apply
        # chain for the kernel to replace
        raise ValueError(
            "fused_apply is incompatible with stateful algorithms "
            "(they own the server step)"
        )
    if cohort_layout not in ("spatial", "megabatch"):
        raise ValueError(
            f"unknown cohort_layout {cohort_layout!r}; "
            f"allowed: spatial | megabatch"
        )
    if cohort_layout == "megabatch" and (scaffold or feddyn):
        # mirror config.validate(): the stateful per-client correction
        # trees (c − cᵢ / −gᵢ) ride the spatial per-block vmap; the
        # megabatch block trains from ONE shared weight replica at step
        # 0 and has no per-client correction slot
        raise ValueError(
            "cohort_layout='megabatch' is incompatible with stateful "
            "algorithms (their per-client correction trees ride the "
            "spatial per-block scan)"
        )
    if reputation and not client_ledger:
        # mirror config.validate(): the trust weights are a pure
        # function of the ledger rows — without the ledger there is no
        # evidence to weight by (and enabling it brings the ledger's
        # own pairing exclusions, which are exactly reputation's)
        raise ValueError(
            "reputation weighting requires client_ledger (trust is "
            "computed from the device-resident ledger rows)"
        )
    if hierarchy:
        # mirror config.validate()'s server.hierarchy pairing
        # rejections: the edge tier re-runs this engine per edge over a
        # sub-population, so any cross-round per-client state or
        # protocol that assumes ONE flat cohort per round is unsound
        # when the cohort is split across E independent invocations
        if scaffold or feddyn:
            raise ValueError(
                "hierarchy is incompatible with stateful algorithms "
                "(the per-client c/h state assumes one flat cohort; "
                "per-edge invocations would fork the recursion)"
            )
        if secagg:
            raise ValueError(
                "hierarchy is incompatible with secure aggregation "
                "(the masking protocol spans one flat cohort; per-edge "
                "sums would leave edge deltas in the clear anyway)"
            )
        if client_dp > 0.0 or example_dp:
            raise ValueError(
                "hierarchy is incompatible with DP (the accountant "
                "assumes one sampling process over the full population, "
                "not E independent edge cohorts)"
            )
        if client_ledger:
            raise ValueError(
                "hierarchy is incompatible with client_ledger (the "
                "device-resident ledger indexes one flat population; "
                "edge sub-cohorts would alias its rows)"
            )
        if error_feedback:
            raise ValueError(
                "hierarchy is incompatible with error_feedback (the "
                "residual memory is keyed by flat cohort slot)"
            )


# fold constant deriving the secure-aggregation mask key from the round
# rng — MUST be identical in both engines (mask parity is the parity)
_SECAGG_FOLD = 0x5ECA66
# fold constant for the central client-level DP noise key (DP-FedAvg);
# identical in both engines so parity tests cover the noisy path too
_CLIENT_DP_FOLD = 0xD9FEDA
# fold constant for the downlink broadcast-quantization dither
_DOWNLINK_FOLD = 0xD0147


def _client_dp_noise(dp_key, template, std):
    """Central DP-FedAvg noise tree (McMahan et al. 2018): one Gaussian
    per coordinate with traced std ``z·S/denom``, one threefry stream
    per leaf, cast to the leaf dtype. Added ONCE to the aggregated mean
    delta — never per client. Shared by both engines."""
    leaves, treedef = jax.tree.flatten(template)
    out = []
    for i, leaf in enumerate(leaves):
        n = jax.random.normal(
            jax.random.fold_in(dp_key, i), leaf.shape, jnp.float32
        )
        out.append(leaf + (n * std).astype(leaf.dtype))
    return jax.tree.unflatten(treedef, out)


def _secagg_quantize(delta_b, b_w, b_part, quant_step: float):
    """Weighted fixed-point quantization of a delta block, shared by
    both mask modes AND pinned behind optimization barriers: the
    weighting multiply and the round(c/step) must lower to the SAME
    instructions whether this runs eagerly (sequential oracle), inside
    a jitted helper, or fused into the sharded round program — an FMA/
    reassociation difference of one ulp at a .5 boundary flips a
    quantization unit and breaks the engines' bitwise-parity contract
    (observed: ring-eager vs pairwise-jit diverged by exactly 1 unit on
    2 of 60k coordinates before the barriers)."""
    part = b_part.astype(jnp.float32)
    contrib = jax.tree.map(
        lambda dd: dd * (part * b_w.astype(jnp.float32)).reshape(
            (dd.shape[0],) + (1,) * (dd.ndim - 1)
        ),
        delta_b,
    )
    contrib = jax.lax.optimization_barrier(contrib)
    # multiply by the PRECOMPUTED f32 reciprocal instead of dividing:
    # XLA canonicalizes division-by-constant to reciprocal multiplication
    # under jit but NOT in eager dispatch, and the two round differently
    # at .5 boundaries (observed: c/1e-4 = 2.5000002 vs c*1e4 = 2.5) —
    # doing the multiply ourselves makes every context emit the same op
    inv_step = jnp.float32(1.0 / quant_step)
    q = jax.tree.map(
        lambda c: jnp.round(c * inv_step).astype(jnp.int32), contrib
    )
    return jax.lax.optimization_barrier(q)


def _secagg_masks(mask_key, slot, template):
    """Uniform int32 mask tree for one client ``slot`` (SecAgg core,
    Bonawitz et al. 2017 §4 arithmetic): one threefry stream per
    (slot, leaf), bitcast so all 32 bits survive (astype would clamp).
    A client's wire mask is ``_secagg_masks(slot) − _secagg_masks(next)``
    over int32 wraparound; summed over the FULL cohort ring every
    stream appears once with + and once with −, so the aggregate
    cancellation is EXACT mod 2^32 — not float-approximate. Shared by
    both engines."""
    leaves, treedef = jax.tree.flatten(template)
    ks = jax.random.fold_in(mask_key, slot)
    out = []
    for i, leaf in enumerate(leaves):
        bits = jax.random.bits(
            jax.random.fold_in(ks, i), leaf.shape, jnp.uint32
        )
        out.append(jax.lax.bitcast_convert_type(bits, jnp.int32))
    return jax.tree.unflatten(treedef, out)


def _secagg_upload(delta_b, b_w, b_slot, b_part, mask_key, params,
                   quant_step: float, cohort_size: int):
    """One block's secure-aggregation contributions, as the sum of the
    protocol's two message kinds (Bonawitz et al. 2017 §5 round shape):

    - **client upload** (survivors, ``part = 1``): the WEIGHTED delta
      quantized to fixed-point int32 (exact for |q| < 2^24) plus the
      ring mask ``m(slot) − m(slot+1 mod K)``. Masks are committed to
      the STATIC full-cohort ring BEFORE training — no participant
      knowledge enters mask construction.
    - **server reconstruction** (dropped, ``part = 0``): the dropped
      client's upload never arrives; the server, learning the dropout
      set only AFTER collecting uploads, reconstructs that client's
      mask term ``m(slot) − m(slot+1)`` from the recovered seed (here:
      the shared mask key — the simulation stand-in for Shamir
      seed-share reconstruction) and adds it so the full ring still
      telescopes to zero. The dropped client's DATA (``q``) never
      enters the aggregate.

    Both terms ride the same int32 accumulator, so cancellation stays
    exact mod 2^32. Shared by both engines."""
    q = _secagg_quantize(delta_b, b_w, b_part, quant_step)
    b_next = (b_slot + 1) % cohort_size
    m_own = jax.vmap(lambda s: _secagg_masks(mask_key, s, params))(b_slot)
    m_nxt = jax.vmap(lambda s: _secagg_masks(mask_key, s, params))(b_next)
    parti = b_part.astype(jnp.int32)

    def merge(qq, a, b):
        pshape = (parti.shape[0],) + (1,) * (a.ndim - 1)
        p = parti.reshape(pshape)
        upload = p * (qq + a - b)  # what a survivor sends
        reconstruction = (1 - p) * (a - b)  # what the server rebuilds
        return upload + reconstruction

    return jax.tree.map(merge, q, m_own, m_nxt)


# base key for expanding a 32-bit pairwise seed into a params-shaped
# mask stream; distinct from every other stream family in the program
_SECAGG_PAIR_FOLD = 0x5ECA67


def _pairwise_prg(seed_u32, template):
    """Expand one pairwise seed into a params-shaped int32 mask tree:
    one threefry stream per (seed, leaf), bitcast so all 32 bits
    survive. BOTH endpoints of a pair (and the server's reconstruction)
    expand the identical stream from the identical seed — that identity
    is the whole cancellation argument."""
    leaves, treedef = jax.tree.flatten(template)
    ks = jax.random.fold_in(
        jax.random.PRNGKey(_SECAGG_PAIR_FOLD), seed_u32
    )
    out = []
    for i, leaf in enumerate(leaves):
        bits = jax.random.bits(
            jax.random.fold_in(ks, i), leaf.shape, jnp.uint32
        )
        out.append(jax.lax.bitcast_convert_type(bits, jnp.int32))
    return jax.tree.unflatten(treedef, out)


def _secagg_pairwise_upload(delta_b, b_w, b_slot, b_part, part_full,
                            seeds, params, quant_step: float,
                            cohort_size: int):
    """One block's pairwise-masked contributions (Bonawitz §4–5 shape;
    ``server.secagg_mode="pairwise"``). Per client i the protocol's two
    message kinds are:

    - **survivor upload**: q_i + Σ_{j>i} PRG(s_ij) − Σ_{j<i} PRG(s_ij)
      — every pair's stream appears once with + and once with −, so the
      full-cohort sum telescopes to zero exactly (mod 2^32).
    - **server reconstruction** (i dropped): the survivors' uploads
      contain the now-uncancelled terms sgn(i−s)·PRG(s_si); the server,
      holding i's Shamir-reconstructed seeds (privacy/secagg_keys.py —
      the driver performs that recovery for real and aborts below
      threshold), adds −Σ_{s surviving} sgn(i−s)·PRG(s_si).

    Both reduce to one signed coefficient per ordered pair —
    ``coeff_ij = sgn(j−i)·[part_i·1(j≠i) + (1−part_i)·part_j]``
    (for i surviving the mask sign; for i dropped, −sgn(i−j)·part_j =
    sgn(j−i)·part_j, the reconstruction sign) — so each pair stream is
    expanded ONCE per client row. Cost: K·(K−1) PRG expansions of
    |params| per round (the real protocol's client-side cost, all paid
    on one chip here) vs the ring mode's 2K; opt-in accordingly.
    """
    q = _secagg_quantize(delta_b, b_w, b_part, quant_step)
    parti_full = part_full.astype(jnp.int32)  # [K]

    def one_client(slot, p_i, q_i):
        row = seeds[slot]  # [K] this client's pairwise seeds
        j_ids = jnp.arange(cohort_size, dtype=jnp.int32)
        sgn = jnp.sign(j_ids - slot).astype(jnp.int32)
        coeff = sgn * (
            p_i * (j_ids != slot).astype(jnp.int32)
            + (1 - p_i) * parti_full
        )  # [K] ∈ {-1, 0, +1}

        def add_pair(acc, sj):
            s_ij, c_ij = sj
            m = _pairwise_prg(s_ij, params)
            return jax.tree.map(lambda a, mm: a + c_ij * mm, acc, m), None

        acc0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.int32), params)
        if any(
            CLIENT_AXIS in getattr(jax.typeof(x), "vma", frozenset())
            for x in jax.tree.leaves(q_i)
        ):
            # under shard_map the carry becomes device-varying after the
            # first += (coeff depends on this lane's slot); the initial
            # zeros must match (scan-vma typing). No-op for the eager
            # sequential oracle, which has no mesh context.
            acc0 = _pcast_varying(acc0)
        masked, _ = jax.lax.scan(add_pair, acc0, (row, coeff))
        # survivors ship q + mask; dropped ship only the reconstruction
        return jax.tree.map(lambda qq, mm: p_i * qq + mm, q_i, masked)

    parti = b_part.astype(jnp.int32)
    return jax.vmap(one_client)(b_slot, parti, q)


def _feddyn_prepare(client_cfg, scaffold, feddyn_alpha, aggregator,
                    compression, clip_delta_norm):
    """FedDyn constraint checks + prox_mu=α injection, SHARED by both
    engine factories so the guards and the injected objective can't
    drift between the engine and its parity oracle."""
    feddyn = feddyn_alpha > 0.0
    if not feddyn:
        return False, client_cfg
    if scaffold:
        raise ValueError("scaffold and feddyn are mutually exclusive")
    if client_cfg.prox_mu:
        raise ValueError("feddyn injects prox_mu=alpha; set prox_mu=0")
    if aggregator != "weighted_mean" or compression or clip_delta_norm > 0:
        # params would move by the modified deltas while gᵢ/h track the
        # raw trajectory — guard here too so direct engine callers can't
        # bypass config.validate()
        raise ValueError(
            "feddyn is incompatible with robust aggregators, "
            "compression, or delta clipping"
        )
    import dataclasses as _dc

    return True, _dc.replace(client_cfg, prox_mu=feddyn_alpha)


def _feddyn_g_update(b_c, params, w_b, part, alpha: float):
    """FedDyn ``gᵢ⁺ = gᵢ − α·(w_K − w₀)`` over a ``[width, ...]`` block,
    participants only; f32 math. Shared by both engines."""
    return jax.tree.map(
        lambda gi, w0, wk: gi
        - alpha * part.reshape((gi.shape[0],) + (1,) * (gi.ndim - 1))
        * (wk.astype(jnp.float32) - w0[None].astype(jnp.float32)),
        b_c, params, w_b,
    )


def _feddyn_server_step(params, mean_delta, h_new, alpha: float):
    """FedDyn server update ``w ← w₀ + Δ̄ − h⁺/α``; f32 math with the
    final cast back to the params dtype. Shared by both engines."""
    return jax.tree.map(
        lambda p, d, h: (
            p.astype(jnp.float32) + d.astype(jnp.float32) - h / alpha
        ).astype(p.dtype),
        params, mean_delta, h_new,
    )


def make_sharded_round_fn(model, client_cfg, dp_cfg, task, mesh, server_update,
                          cohort_size: int, donate: bool = True,
                          client_vmap_width: int = 1, local_dtype=None,
                          agg: str = "examples", scaffold: bool = False,
                          num_clients: int = 0,
                          aggregator: str = "weighted_mean",
                          trim_ratio: float = 0.1,
                          compression: str = "", topk_ratio: float = 0.01,
                          qsgd_levels: int = 256, topk_exact: bool = False,
                          clip_delta_norm: float = 0.0,
                          feddyn_alpha: float = 0.0,
                          byzantine_f: int = 0,
                          scan_unroll: int = 1,
                          secagg: bool = False,
                          secagg_quant_step: float = 1e-4,
                          secagg_mode: str = "ring",
                          client_dp_noise: float = 0.0,
                          dp_fixed_denom: float = 0.0,
                          downlink: str = "",
                          downlink_levels: int = 256,
                          error_feedback: bool = False,
                          fuse_rounds: int = 1,
                          attack: str = "",
                          attack_scale: float = 10.0,
                          attack_eps: float = 1.0,
                          on_device_mask: bool = False,
                          client_ledger: bool = False,
                          ledger_ema: float = 0.2,
                          ledger_zmax: float = 3.5,
                          reputation: bool = False,
                          rep_floor: float = 0.05,
                          rep_strength: float = 6.0,
                          rep_z_gain: float = 1.0,
                          fused_apply: bool = False,
                          cohort_layout: str = "spatial",
                          hierarchy: bool = False):
    """Build the jitted one-program round function.

    ``cohort_layout`` (``run.cohort_layout``): ``"spatial"`` is the
    classic placement — each lane trains its K/L clients in
    ``client_vmap_width`` blocks, so with width 1 every per-chip GEMM
    is capped at one client's batch. ``"megabatch"`` collapses the
    cohort axis into the GEMM batch: the lane's whole client chunk
    trains as ONE block (``client_vmap_width`` is owned by the layout),
    with the first local step run from the REPLICATED round weights so
    its forward/activation-gradient GEMMs contract the flattened
    ``[K_local·batch, ...]`` megabatch against one un-batched weight,
    and the remaining (diverged-weights) steps scanned as a lane-local
    vmap — one batched GEMM per layer instead of K_local sequential
    launches (client/trainer.py ``megabatch``). Purely a performance
    layout: every wire shape — the ``[K]`` weights/participation, the
    ``[K, 2]`` on-device mask spec, the ``[K, ·]`` upload stack, the
    psum/robust-reduce aggregation contract, ledger stats — is
    unchanged, and megabatch ≡ spatial is parity-pinned across
    aggregators × attacks × EF × fuse_rounds
    (tests/test_round_engine.py). Incompatible with stateful
    algorithms (``_check_engine_compat``) and batch-sharded meshes
    (the flattened rows are the axis the batch mesh splits).

    Signature of the returned fn::

        (params, server_opt_state, train_x, train_y,
         idx [K,steps,batch], mask [K,steps,batch], n_ex [K], rng)
        → (new_params, new_server_opt_state, RoundMetrics)

    ``on_device_mask``: the ``mask`` input is the compact ``[K, 2]``
    int32 ``(examples_per_epoch, valid_steps)`` spec instead of the
    full ``[K, steps, batch]`` float32 slab; each lane rebuilds its
    mask shard in-program via ``broadcasted_iota < n``
    (:func:`_mask_from_spec`) — bit-identical to the shipped mask, at
    ~half the round's host→device wire bytes. The grid's step count is
    read off ``idx``, so one engine serves every ``run.shape_buckets``
    rung (jit caches one executable per realized [K, steps, batch]
    shape — the ladder bounds the retrace budget).

    ``n_ex`` are the per-client example counts; simulated client dropout
    (SURVEY.md §5) is upstream zeroing of entries — exact math, no
    control-flow divergence.

    ``agg`` selects the FedAvg weights: ``"examples"`` (wᵢ = nᵢ, the
    classic example-weighted mean, correct under UNIFORM cohort
    sampling) or ``"uniform"`` (wᵢ = 1 for participants — the unbiased
    pairing for size-proportional ``server.sampling="weighted"``, where
    example-weighting would count shard size twice). Dropped clients
    (nᵢ = 0) carry zero weight in both modes; the ``examples`` metric
    always reports Σnᵢ.

    ``client_vmap_width``: how many of a lane's clients train as one
    ``vmap`` block (effective conv/matmul batch = width × batch_size —
    what keeps the MXU fed when per-client batches are small). 1 = pure
    sequential ``lax.scan`` (minimum memory); 0 = the whole lane in one
    vmap; any other value must exactly divide the lane's client count
    (raises otherwise — never silently rewritten). Peak memory scales
    with width (one activation set per vmapped client), so big-model
    configs keep it low.

    ``scaffold``: SCAFFOLD control variates (Karimireddy et al. 2020,
    option II). The round fn takes three extra trailing inputs —
    ``c_global`` (replicated params-shaped tree), ``c_clients`` (the
    FULL per-client state store: a ``[N_pad, ...]`` stacked tree,
    mesh-sharded over the ``clients`` axis on its leading dim — N_pad
    must be a lane-count multiple; pad rows are never addressed), and
    ``cohort`` (``[K]`` int32 of this round's client ids, replicated) —
    and returns ``(params, opt_state, new_c_global, new_c_clients,
    metrics)``. The cohort rows are gathered INSIDE the round program
    (each lane contributes the rows its state shard owns; one psum
    replicates the cohort's state) and scattered back after the update
    (all_gather of the cohort's new rows + a windowed in-shard write) —
    per-client state is device-resident across rounds with ZERO host
    involvement, and the collectives ride the ICI like the aggregation
    psum. Per-round state traffic: 2·K·|params| (one psum + one
    all_gather), vs the host round-trip of the same bytes over PCIe the
    host-resident design would cost. HBM budget: N_pad·|params| at
    ``state_dtype`` SHARDED over lanes (per-chip share: N_pad/L rows);
    ``state_dtype=bfloat16`` halves it at the cost of rounding the
    PERSISTENT control variates each round (the in-round c math stays
    f32 — upcast at gather, downcast at scatter; the c_global running
    sum tracks the unrounded f32 increments, so c == mean(cᵢ) holds to
    bf16 rounding only). Per step the client gradient gets
    ``+ (c − cᵢ)``; afterwards ``cᵢ⁺ = cᵢ − c + (w₀ − w_K)/(K·lr)``
    (the option-II identity: exactly the client's average applied local
    gradient), and ``c ← c + Σᵢ Δcᵢ / num_clients``. Requires plain
    client SGD (momentum breaks the identity — config.validate enforces
    it); non-participating clients (dropout / empty shards) keep cᵢ and
    contribute zero Δc. All in-round c math is f32 regardless of local
    dtype.

    ``aggregator``: ``"weighted_mean"`` (default — the single-psum
    FedAvg path) or a Byzantine-robust statistic (``"median"`` /
    ``"trimmed_mean"``, server/aggregation.py ``robust_reduce``). Robust
    modes emit the cohort's per-client deltas client-sharded from the
    lane and reduce them with plain jnp ops OUTSIDE the shard_map but
    inside the same jit — GSPMD inserts the cross-lane collectives for
    the coordinate-wise sort, so one XLA program per round still holds.
    Costs K× the aggregation memory/traffic of the psum path (inherent:
    order statistics need all K values).

    ``error_feedback`` activates EF compression memory (the EF-SGD /
    EF21 family, Seide et al. 2014; Stich et al. 2018; Richtárik et al.
    2021) on the SAME device-resident per-client store as scaffold:
    each client keeps a params-shaped residual ``eᵢ``; per round the
    participant uploads ``C(Δᵢ + eᵢ)`` and keeps ``eᵢ⁺ = Δᵢ + eᵢ −
    C(Δᵢ + eᵢ)`` (non-participants keep ``eᵢ``), which turns the BIASED
    top-k operator into an asymptotically-unbiased one — every dropped
    coordinate is retried until it ships. The round fn takes two extra
    trailing inputs (``e_clients`` — the ``[N_pad, ...]`` store,
    mesh-sharded over ``clients`` — and ``cohort``) and returns
    ``(params, opt_state, new_e_clients, metrics)``; gather/scatter
    run in-program exactly like scaffold's (zero host sync,
    multi-host capable). Requires ``compression``; incompatible with
    stateful algorithms (store conflict), robust aggregation, secagg,
    and client-level DP (see ``_check_engine_compat``).

    ``feddyn_alpha`` > 0 activates FedDyn (Acar et al. 2021) on the
    SAME stateful plumbing as scaffold (mutually exclusive): the
    per-client state gᵢ enters as the gradient correction ``−gᵢ``, the
    proximal pull ``α(w−w₀)`` is injected via prox_mu, afterwards
    ``gᵢ⁺ = gᵢ − α·(w_K − w₀)`` (participants only), and the server
    applies ``h ← h + ΣΔgᵢ/N;  w ← w₀ + Δ̄ − h/α`` (c_global carries h;
    the server optimizer is bypassed — FedDyn defines its own update —
    but the round counter still advances for LR decay).

    ``attack`` (server/attacks.py): Byzantine adversary simulation. The
    round fn gains an optional trailing ``byz`` input — a ``[K]`` 0/1
    mask of compromised cohort slots, an ARRAY input alongside ``n_ex``
    so the attacked-set can change per round with no retrace. On
    attacked rounds the lane emits the per-client delta stack (the
    robust aggregators' path — order statistics need it anyway, and
    ``alie`` needs cohort statistics), the attack transform applies to
    the stack after clipping/compression and before aggregation —
    exactly where a real attacker controls the upload — and the
    aggregate is the weighted mean over the (poisoned) stack or
    ``robust_reduce`` under a robust ``aggregator``. The transform and
    the stack aggregation are one shared implementation with the
    sequential oracle, so attacked-round parity holds by construction.

    ``client_ledger`` (obs/ledger.py): the round fn takes two extra
    trailing inputs — the ``[num_clients, LEDGER_WIDTH]`` float32
    ledger store (replicated) and the ``[K]`` int32 cohort ids — and
    returns the updated ledger just before the metrics. The per-client
    stats block (upload L2, cosine vs the aggregated delta, clip/EF
    residual, loss, robust-z flag) is computed in-program from the
    cohort's WIRE uploads (post clip/compression/attack) and scattered
    into the ledger with the EF store's OOB-drop discipline; the
    params trajectory is untouched — the weighted-mean path still
    aggregates through its psum, the upload stack only feeds the
    stats. Under ``fuse_rounds > 1`` the ledger rides the scan carry
    and the cohort ids a stacked ``[fuse, K]`` input.

    ``reputation`` (server/aggregation.py ``reputation_weights``;
    requires ``client_ledger``): each round converts the cohort's
    ledger rows — flag-rate, above-threshold z-EMA — into ``[K]``
    multiplicative trust weights IN-PROGRAM, from the ledger as carried
    into the round (this round's stats land after aggregation). On the
    psum path the trust rides a ``[K]`` lane input multiplied into the
    FedAvg weight (numerator and denominator — the loss metric becomes
    the same trust-weighted mean); on the stack paths it reweights
    ``stack_weighted_mean`` or scales the deltas fed to
    ``robust_reduce`` (soft suppression — order statistics stay
    unweighted). Unseen clients carry trust exactly 1, so fresh runs
    start as plain FedAvg. Composes with ``fuse_rounds`` (trust derives
    from the carried ledger per sub-round) and with the attack stack —
    that composition is the point: soft degradation where krum's hard
    rejection breaks near f ≈ K/2.

    ``fused_apply`` (``server.fused_apply``, ops/pallas_apply.py):
    requires a ``server_update`` built by ``make_server_update_fn``
    with the same flag (which already fuses the psum path's delta
    apply + optimizer into one pallas pass). Here it additionally
    routes the STACKED paths — attacked weighted_mean and krum — into
    ``server_update.fused_reduce``: trust/weight scaling, the weighted
    reduction (krum's winner as a one-hot row via
    ``_fused_stack_inputs``), the delta apply, and the optimizer run
    as one VMEM-resident kernel pass, with the delta emitted for the
    client ledger's cosine stat. median/trimmed_mean keep their
    per-coordinate sorts and take the apply-only fusion. Fused ≡
    unfused at f32-reassociation tolerance (tests/test_fused_apply.py).
    """
    _check_engine_compat(scaffold, aggregator, compression, clip_delta_norm,
                         secagg=secagg, feddyn=feddyn_alpha > 0.0,
                         client_dp=client_dp_noise, downlink=downlink,
                         secagg_quant_step=secagg_quant_step,
                         error_feedback=error_feedback, attack=attack,
                         client_ledger=client_ledger,
                         reputation=reputation, fused_apply=fused_apply,
                         cohort_layout=cohort_layout,
                         example_dp=bool(getattr(dp_cfg, "enabled", False)),
                         hierarchy=hierarchy)
    if fused_apply and not hasattr(server_update, "fused_reduce"):
        # the stacked-path kernel entry lives on the fused server
        # update (make_server_update_fn with cfg.fused_apply) — a
        # mismatched pairing would silently run the unfused tail
        raise ValueError(
            "fused_apply=True requires a server_update built by "
            "make_server_update_fn with fused_apply enabled"
        )
    if client_dp_noise > 0.0 and agg != "uniform":
        # the fixed-denominator sensitivity analysis needs w_i ∈ {0,1}
        raise ValueError(
            "client-level DP requires uniform aggregation weights "
            "(the driver selects them automatically)"
        )
    feddyn, client_cfg = _feddyn_prepare(
        client_cfg, scaffold, feddyn_alpha, aggregator, compression,
        clip_delta_norm,
    )
    batch_sharded = has_batch_axis(mesh)
    if batch_sharded and client_cfg.batch_size % mesh.shape[BATCH_AXIS]:
        raise ValueError(
            f"batch_size {client_cfg.batch_size} not divisible by "
            f"{mesh.shape[BATCH_AXIS]} batch shards"
        )
    megabatch = cohort_layout == "megabatch"
    if megabatch and batch_sharded:
        # mirror config.validate(): the flattened [K_local·batch] rows
        # ARE the axis the batch mesh shards
        raise ValueError(
            "cohort_layout='megabatch' is incompatible with a "
            "batch-sharded mesh (run.batch_shards > 1)"
        )
    local_train = make_local_train_fn(
        model, client_cfg, dp_cfg, task,
        batch_axis=BATCH_AXIS if batch_sharded else None,
        local_dtype=local_dtype, scan_unroll=scan_unroll,
        megabatch=megabatch,
    )
    n_lanes = mesh.shape[CLIENT_AXIS]
    if cohort_size % n_lanes != 0:
        raise ValueError(f"cohort {cohort_size} not divisible by lanes {n_lanes}")
    clients_per_lane = cohort_size // n_lanes
    if megabatch:
        # the layout owns the in-lane batching: the whole lane is one
        # block (config.validate rejects an explicit width >= 2)
        width = clients_per_lane
    else:
        width = client_vmap_width if client_vmap_width > 0 else clients_per_lane
        if width > clients_per_lane or clients_per_lane % width != 0:
            raise ValueError(
                f"client_vmap_width {width} must divide the {clients_per_lane} "
                f"clients per lane (cohort {cohort_size} / {n_lanes} lanes); "
                f"use 0 for the full lane"
            )

    if agg not in ("examples", "uniform"):
        raise ValueError(f"unknown aggregation mode {agg!r}")
    stateful = scaffold or feddyn
    # use_store: anything that rides the device-resident [N_pad, ...]
    # per-client store (stateful algorithms carry c_global + the dc psum
    # on top of it; error feedback only the store itself)
    use_store = stateful or error_feedback
    if fuse_rounds > 1 and (stateful or secagg):
        # scaffold/feddyn's c_global recursion is rejected by
        # config.validate (algorithm pairing); secagg's pairwise seed
        # matrices are per-round host PROTOCOL outputs (DH agreement +
        # Shamir recovery of the realized dropout set) that cannot be
        # precomputed into a stacked scan input. Robust aggregators,
        # upload attacks, and error feedback all fuse: the per-client
        # delta stack stays private to the scan body, byzantine masks
        # become [fuse, K] scan inputs, and the EF store rides the scan
        # carry (mirrors config.validate).
        raise ValueError(
            "fuse_rounds > 1 is incompatible with stateful algorithms "
            "and secure aggregation"
        )
    if use_store and num_clients <= 0:
        raise ValueError("per-client state requires num_clients")
    if aggregator not in ("weighted_mean", "median", "trimmed_mean", "krum"):
        raise ValueError(f"unknown aggregator {aggregator!r}")
    robust = aggregator != "weighted_mean"
    # attacked rounds need the per-client delta stack (the transform —
    # and alie's cohort statistics — act on individual uploads), so the
    # lane emits it exactly as the robust aggregators do
    emit_stack = robust or bool(attack)
    use_decay = client_cfg.lr_decay != 1.0
    from colearn_federated_learning_tpu.ops.compression import (
        downlink_quantize,
        make_compressor,
    )

    compress = make_compressor(compression, topk_ratio, qsgd_levels,
                               topk_exact=topk_exact)

    def _bcast(params, rng):
        """The weights clients actually receive this round."""
        if not downlink:
            return params
        return downlink_quantize(
            params, jax.random.fold_in(rng, _DOWNLINK_FOLD), downlink_levels
        )

    def _cohort_keys(rng, n):
        """Per-client round keys, pinned REPLICATED before they enter the
        shard_map. On the 2-axis clients×batch mesh, pre-0.6 jax's GSPMD
        partitioner can mis-partition the threefry computation feeding
        the manual region (observed on jax 0.4.37 CPU: every key word
        arrives summed over the batch axis — per-client DP noise then
        diverges between the 1D and 2D meshes); the explicit replicated
        constraint forces the partitioner to materialize the true
        values. No-op placement-wise on 1D meshes and vma-aware jax."""
        keys = jax.random.split(rng, n)
        if batch_sharded:
            from jax.sharding import NamedSharding

            keys = jax.lax.with_sharding_constraint(
                keys, NamedSharding(mesh, P())
            )
        return keys

    def lane_fn(params, train_x, train_y, idx, mask, n_ex, keys, *rest):
        # idx/mask: [C, steps, batch] — this lane's chunk of the cohort
        # Mark params as device-varying so scan carries (which mix in
        # per-lane data) type-check under shard_map's vma system.
        if on_device_mask:
            # mask arrived as the [C, 2] spec; rebuild this lane's (and,
            # under a batch axis, this shard's) mask columns in-program
            off = (
                jax.lax.axis_index(BATCH_AXIS) * idx.shape[2]
                if batch_sharded else 0
            )
            mask = _mask_from_spec(
                mask, idx.shape[1], idx.shape[2], client_cfg.local_epochs,
                client_cfg.batch_size, off,
            )
        rest = list(rest)
        lr_scale = rest.pop(0) if use_decay else None
        # reputation trust weights: [C] per-lane chunk, computed outside
        # the shard_map from the replicated ledger (same jit program)
        trust_l = rest.pop(0) if reputation else None
        c_global, c_cohort, c_all, state_pos = None, None, None, None
        if use_store:
            # Device-resident per-client state (VERDICT r3 missing-#1):
            # c_all is this lane's shard of the FULL [N_pad, ...] state
            # store (scaffold/feddyn control variates, or the EF
            # compression residuals). Gather the cohort's rows
            # in-program: each lane `take`s the rows its shard owns (OOB
            # positions fill 0), and ONE psum superposes the lanes —
            # every row is owned by exactly one lane, so the sum is
            # exact even in bf16. The lane then slices its own K/L chunk
            # of the replicated cohort state and upcasts to f32 for the
            # state math.
            if stateful:
                c_global = rest.pop(0)
            c_all, cohort_ids = rest.pop(0), rest.pop(0)
            lane = jax.lax.axis_index(CLIENT_AXIS)
            rows = jax.tree.leaves(c_all)[0].shape[0]  # N_pad / lanes
            state_pos = cohort_ids - lane * rows  # [K]; OOB = not owned
            # negative indices WRAP in take/scatter (numpy semantics) —
            # remap rows owned by earlier lanes to an explicit OOB value
            # so fill/drop treat them as not-owned
            state_pos = jnp.where(state_pos >= 0, state_pos, rows)
            gathered = jax.tree.map(
                lambda a: jnp.take(
                    a, state_pos, axis=0, mode="fill", fill_value=0
                ).astype(jnp.float32),
                c_all,
            )
            cohort_rep = jax.tree.map(
                lambda g: jax.lax.psum(g, CLIENT_AXIS), gathered
            )
            c_cohort = jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(
                    a, lane * clients_per_lane, clients_per_lane, 0
                ),
                cohort_rep,
            )
        if secagg:
            if secagg_mode == "pairwise":
                # [K, K] replicated pairwise-seed matrix (host-built by
                # privacy/secagg_keys.py: DH agreement; dropped rows are
                # the server's Shamir reconstruction). Masks still
                # commit to the static full cohort before training.
                pair_seeds = rest.pop(0)
                part_full = jax.lax.all_gather(
                    n_ex > 0, CLIENT_AXIS, tiled=True
                )
            else:
                mask_key = rest.pop(0)
            # the mask ring is STATIC over the full cohort (committed
            # before training / before dropouts are known): this lane's
            # global slots are its position in the cohort layout
            lane = jax.lax.axis_index(CLIENT_AXIS)
            slots_l = (
                lane * clients_per_lane
                + jnp.arange(clients_per_lane, dtype=jnp.int32)
            )
        dp_key = rest.pop(0) if client_dp_noise > 0.0 else None
        params = _pcast_varying(params)
        if stateful:
            c_global = _pcast_varying(c_global)

        def _train_block(p, b_idx, b_mask, b_keys, extra):
            """One client block through local training. The megabatch
            layout hands the whole block to the fused block trainer
            (shared-weight step 0 at [C·batch] rows + lane-local vmap);
            the spatial layout vmaps the per-client fn over the block —
            the same per-client step body either way."""
            if megabatch:
                return local_train(
                    p, train_x, train_y, b_idx, b_mask, b_keys, *extra
                )
            return jax.vmap(
                local_train,
                in_axes=(None, None, None, 0, 0, 0) + (None,) * len(extra),
            )(p, train_x, train_y, b_idx, b_mask, b_keys, *extra)

        def per_block(acc, inp):
            b_tr = None
            if reputation:
                # trust rides scan slot 4 (after keys); strip it here so
                # the per-path unpacking below stays untouched
                inp = list(inp)
                b_tr = inp.pop(4)
                inp = tuple(inp)
            b_c = None
            if error_feedback:
                # EF residual rows ride the store slot; training itself
                # is plain (the memory only touches the upload)
                b_idx, b_mask, b_n, b_keys, b_c = inp
                extra = () if lr_scale is None else (lr_scale,)
                w_b, m_b = _train_block(params, b_idx, b_mask, b_keys, extra)
            elif stateful:
                b_idx, b_mask, b_n, b_keys, b_c = inp
                if scaffold:
                    # SCAFFOLD correction (c − cᵢ), constant over the
                    # local phase; f32 leaf broadcast [..] − [width, ..]
                    corr = jax.tree.map(lambda cg, ci: cg - ci, c_global, b_c)
                else:
                    # FedDyn linear term: −gᵢ (the global h only enters
                    # server-side)
                    corr = jax.tree.map(jnp.negative, b_c)
                w_b, m_b = jax.vmap(
                    local_train, in_axes=(None, None, None, 0, 0, 0, None, 0),
                )(params, train_x, train_y, b_idx, b_mask, b_keys, lr_scale, corr)
            else:
                if secagg:  # leading axis: width
                    b_idx, b_mask, b_n, b_keys, b_slot = inp
                else:
                    b_idx, b_mask, b_n, b_keys = inp
                extra = () if lr_scale is None else (lr_scale,)
                w_b, m_b = _train_block(params, b_idx, b_mask, b_keys, extra)
            # FedAvg weight per client: example count, or participation
            # (n>0) under "uniform" — dropout zeroing propagates either way
            b_w = b_n if agg == "examples" else (b_n > 0).astype(b_n.dtype)
            if reputation:
                # reputation folds multiplicatively into the FedAvg
                # weight — numerator AND denominator (a true reweighted
                # mean), and the loss metric weights identically
                b_w = b_w * b_tr.astype(b_w.dtype)
            d_acc, w_acc, n_acc, l_acc, dc_acc = acc
            ys = {}
            # per-client deltas in f32 (bf16 local weights upcast here, so
            # client-side mixed precision never degrades the aggregation);
            # the uplink-compression operator applies per client BEFORE any
            # aggregation — exactly where a real client would compress
            delta_b = jax.tree.map(
                lambda w, p: w.astype(jnp.float32) - p[None].astype(jnp.float32),
                w_b, params,
            )
            # client_ledger: the residual stat compares what the client
            # computed against what it ships — raw delta on the plain
            # path, the EF accumulator (delta + memory) under EF
            pre_b = delta_b if client_ledger else None
            if clip_delta_norm > 0.0:
                delta_b = _clip_block(delta_b, clip_delta_norm)
            if error_feedback:
                # EF memory: the wire message is C(Δᵢ + eᵢ); the
                # residual of that SAME quantity becomes the new eᵢ.
                # Non-participants (dropout: Δᵢ = 0, weight 0) keep eᵢ
                # bit-identical — their C(eᵢ) never ships (zero weight
                # in the aggregation contraction below).
                part_b = (b_n > 0).astype(jnp.float32)

                def _bshape(p, d):
                    return p.reshape((d.shape[0],) + (1,) * (d.ndim - 1))

                acc_b = jax.tree.map(
                    lambda d, e: d + e.astype(jnp.float32), delta_b, b_c
                )
                if client_ledger:
                    pre_b = acc_b  # ledger resid = ||e_i^+|| under EF
                comp_b = compress(acc_b, b_keys)
                ys["c"] = jax.tree.map(
                    lambda a, cp, e: jnp.where(
                        _bshape(part_b, a) > 0, a - cp, e.astype(jnp.float32)
                    ),
                    acc_b, comp_b, b_c,
                )
                delta_b = comp_b
            elif compress is not None:
                delta_b = compress(delta_b, b_keys)
            if emit_stack or client_ledger:
                # robust/attacked modes need every client's delta
                # individually — emit the block's deltas instead of
                # accumulating; the ledger emits them ALONGSIDE the
                # psum accumulation (stats only — aggregation unchanged)
                ys["delta"] = delta_b
            if client_ledger:
                from colearn_federated_learning_tpu.obs.ledger import (
                    upload_residual,
                )

                ys["pc_loss"] = m_b.loss
                ys["pc_resid"] = upload_residual(pre_b, delta_b)
            if emit_stack:
                pass  # the stack IS the aggregation input downstream
            elif secagg:
                # survivor uploads + server mask reconstruction for
                # dropped clients (n = 0); the int32 accumulator's
                # wraparound is the protocol's mod-2^32 arithmetic
                if secagg_mode == "pairwise":
                    upload_b = _secagg_pairwise_upload(
                        delta_b, b_w, b_slot, b_n > 0, part_full,
                        pair_seeds, params, secagg_quant_step, cohort_size,
                    )
                else:
                    upload_b = _secagg_upload(
                        delta_b, b_w, b_slot, b_n > 0, mask_key, params,
                        secagg_quant_step, cohort_size,
                    )
                d_acc = jax.tree.map(
                    lambda a, u: a + u.sum(0), d_acc, upload_b
                )
            else:
                # Σ over the block of w_i·(Δ_i), fused as one contraction
                d_acc = jax.tree.map(
                    lambda a, dd: a + jnp.einsum(
                        "c,c...->...", b_w.astype(jnp.float32), dd
                    ).astype(a.dtype),
                    d_acc, delta_b,
                )
            if stateful:
                # Kᵢ = # non-padded steps, counted on the GLOBAL mask so
                # batch shards agree on validity (same rule as the
                # trainer's _global_count — a step whose valid examples
                # all sit on another batch shard is still a real step)
                step_counts = b_mask.sum(-1)  # [width, steps] (this shard)
                if batch_sharded:
                    step_counts = jax.lax.psum(step_counts, BATCH_AXIS)
                k_valid = (step_counts > 0).sum(-1).astype(jnp.float32)
                part = ((b_n > 0) & (k_valid > 0)).astype(jnp.float32)
                if scaffold:
                    lr_i = jnp.float32(client_cfg.lr)
                    if lr_scale is not None:
                        lr_i = lr_i * lr_scale.astype(jnp.float32)
                    new_c_block = _scaffold_c_update(
                        b_c, c_global, params, w_b, k_valid, lr_i, part
                    )
                else:
                    new_c_block = _feddyn_g_update(
                        b_c, params, w_b, part, feddyn_alpha
                    )
                dc_acc = jax.tree.map(
                    lambda a, nc, ci: a + (nc - ci).sum(0), dc_acc, new_c_block, b_c
                )
                ys["c"] = new_c_block
            return (d_acc, w_acc + b_w.sum(), n_acc + b_n.sum(),
                    l_acc + (b_w * m_b.loss).sum(), dc_acc), ys

        n_blocks = idx.shape[0] // width
        scan_in = (idx, mask, n_ex, keys)
        if reputation:
            scan_in += (trust_l,)
        scan_in += (c_cohort,) if use_store else ()
        if secagg:
            scan_in += (slots_l,)
        blocked = jax.tree.map(
            lambda a: a.reshape((n_blocks, width) + a.shape[1:]), scan_in
        )
        # dc accumulates f32 c-variate deltas regardless of params dtype
        # (the "all c math is f32" invariant — and the scan carry must
        # match the f32 per-block increment)
        dc0 = (
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            if stateful else jnp.zeros(())
        )
        # robust/attacked modes emit per-client deltas as scan ys instead
        # of the weighted-sum accumulator — collapse that carry slot to a
        # scalar; secagg accumulates the masked fixed-point uploads in int32
        if emit_stack:
            d0 = jnp.zeros(())
        elif secagg:
            d0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.int32), params)
        else:
            d0 = trees.tree_zeros_like(params)
        acc0 = _pcast_varying(
            (d0, jnp.zeros(()), jnp.zeros(()), jnp.zeros(()), dc0),
        )
        (d_sum, w_sum, n_sum, l_sum, dc_sum), ys = jax.lax.scan(
            per_block, acc0, blocked
        )
        # The aggregation collective — the reference's NCCL allreduce
        # (BASELINE.json:5) as a single XLA psum over the ICI.
        w_sum = jax.lax.psum(w_sum, CLIENT_AXIS)
        n_sum = jax.lax.psum(n_sum, CLIENT_AXIS)
        l_sum = jax.lax.psum(l_sum, CLIENT_AXIS)
        # weights here are integer example counts or 0/1 participation
        # flags, so w_sum ∈ (0,1) is impossible — the where-form is
        # exactly the max-with-1 floor, written to match the async engine
        denom = jnp.where(w_sum > 0, w_sum, 1.0)
        unblock = lambda t: jax.tree.map(  # noqa: E731  [n_blocks,width,...]→[C,...]
            lambda a: a.reshape((idx.shape[0],) + a.shape[2:]), t
        )
        out = {"n": n_sum, "loss": l_sum / denom}
        # Under client-level DP the mean's denominator is the FIXED
        # public cohort size, never the realized weight sum — a
        # data-dependent denominator is itself private and would break
        # the sensitivity analysis (dropout then attenuates the
        # estimator instead of leaking through the divisor).
        # under poisson sampling the engine's static row count is the
        # PADDED cap; the DP estimator's fixed public denominator stays
        # the nominal qN = configured cohort_size (dp_fixed_denom)
        agg_denom = (
            jnp.float32(dp_fixed_denom or cohort_size)
            if client_dp_noise > 0.0 else denom
        )
        if emit_stack or client_ledger:
            out["deltas"] = unblock(ys["delta"])  # client-sharded stack
        if client_ledger:
            # per-client loss / residual-magnitude columns of the
            # ledger stats block ([K], client-sharded like the stack)
            out["pc_loss"] = unblock(ys["pc_loss"])
            out["pc_resid"] = unblock(ys["pc_resid"])
        if not emit_stack:
            d_sum = jax.lax.psum(d_sum, CLIENT_AXIS)
            if secagg:
                # the cross-lane psum completed the mod-2^32 ring — masks
                # are gone EXACTLY; dequantize back to the params dtype
                out["mean_delta"] = jax.tree.map(
                    lambda d, p: (
                        d.astype(jnp.float32) * secagg_quant_step / agg_denom
                    ).astype(p.dtype),
                    d_sum, params,
                )
            else:
                out["mean_delta"] = trees.tree_scale(d_sum, 1.0 / agg_denom)
            if dp_key is not None:
                # central DP-FedAvg noise: std = z·S/K with per-client
                # sensitivity S = clip (uniform weights enforced) and
                # fixed K; every lane derives the identical streams, so
                # the replicated aggregate stays replicated
                std = (
                    jnp.float32(client_dp_noise * clip_delta_norm)
                    / agg_denom
                )
                out["mean_delta"] = _client_dp_noise(
                    dp_key, out["mean_delta"], std
                )
        if stateful:
            out["dc_sum"] = jax.lax.psum(dc_sum, CLIENT_AXIS)
        if use_store:
            # scatter the cohort's updated rows back into the sharded
            # state store, in-program: all lanes see the full [K, ...]
            # new state (all_gather in cohort order), then each lane
            # writes the rows its shard owns (OOB positions drop).
            # state_pos is unique per owned row (cohorts sample without
            # replacement), so the windowed write is well-defined.
            new_c_full = jax.tree.map(
                lambda t: jax.lax.all_gather(
                    t, CLIENT_AXIS, axis=0, tiled=True
                ),
                unblock(ys["c"]),
            )
            out["c_all"] = jax.tree.map(
                lambda a, nn: a.at[state_pos].set(
                    nn.astype(a.dtype), mode="drop"
                ),
                c_all, new_c_full,
            )
        return out

    # [K, steps, batch] index/mask tensors additionally shard the batch
    # dim over the batch axis when present; n_ex/keys stay per-client.
    # The compact mask SPEC has no batch dim — cohort over lanes only.
    cohort_spec = (
        P(CLIENT_AXIS, None, BATCH_AXIS) if batch_sharded else P(CLIENT_AXIS)
    )
    mask_in_spec = P(CLIENT_AXIS) if on_device_mask else cohort_spec
    in_specs = (P(), P(), P(), cohort_spec, mask_in_spec, P(CLIENT_AXIS), P(CLIENT_AXIS))
    if use_decay:
        in_specs += (P(),)  # lr_scale scalar, replicated
    if reputation:
        in_specs += (P(CLIENT_AXIS),)  # [K] trust weights, per-client
    if stateful:
        # c_global (replicated), c_clients (state store, sharded on its
        # leading N_pad dim), cohort ids (replicated)
        in_specs += (P(), P(CLIENT_AXIS), P())
    elif error_feedback:
        # e_clients store (sharded) + cohort ids; no global state
        in_specs += (P(CLIENT_AXIS), P())
    if secagg:
        in_specs += (P(),)  # replicated mask key; the ring is static
    if client_dp_noise > 0.0:
        in_specs += (P(),)  # central DP noise key, replicated
    out_specs = {"n": P(), "loss": P()}
    if emit_stack or client_ledger:
        out_specs["deltas"] = P(CLIENT_AXIS)
    if client_ledger:
        out_specs["pc_loss"] = P(CLIENT_AXIS)
        out_specs["pc_resid"] = P(CLIENT_AXIS)
    if not emit_stack:
        out_specs["mean_delta"] = P()
    if stateful:
        out_specs["dc_sum"] = P()
    if use_store:
        out_specs["c_all"] = P(CLIENT_AXIS)
    sharded_lane = jax.shard_map(
        lane_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
    )

    def _wire_stack(out, n_ex, byz, keys):
        """The cohort's [K, ...] WIRE uploads: the lane-emitted stack
        with the attack transform applied (plain jnp under the same
        jit — GSPMD handles the client-sharded axis), after clipping/
        compression and before aggregation: the upload boundary. Feeds
        the robust/attacked aggregation AND the client-ledger stats."""
        deltas = out["deltas"]
        if attack:
            from colearn_federated_learning_tpu.server.attacks import (
                apply_upload_attack,
            )

            # scope name matches the obs/roofline.py cost-model phase
            # (`attack_transform`) so device profiles join the analytic
            # FLOP/byte model by name
            with jax.named_scope("round_attack_transform"):
                deltas = apply_upload_attack(
                    deltas, byz, keys, attack, attack_scale, attack_eps,
                    participation=n_ex > 0,
                )
        return deltas

    def _mean_delta(out, n_ex, params=None, wire=None, trust=None):
        if emit_stack:
            if robust:
                from colearn_federated_learning_tpu.server.aggregation import (
                    robust_reduce,
                    scale_deltas_by_trust,
                )

                if trust is not None:
                    # reputation under a robust aggregator: scale each
                    # upload by its trust (soft suppression) — order
                    # statistics themselves stay unweighted by design
                    wire = scale_deltas_by_trust(wire, trust)
                # the coordinate-wise sort runs as plain jnp under jit —
                # GSPMD handles the lanes
                return robust_reduce(wire, n_ex > 0, aggregator,
                                     trim_ratio, byzantine_f)
            from colearn_federated_learning_tpu.server.attacks import (
                stack_weighted_mean,
            )

            # weighted_mean over the (attacked) stack — the stacked-path
            # twin of the in-lane psum accumulation, shared with the
            # sequential oracle; trust reweights it multiplicatively
            return stack_weighted_mean(wire, n_ex, agg, params, trust)
        return out["mean_delta"]

    def _trust_weights(ledger, cohort):
        """[K] reputation trust from the ledger AS CARRIED INTO the
        round (the round's own stats scatter lands after aggregation).
        Plain jnp under the round jit — host-free, fuses into the scan
        body under fuse_rounds."""
        from colearn_federated_learning_tpu.server.aggregation import (
            reputation_weights,
        )

        return reputation_weights(
            ledger, cohort.astype(jnp.int32), rep_floor, rep_strength,
            rep_z_gain, ledger_zmax,
        )

    def _ledger_update(out, wire, mean_delta, n_ex, ledger, cohort):
        """In-program ledger step: the shared stats block over the wire
        uploads, scattered into the device-resident store (obs/ledger).
        Runs under the round jit — zero extra host round-trips."""
        from colearn_federated_learning_tpu.obs.ledger import (
            client_round_stats,
            update_ledger,
        )

        with jax.named_scope("round_client_ledger"):
            stats = client_round_stats(
                wire, mean_delta, out["pc_loss"], out["pc_resid"], n_ex,
                ledger_zmax,
            )
            return update_ledger(
                ledger, cohort.astype(jnp.int32), n_ex, stats, ledger_ema
            )

    if stateful:

        @partial(jax.jit, donate_argnums=(0, 1, 8, 9) if donate else ())
        def round_fn(params, server_opt_state, train_x, train_y, idx, mask,
                     n_ex, rng, c_global, c_clients, cohort):
            n_lanes_ = mesh.shape[CLIENT_AXIS]
            for leaf in jax.tree.leaves(c_clients):
                if leaf.shape[0] % n_lanes_:
                    raise ValueError(
                        f"c_clients leading dim {leaf.shape[0]} must be a "
                        f"multiple of {n_lanes_} lanes (pad the state "
                        f"store; pad rows are never addressed)"
                    )
                break
            keys = _cohort_keys(rng, idx.shape[0])
            extra = ()
            if use_decay:
                extra = (_decay_scale(client_cfg.lr_decay, server_opt_state),)
            with jax.named_scope("round_local_train"):
                out = sharded_lane(
                    params, train_x, train_y, idx, mask, n_ex, keys,
                    *extra, c_global, c_clients, cohort.astype(jnp.int32),
                )
            # both algorithms accumulate their global state the same way:
            # scaffold  c ← c + ΣΔcᵢ/N   (paper's |S|/N · mean over S)
            # feddyn    h ← h + ΣΔgᵢ/N   (= h − α·(1/N)Σ(wᵢ−w₀))
            with jax.named_scope("round_aggregate"):
                new_c_global = jax.tree.map(
                    lambda c, dc: c + dc / float(num_clients), c_global, out["dc_sum"]
                )
                mean_delta = _mean_delta(out, n_ex)
            with jax.named_scope("round_server_apply"):
                if feddyn:
                    # FedDyn server step; the configured server optimizer
                    # is bypassed (the paper defines the update), only
                    # the round counter advances
                    new_params = _feddyn_server_step(
                        params, mean_delta, new_c_global, feddyn_alpha
                    )
                    new_opt_state = dict(
                        server_opt_state, round=server_opt_state["round"] + 1
                    )
                else:
                    new_params, new_opt_state = server_update(
                        params, server_opt_state, mean_delta
                    )
            return (new_params, new_opt_state, new_c_global, out["c_all"],
                    RoundMetrics(out["loss"], out["n"]))

        return instrument("round.stateful", round_fn)

    if error_feedback:

        def _ef_check(e_clients):
            n_lanes_ = mesh.shape[CLIENT_AXIS]
            for leaf in jax.tree.leaves(e_clients):
                if leaf.shape[0] % n_lanes_:
                    raise ValueError(
                        f"e_clients leading dim {leaf.shape[0]} must be a "
                        f"multiple of {n_lanes_} lanes (pad the state "
                        f"store; pad rows are never addressed)"
                    )
                break

        def _ef_one_round(params, server_opt_state, train_x, train_y, idx,
                          mask, n_ex, rng, e_clients, cohort, ledger=None):
            if client_ledger and ledger is None:
                raise TypeError("client_ledger requires the ledger input")
            keys = _cohort_keys(rng, idx.shape[0])
            extra = ()
            if use_decay:
                extra = (_decay_scale(client_cfg.lr_decay, server_opt_state),)
            if reputation:
                # EF aggregates through the psum path — trust enters as
                # the [K] lane input multiplied into the FedAvg weight
                extra = extra + (_trust_weights(ledger, cohort),)
            with jax.named_scope("round_local_train"):
                out = sharded_lane(
                    _bcast(params, rng), train_x, train_y, idx, mask, n_ex,
                    keys, *extra, e_clients, cohort.astype(jnp.int32),
                )
            new_ledger = None
            if client_ledger:
                # EF aggregates through the psum path; the stats block
                # reads the emitted C(delta+e) upload stack
                new_ledger = _ledger_update(
                    out, out["deltas"], out["mean_delta"], n_ex, ledger,
                    cohort,
                )
            with jax.named_scope("round_server_apply"):
                new_params, new_opt_state = server_update(
                    params, server_opt_state, out["mean_delta"]
                )
            metrics = RoundMetrics(out["loss"], out["n"])
            if client_ledger:
                return (new_params, new_opt_state, out["c_all"],
                        new_ledger, metrics)
            return new_params, new_opt_state, out["c_all"], metrics

        if fuse_rounds > 1:
            # fused EF: the device-resident [N_pad, ...] residual store
            # is a DONATED scan carry — the in-program scatter updates
            # it each fused sub-round with zero host involvement, and
            # the store buffer is reused across the whole chunk. The
            # client ledger (when on) rides the same carry.
            _ef_donate = (0, 1, 8) + ((10,) if client_ledger else ())

            @partial(jax.jit, donate_argnums=_ef_donate if donate else ())
            def round_fn(params, server_opt_state, train_x, train_y, idx_f,
                         mask_f, n_ex_f, rngs, e_clients, cohorts,
                         ledger=None):
                _ef_check(e_clients)
                if client_ledger and ledger is None:
                    raise TypeError("client_ledger requires the ledger input")

                def body(carry, inp):
                    p, o, e, led = carry
                    i, m, n, r, coh = inp
                    res = _ef_one_round(
                        p, o, train_x, train_y, i, m, n, r, e, coh, led
                    )
                    if client_ledger:
                        p, o, e, led, met = res
                    else:
                        p, o, e, met = res
                    return (p, o, e, led), met

                (p, o, e, led), ms = jax.lax.scan(
                    body, (params, server_opt_state, e_clients, ledger),
                    (idx_f, mask_f, n_ex_f, rngs, cohorts),
                )
                if client_ledger:
                    return p, o, e, led, ms
                return p, o, e, ms  # RoundMetrics with [F]-stacked fields

            return instrument("round.ef_fused", round_fn,
                              rounds_per_call=fuse_rounds)

        _ef_donate1 = (0, 1, 8) + ((10,) if client_ledger else ())

        @partial(jax.jit, donate_argnums=_ef_donate1 if donate else ())
        def round_fn(params, server_opt_state, train_x, train_y, idx, mask,
                     n_ex, rng, e_clients, cohort, ledger=None):
            _ef_check(e_clients)
            return _ef_one_round(params, server_opt_state, train_x, train_y,
                                 idx, mask, n_ex, rng, e_clients, cohort,
                                 ledger)

        return instrument("round.ef", round_fn)

    if secagg:

        @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
        def round_fn(params, server_opt_state, train_x, train_y, idx, mask,
                     n_ex, rng, pair_seeds=None):
            keys = _cohort_keys(rng, idx.shape[0])
            if secagg_mode == "pairwise":
                # pairwise mode: the seed matrix is a host-built INPUT
                # (key agreement + Shamir recovery are host protocol
                # steps), not derivable from the round rng
                if pair_seeds is None:
                    raise TypeError("secagg_mode='pairwise' requires pair_seeds")
                secagg_in = pair_seeds
            else:
                # ring mode: the mask key is a pure function of the
                # round rng — every lane (and the sequential oracle)
                # derives the same streams
                secagg_in = jax.random.fold_in(rng, _SECAGG_FOLD)
            extra = ()
            if use_decay:
                extra = (_decay_scale(client_cfg.lr_decay, server_opt_state),)
            tail = (
                (jax.random.fold_in(rng, _CLIENT_DP_FOLD),)
                if client_dp_noise > 0.0 else ()
            )
            with jax.named_scope("round_local_train"):
                out = sharded_lane(
                    _bcast(params, rng), train_x, train_y, idx, mask, n_ex,
                    keys, *extra, secagg_in, *tail,
                )
            with jax.named_scope("round_server_apply"):
                new_params, new_opt_state = server_update(
                    params, server_opt_state, out["mean_delta"]
                )
            return new_params, new_opt_state, RoundMetrics(out["loss"], out["n"])

        return instrument("round.secagg", round_fn)

    def _one_round(params, server_opt_state, train_x, train_y, idx, mask,
                   n_ex, rng, byz=None, ledger=None, cohort=None):
        if attack and byz is None:
            raise TypeError(f"attack={attack!r} requires the byz mask input")
        if client_ledger and (ledger is None or cohort is None):
            raise TypeError(
                "client_ledger requires the ledger and cohort inputs"
            )
        keys = _cohort_keys(rng, idx.shape[0])
        extra = ()
        if use_decay:
            # round-indexed client LR decay, derived inside the program
            # from the server state's round counter (aggregation.py)
            extra = (_decay_scale(client_cfg.lr_decay, server_opt_state),)
        trust = None
        if reputation:
            trust = _trust_weights(ledger, cohort)
            extra = extra + (trust,)
        tail = (
            (jax.random.fold_in(rng, _CLIENT_DP_FOLD),)
            if client_dp_noise > 0.0 else ()
        )
        # named scopes carry the round's in-program phases into device
        # profiles (jax.profiler / bench traces) — the only attribution
        # possible for phases fused inside ONE XLA program
        with jax.named_scope("round_local_train"):
            out = sharded_lane(
                _bcast(params, rng), train_x, train_y, idx, mask, n_ex, keys,
                *extra, *tail,
            )
        wire = None
        if emit_stack or client_ledger:
            wire = _wire_stack(out, n_ex, byz, keys)
        if fused_apply and emit_stack and aggregator in (
            "weighted_mean", "krum",
        ):
            # the fused server chain (server.fused_apply): trust/weight
            # scaling → weighted reduction → delta apply → optimizer as
            # ONE pallas pass over the flat param vector. The stack is
            # pinned replicated first: the kernel is an opaque custom
            # call GSPMD cannot partition, and the robust/attacked
            # paths materialize the full stack for their cross-lane
            # statistics anyway.
            with jax.named_scope("round_fused_reduce_apply"):
                stack_in, w_in = _fused_stack_inputs(
                    wire, n_ex, trust, aggregator, agg, byzantine_f,
                    cohort_size,
                )
                from jax.sharding import NamedSharding

                rep = NamedSharding(mesh, P())
                stack_in = jax.tree.map(
                    lambda a: jax.lax.with_sharding_constraint(a, rep),
                    stack_in,
                )
                new_params, new_opt_state, delta = server_update.fused_reduce(
                    params, server_opt_state, stack_in, w_in
                )
        else:
            with jax.named_scope("round_aggregate"):
                delta = _mean_delta(out, n_ex, params, wire, trust)
            with jax.named_scope("round_server_apply"):
                new_params, new_opt_state = server_update(
                    params, server_opt_state, delta
                )
        new_ledger = None
        if client_ledger:
            new_ledger = _ledger_update(out, wire, delta, n_ex, ledger,
                                        cohort)
        metrics = RoundMetrics(out["loss"], out["n"])
        if client_ledger:
            return new_params, new_opt_state, new_ledger, metrics
        return new_params, new_opt_state, metrics

    if fuse_rounds > 1:
        # Multi-round fusion (r5, VERDICT r4 weak-#2; generalized r6):
        # F rounds as ONE XLA program — a lax.scan over the per-round
        # body with stacked [F, ...] index tensors and the SAME
        # per-round rngs the unfused loop derives, so fused ≡ unfused
        # bitwise (test-pinned) while the per-round dispatch cost (the
        # dominant cost of the tiny-model configs on a relayed chip) is
        # paid once per F. Robust aggregators and upload attacks fuse
        # too: _one_round's per-client delta stack (and the attack
        # transform / coordinate-wise sort over it) stays PRIVATE to
        # the scan body — only the [F]-stacked scalar metrics leave the
        # program — and the per-round byzantine masks ride a stacked
        # [F, K] scan input alongside n_ex_f.

        _fuse_donate = (0, 1) + ((9,) if client_ledger else ())

        @partial(jax.jit, donate_argnums=_fuse_donate if donate else ())
        def round_fn(params, server_opt_state, train_x, train_y, idx_f,
                     mask_f, n_ex_f, rngs, byz_f=None, ledger=None,
                     cohorts_f=None):
            if attack and byz_f is None:
                raise TypeError(
                    f"attack={attack!r} requires the stacked [fuse, K] "
                    f"byz mask input"
                )
            if client_ledger and (ledger is None or cohorts_f is None):
                raise TypeError(
                    "client_ledger requires the ledger input and the "
                    "stacked [fuse, K] cohort ids"
                )

            def body(carry, inp):
                p, o, led = carry
                rest = list(inp)
                i, m, n, r = rest[:4]
                rest = rest[4:]
                bz = rest.pop(0) if attack else None
                coh = rest.pop(0) if client_ledger else None
                res = _one_round(p, o, train_x, train_y, i, m, n, r,
                                 bz, led, coh)
                if client_ledger:
                    p, o, led, met = res
                else:
                    p, o, met = res
                return (p, o, led), met

            xs = (idx_f, mask_f, n_ex_f, rngs)
            if attack:
                xs += (byz_f,)
            if client_ledger:
                # the ledger rides the scan CARRY (donated — the store
                # buffer is reused across the chunk, like the EF store);
                # per-sub-round cohort ids ride a stacked scan input
                xs += (cohorts_f,)
            (p, o, led), ms = jax.lax.scan(
                body, (params, server_opt_state, ledger), xs
            )
            if client_ledger:
                return p, o, led, ms
            return p, o, ms  # RoundMetrics with [F]-stacked fields

        return instrument("round.fused", round_fn,
                          rounds_per_call=fuse_rounds)

    # keep the compiled program's name "jit_round_fn": profiling tools
    # (bench._parse_device_ms) identify the round program by it
    _one_round.__name__ = "round_fn"
    # the ledger input (arg 9, passed positionally by the driver) is
    # donated like the state stores — the round updates it in place
    _donate = (0, 1) + ((9,) if client_ledger else ())
    round_fn = partial(jax.jit, donate_argnums=_donate if donate else ())(
        _one_round
    )
    return instrument("round.sync", round_fn)


def make_device_round_fn(round_fn, schedule_fn, fuse, *, client_ledger=False,
                         data_sharding=None, cohort_sharding=None,
                         client_sharding=None, fused_cohort_sharding=None,
                         fused_client_sharding=None, donate=True):
    """Wrap a (donate-free) sharded engine with the device-resident
    control plane (``run.control_plane="device"``, server/device_plane):
    the [K] cohort ids, [K, steps, batch] index slab, [K, 2] spec,
    weights, and churn realization all derive IN-PROGRAM from
    ``schedule_fn(arrays, round_idx)`` — the host ships only the static
    plan tables (once) and a round index per dispatch.

    ``round_fn`` must be built with ``donate=False``: donation moves to
    this outer jit (params/opt, plus the ledger when present), since the
    inner engine's buffers are now program-internal values.

    Under ``fuse > 1`` the schedule derivation is vmapped over the
    chunk's round vector and feeds the engine's fused lax.scan directly
    — each sub-round's cohort and gates materialize inside the scan
    body's program, so host I/O collapses to flush boundaries.

    Returns ``(params, opt[, ledger], metrics, sched)`` where ``sched``
    is the realized schedule WITHOUT the index slab (cohort / spec /
    n_ex / churn-stat scalars; [F]-stacked under fuse) — fetched at
    flush so telemetry, digests, and parity pins see exactly what the
    program executed."""
    _sched_out = ("cohort", "spec", "n_ex",
                  "unavailable", "dropped", "crashed")

    def _constrain(x, sharding):
        if sharding is None:
            return x
        return jax.lax.with_sharding_constraint(x, sharding)

    def _rng_rows(rng_key, rounds):
        # the same per-round keys the host loop derives: fold_in per
        # round, normalized to raw uint32 rows iff the key is typed
        # (a restored checkpoint's rng_key comes back typed) — the
        # fused scan body consumes raw key data, identical bits
        rngs = jax.vmap(lambda r: jax.random.fold_in(rng_key, r))(rounds)
        if jax.dtypes.issubdtype(rngs.dtype, jax.dtypes.prng_key):
            rngs = jax.random.key_data(rngs)
        return rngs

    _dev_donate = (0, 1) + ((7,) if client_ledger else ())

    if fuse > 1:

        @partial(jax.jit, donate_argnums=_dev_donate if donate else ())
        def device_round_fn(params, server_opt_state, train_x, train_y,
                            arrays, round0, rng_key, ledger=None):
            rounds = round0.astype(jnp.int32) + jnp.arange(
                fuse, dtype=jnp.int32
            )
            with jax.named_scope("round_control_plane"):
                sched = jax.vmap(lambda r: schedule_fn(arrays, r))(rounds)
            idx_f = _constrain(sched["idx"], fused_cohort_sharding)
            spec_f = _constrain(sched["spec"], fused_client_sharding)
            n_ex_f = _constrain(sched["n_ex"], fused_client_sharding)
            rngs = _rng_rows(rng_key, rounds)
            tail = ()
            if client_ledger:
                tail = (ledger, _constrain(sched["cohort"], data_sharding))
            out = round_fn(params, server_opt_state, train_x, train_y,
                           idx_f, spec_f, n_ex_f, rngs, None, *tail)
            return out + ({k: sched[k] for k in _sched_out},)

        return instrument("round.device_fused", device_round_fn,
                          rounds_per_call=fuse)

    @partial(jax.jit, donate_argnums=_dev_donate if donate else ())
    def device_round_fn(params, server_opt_state, train_x, train_y,
                        arrays, round_idx, rng_key, ledger=None):
        with jax.named_scope("round_control_plane"):
            sched = schedule_fn(arrays, round_idx.astype(jnp.int32))
        idx = _constrain(sched["idx"], cohort_sharding)
        spec = _constrain(sched["spec"], client_sharding)
        n_ex = _constrain(sched["n_ex"], client_sharding)
        rng = jax.random.fold_in(rng_key, round_idx)
        tail = ()
        if client_ledger:
            # in-program ledger slot assignment: the dense store's slot
            # ids ARE the cohort ids (validate rejects the paged hot
            # set under device mode), so the _ledger_slot_ids host
            # remap vanishes from the hot path
            tail = (ledger, _constrain(sched["cohort"], data_sharding))
        out = round_fn(params, server_opt_state, train_x, train_y,
                       idx, spec, n_ex, rng, None, *tail)
        return out + ({k: sched[k] for k in _sched_out},)

    return instrument("round.device", device_round_fn)


def make_async_round_fn(model, client_cfg, dp_cfg, task, mesh, server_update,
                        buffer_size: int, window: int, donate: bool = True,
                        client_vmap_width: int = 1, local_dtype=None,
                        clip_delta_norm: float = 0.0, scan_unroll: int = 1,
                        client_ledger: bool = False,
                        ledger_ema: float = 0.2,
                        ledger_zmax: float = 3.5,
                        reputation: bool = False,
                        rep_floor: float = 0.05,
                        rep_strength: float = 6.0,
                        rep_z_gain: float = 1.0):
    """Asynchronous buffered FL (FedBuff, Nguyen et al. 2022) — one
    server step as one XLA program.

    Clients train against STALE parameter versions: ``history`` is a
    ``[window, ...]`` ring of past global params (replicated), each of
    the ``buffer_size`` completing clients gathers its own start version
    by slot index, trains, and contributes ``delta vs ITS start params``
    weighted by the host-computed staleness decay. The server applies
    the weighted mean to the CURRENT params and writes the new version
    into the ring.

    Signature of the returned fn::

        (history, server_opt_state, train_x, train_y,
         idx [K,steps,batch], mask [K,steps,batch], agg_w [K], n_ex [K],
         slots [K] int32, cur_slot int32, next_slot int32, rng)
        → (new_history, new_params, new_opt_state, RoundMetrics)

    ``agg_w`` are the full aggregation weights (base weight × (1+s)^-α,
    dropped clients 0) — staleness lives host-side in the scheduler
    (server/round_driver.py), the program just consumes weights.
    The batch axis / scaffold / robust / compression features of the
    sync engine are deliberately not composed here (config.validate
    rejects them with algorithm=fedbuff).

    ``client_ledger`` (the churn PR — fedbuff promoted onto the
    million-client plane): per-INSERT forensic stats. The lane emits
    the popped buffer's per-client delta stack instead of accumulating
    it in-scan (the sync engine's ``emit_stack`` memory shape — the
    ring, not the stack, is fedbuff's marginal HBM cost), the round fn
    gains trailing ``cohort`` [K] int32 + ``ledger`` inputs, computes
    the SAME shared stats block (obs/ledger.py ``client_round_stats``
    over the wire uploads vs the staleness-weighted aggregate) and
    scatters it by true client id, returning the updated ledger before
    the metrics::

        (..., slots, cohort, ledger, cur_slot, next_slot, rng)
        → (new_history, new_params, new_opt_state, new_ledger, metrics)

    ``reputation`` (requires ``client_ledger``) gates the
    staleness-aware reputation-weighted merge: the [K] trust weights
    derive in-program from the ledger AS CARRIED IN (this step's stats
    land after aggregation) and fold multiplicatively into the
    host-computed staleness decay — the admitted weight is
    ``base·(1+s)^-α·trust``, numerator and denominator. With both
    flags off the program is bit-identical to the pre-churn engine.

    One async-specific wrinkle the sync ledger never sees: the popped
    buffer CAN contain the same client twice (independent in-flight
    arrivals), and ``update_ledger``'s ``.at[].set`` scatter collapses
    duplicate in-range rows to one insert (last-writer-wins). The
    ledger's participation count therefore undercounts absorbed
    updates by at most the within-step duplicate rate — bounded, and
    irrelevant to aggregation (both duplicates' deltas still merge).
    """
    local_train = make_local_train_fn(
        model, client_cfg, dp_cfg, task, local_dtype=local_dtype,
        scan_unroll=scan_unroll,
    )
    n_lanes = mesh.shape[CLIENT_AXIS]
    if buffer_size % n_lanes != 0:
        raise ValueError(
            f"buffer {buffer_size} not divisible by lanes {n_lanes}"
        )
    clients_per_lane = buffer_size // n_lanes
    width = client_vmap_width if client_vmap_width > 0 else clients_per_lane
    if width > clients_per_lane or clients_per_lane % width != 0:
        raise ValueError(
            f"client_vmap_width {width} must divide the {clients_per_lane} "
            f"clients per lane"
        )
    use_decay = client_cfg.lr_decay != 1.0
    if reputation and not client_ledger:
        # mirror config.validate(): trust weights are a pure function
        # of the ledger rows — without the ledger there is no evidence
        raise ValueError(
            "reputation weighting requires client_ledger (trust is "
            "computed from the device-resident ledger rows)"
        )

    if client_ledger:
        # Per-insert stats path: the lane emits the buffer's [K, ...]
        # per-client delta stack (client-sharded) instead of the
        # in-scan weighted accumulation — same memory shape as the
        # sync engine's emit_stack modes; aggregation, stats, and the
        # ledger scatter run as plain jnp under the round jit (GSPMD
        # handles the client-sharded axis), mirroring the sync path.
        def lane_stack_fn(history, train_x, train_y, idx, mask, slots,
                          keys, *rest):
            lr_scale = rest[0] if use_decay else None
            history = _pcast_varying(history)

            def train_one(slot, b_idx, b_mask, key):
                start = jax.tree.map(
                    lambda h: jnp.take(h, slot, axis=0), history
                )
                extra = () if lr_scale is None else (lr_scale,)
                w, m = local_train(start, train_x, train_y, b_idx, b_mask,
                                   key, *extra)
                delta = jax.tree.map(
                    lambda wi, p: (wi.astype(jnp.float32)
                                   - p.astype(jnp.float32)),
                    w, start,
                )
                return delta, m

            def per_block(_, inp):
                b_idx, b_mask, b_slot, b_keys = inp
                delta_b, m_b = jax.vmap(
                    train_one, in_axes=(0, 0, 0, 0),
                )(b_slot, b_idx, b_mask, b_keys)
                pre_b = delta_b  # ledger resid: raw Δ vs shipped Δ
                if clip_delta_norm > 0.0:
                    delta_b = _clip_block(delta_b, clip_delta_norm)
                from colearn_federated_learning_tpu.obs.ledger import (
                    upload_residual,
                )

                ys = {
                    "delta": delta_b,
                    "pc_loss": m_b.loss,
                    "pc_resid": upload_residual(pre_b, delta_b),
                }
                return None, ys

            n_blocks = idx.shape[0] // width
            blocked = jax.tree.map(
                lambda a: a.reshape((n_blocks, width) + a.shape[1:]),
                (idx, mask, slots, keys),
            )
            _, ys = jax.lax.scan(per_block, None, blocked)
            unblock = lambda t: jax.tree.map(  # noqa: E731
                lambda a: a.reshape((idx.shape[0],) + a.shape[2:]), t
            )
            return {
                "deltas": unblock(ys["delta"]),
                "pc_loss": unblock(ys["pc_loss"]),
                "pc_resid": unblock(ys["pc_resid"]),
            }

        stack_in_specs = (P(), P(), P(), P(CLIENT_AXIS), P(CLIENT_AXIS),
                          P(CLIENT_AXIS), P(CLIENT_AXIS))
        if use_decay:
            stack_in_specs += (P(),)
        sharded_stack_lane = jax.shard_map(
            lane_stack_fn, mesh=mesh, in_specs=stack_in_specs,
            out_specs={
                "deltas": P(CLIENT_AXIS),
                "pc_loss": P(CLIENT_AXIS),
                "pc_resid": P(CLIENT_AXIS),
            },
        )

        @partial(jax.jit, donate_argnums=(0, 1, 10) if donate else ())
        def ledger_round_fn(history, server_opt_state, train_x, train_y,
                            idx, mask, agg_w, n_ex, slots, cohort, ledger,
                            cur_slot, next_slot, rng):
            for leaf in jax.tree.leaves(history):
                if leaf.shape[0] != window:
                    raise ValueError(
                        f"history ring has {leaf.shape[0]} slots, engine "
                        f"was built for window={window}"
                    )
                break
            keys = jax.random.split(rng, idx.shape[0])
            extra = ()
            if use_decay:
                extra = (_decay_scale(client_cfg.lr_decay, server_opt_state),)
            with jax.named_scope("fedbuff_train_stack"):
                out = sharded_stack_lane(
                    history, train_x, train_y, idx, mask, slots, keys,
                    *extra,
                )
            wire = out["deltas"]
            trust = None
            w = agg_w.astype(jnp.float32)
            if reputation:
                # staleness-aware reputation-weighted merge: the trust
                # from the CARRIED ledger folds into the host-computed
                # staleness decay — admitted weight base·(1+s)^-α·trust
                from colearn_federated_learning_tpu.server.aggregation import (  # noqa: E501
                    reputation_weights,
                )

                trust = reputation_weights(
                    ledger, cohort.astype(jnp.int32), rep_floor,
                    rep_strength, rep_z_gain, ledger_zmax,
                )
                w = w * trust.astype(jnp.float32)
            with jax.named_scope("fedbuff_aggregate"):
                w_sum = w.sum()
                # async weights are FRACTIONAL — guard only the true
                # all-dropout case (same semantics as the psum path)
                denom = jnp.where(w_sum > 0, w_sum, 1.0)
                mean_delta = jax.tree.map(
                    lambda d: jnp.einsum("c,c...->...", w, d) / denom,
                    wire,
                )
                mean_loss = (w * out["pc_loss"]).sum() / denom
                n_total = n_ex.sum()
            with jax.named_scope("round_server_apply"):
                current = jax.tree.map(
                    lambda h: jnp.take(h, cur_slot, axis=0), history
                )
                new_params, new_opt_state = server_update(
                    current, server_opt_state, mean_delta
                )
                new_history = jax.tree.map(
                    lambda h, p: h.at[next_slot].set(p.astype(h.dtype)),
                    history, new_params,
                )
            with jax.named_scope("round_client_ledger"):
                from colearn_federated_learning_tpu.obs.ledger import (
                    client_round_stats,
                    update_ledger,
                )

                stats = client_round_stats(
                    wire, mean_delta, out["pc_loss"], out["pc_resid"],
                    n_ex, ledger_zmax,
                )
                new_ledger = update_ledger(
                    ledger, cohort.astype(jnp.int32), n_ex, stats,
                    ledger_ema,
                )
            return (new_history, new_params, new_opt_state, new_ledger,
                    RoundMetrics(mean_loss, n_total))

        return instrument("round.fedbuff_ledger", ledger_round_fn)

    def lane_fn(history, train_x, train_y, idx, mask, agg_w, n_ex, slots,
                keys, *rest):
        lr_scale = rest[0] if use_decay else None
        history = _pcast_varying(history)

        def train_one(slot, b_idx, b_mask, key):
            start = jax.tree.map(lambda h: jnp.take(h, slot, axis=0), history)
            extra = () if lr_scale is None else (lr_scale,)
            w, m = local_train(start, train_x, train_y, b_idx, b_mask, key,
                               *extra)
            delta = jax.tree.map(
                lambda wi, p: wi.astype(jnp.float32) - p.astype(jnp.float32),
                w, start,
            )
            return delta, m

        def per_block(acc, inp):
            b_idx, b_mask, b_w, b_n, b_slot, b_keys = inp
            delta_b, m_b = jax.vmap(
                train_one, in_axes=(0, 0, 0, 0),
            )(b_slot, b_idx, b_mask, b_keys)
            if clip_delta_norm > 0.0:
                delta_b = _clip_block(delta_b, clip_delta_norm)
            d_acc, w_acc, n_acc, l_acc = acc
            d_acc = jax.tree.map(
                lambda a, dd: a + jnp.einsum(
                    "c,c...->...", b_w.astype(jnp.float32), dd
                ).astype(a.dtype),
                d_acc, delta_b,
            )
            return (d_acc, w_acc + b_w.sum(), n_acc + b_n.sum(),
                    l_acc + (b_w * m_b.loss).sum()), None

        n_blocks = idx.shape[0] // width
        blocked = jax.tree.map(
            lambda a: a.reshape((n_blocks, width) + a.shape[1:]),
            (idx, mask, agg_w, n_ex, slots, keys),
        )
        d0 = jax.tree.map(
            lambda h: jnp.zeros(h.shape[1:], jnp.float32), history
        )
        acc0 = _pcast_varying(
            (d0, jnp.zeros(()), jnp.zeros(()), jnp.zeros(())),
        )
        (d_sum, w_sum, n_sum, l_sum), _ = jax.lax.scan(per_block, acc0, blocked)
        d_sum = jax.lax.psum(d_sum, CLIENT_AXIS)
        w_sum = jax.lax.psum(w_sum, CLIENT_AXIS)
        n_sum = jax.lax.psum(n_sum, CLIENT_AXIS)
        l_sum = jax.lax.psum(l_sum, CLIENT_AXIS)
        # Async weights are FRACTIONAL (staleness decay), so a max-with-1
        # floor would silently attenuate legitimate updates whenever
        # w_sum < 1 — guard only the true all-dropout case, same
        # degenerate-round semantics as the sync engine (zero delta,
        # zero loss).
        denom = jnp.where(w_sum > 0, w_sum, 1.0)
        return trees.tree_scale(d_sum, 1.0 / denom), n_sum, l_sum / denom

    in_specs = (P(), P(), P(), P(CLIENT_AXIS), P(CLIENT_AXIS),
                P(CLIENT_AXIS), P(CLIENT_AXIS), P(CLIENT_AXIS),
                P(CLIENT_AXIS))
    if use_decay:
        in_specs += (P(),)
    sharded_lane = jax.shard_map(
        lane_fn, mesh=mesh, in_specs=in_specs, out_specs=(P(), P(), P()),
    )

    @partial(jax.jit, donate_argnums=(0, 1) if donate else ())
    def round_fn(history, server_opt_state, train_x, train_y, idx, mask,
                 agg_w, n_ex, slots, cur_slot, next_slot, rng):
        # the ring size must agree with the host scheduler's slot
        # arithmetic (versions % window) — a mismatch would gather stale
        # params from the WRONG slot with no runtime error
        for leaf in jax.tree.leaves(history):
            if leaf.shape[0] != window:
                raise ValueError(
                    f"history ring has {leaf.shape[0]} slots, engine was "
                    f"built for window={window}"
                )
            break
        keys = jax.random.split(rng, idx.shape[0])
        extra = ()
        if use_decay:
            extra = (_decay_scale(client_cfg.lr_decay, server_opt_state),)
        with jax.named_scope("fedbuff_train_aggregate"):
            mean_delta, n_total, mean_loss = sharded_lane(
                history, train_x, train_y, idx, mask, agg_w, n_ex, slots, keys,
                *extra,
            )
        with jax.named_scope("round_server_apply"):
            current = jax.tree.map(
                lambda h: jnp.take(h, cur_slot, axis=0), history
            )
            new_params, new_opt_state = server_update(
                current, server_opt_state, mean_delta
            )
            new_history = jax.tree.map(
                lambda h, p: h.at[next_slot].set(p.astype(h.dtype)),
                history, new_params,
            )
        return (new_history, new_params, new_opt_state,
                RoundMetrics(mean_loss, n_total))

    return instrument("round.fedbuff", round_fn)


def make_sequential_round_fn(model, client_cfg, dp_cfg, task, server_update,
                             local_dtype=None, agg: str = "examples",
                             scaffold: bool = False, num_clients: int = 0,
                             aggregator: str = "weighted_mean",
                             trim_ratio: float = 0.1,
                             compression: str = "", topk_ratio: float = 0.01,
                             qsgd_levels: int = 256, topk_exact: bool = False,
                             clip_delta_norm: float = 0.0,
                             feddyn_alpha: float = 0.0,
                             byzantine_f: int = 0,
                             secagg: bool = False,
                             secagg_quant_step: float = 1e-4,
                             secagg_mode: str = "ring",
                             scan_unroll: int = 1,
                             client_dp_noise: float = 0.0,
                             dp_fixed_denom: float = 0.0,
                             downlink: str = "",
                             downlink_levels: int = 256,
                             error_feedback: bool = False,
                             attack: str = "",
                             attack_scale: float = 10.0,
                             attack_eps: float = 1.0,
                             on_device_mask: bool = False,
                             client_ledger: bool = False,
                             ledger_ema: float = 0.2,
                             ledger_zmax: float = 3.5,
                             reputation: bool = False,
                             rep_floor: float = 0.05,
                             rep_strength: float = 6.0,
                             rep_z_gain: float = 1.0,
                             fused_apply: bool = False,
                             cohort_layout: str = "spatial"):
    """Reference-semantics engine: python loop over the cohort, jitted
    per-client local training, host-side weighted mean. Used for
    single-device debugging and as the parity oracle the shard_map
    engine is tested against (SURVEY.md §4.3). ``scaffold``, ``feddyn``,
    ``error_feedback`` and ``aggregator`` mirror the sharded engine's
    signature exactly (under ``error_feedback`` the round fn takes the
    cohort's e-rows as ``c_cohort`` — ``c_global`` stays None — and
    returns ``(params, opt_state, new_e_cohort, metrics)``).
    ``on_device_mask`` mirrors the sharded engine's compact-spec mask
    input: ``mask`` arrives as the ``[K, 2]`` spec and is expanded to
    the identical full float32 mask before the loop (the loop itself is
    the oracle — it sees exactly what the lanes rebuild in-program).
    ``client_ledger`` mirrors the sharded engine: the round fn takes
    ``ledger`` + ``ledger_ids`` and returns the updated ledger before
    the metrics, built from the SAME shared stats/update helpers
    (obs/ledger.py) over the same wire-upload stack.
    ``cohort_layout`` is accepted for signature symmetry and validated
    through the shared compat mirror, but the oracle itself is
    layout-free: the python loop IS the reference semantics both
    layouts must reproduce."""
    if agg not in ("examples", "uniform"):
        raise ValueError(f"unknown aggregation mode {agg!r}")
    _check_engine_compat(scaffold, aggregator, compression, clip_delta_norm,
                         secagg=secagg, feddyn=feddyn_alpha > 0.0,
                         client_dp=client_dp_noise, downlink=downlink,
                         secagg_quant_step=secagg_quant_step,
                         error_feedback=error_feedback, attack=attack,
                         client_ledger=client_ledger,
                         reputation=reputation, fused_apply=fused_apply,
                         cohort_layout=cohort_layout,
                         example_dp=bool(getattr(dp_cfg, "enabled", False)))
    if fused_apply and not hasattr(server_update, "fused_reduce"):
        raise ValueError(
            "fused_apply=True requires a server_update built by "
            "make_server_update_fn with fused_apply enabled"
        )
    if client_dp_noise > 0.0 and agg != "uniform":
        raise ValueError(
            "client-level DP requires uniform aggregation weights "
            "(the driver selects them automatically)"
        )
    feddyn, client_cfg = _feddyn_prepare(
        client_cfg, scaffold, feddyn_alpha, aggregator, compression,
        clip_delta_norm,
    )
    stateful = scaffold or feddyn
    if stateful and num_clients <= 0:
        raise ValueError("stateful algorithms require num_clients")
    if aggregator not in ("weighted_mean", "median", "trimmed_mean", "krum"):
        raise ValueError(f"unknown aggregator {aggregator!r}")
    robust = aggregator != "weighted_mean"
    from colearn_federated_learning_tpu.ops.compression import (
        downlink_quantize,
        make_compressor,
    )

    compress = make_compressor(compression, topk_ratio, qsgd_levels,
                               topk_exact=topk_exact)
    local_train = instrument(
        "seq.local_train",
        jax.jit(make_local_train_fn(model, client_cfg, dp_cfg, task,
                                    local_dtype=local_dtype,
                                    scan_unroll=scan_unroll)),
    )
    update = instrument("seq.server_apply", jax.jit(server_update))
    # the fused stacked-path entry, jitted ONCE at the factory (the
    # interpret-mode kernel would otherwise re-trace eagerly per round)
    fused_reduce = (
        instrument("seq.fused_reduce", jax.jit(server_update.fused_reduce))
        if fused_apply else None
    )

    use_decay = client_cfg.lr_decay != 1.0
    # ONE jit wrapper per factory: eager per-client pairwise uploads
    # re-trace the K-step PRG scan every call (~seconds each), and a
    # wrapper created inside round_fn would re-compile every ROUND —
    # the cache lives with the wrapper, so it must outlive the round
    pairwise_up = (
        instrument("seq.secagg_upload",
                   jax.jit(_secagg_pairwise_upload, static_argnums=(7, 8)),
                   static_argnums=(7, 8))
        if secagg and secagg_mode == "pairwise" else None
    )

    def round_fn(params, server_opt_state, train_x, train_y, idx, mask, n_ex, rng,
                 c_global=None, c_cohort=None, pair_seeds=None, byz=None,
                 ledger=None, ledger_ids=None):
        if attack and byz is None:
            raise TypeError(f"attack={attack!r} requires the byz mask input")
        if client_ledger and (ledger is None or ledger_ids is None):
            raise TypeError(
                "client_ledger requires the ledger and ledger_ids inputs"
            )
        trust = None
        if reputation:
            # the SAME shared helper as the sharded program, on the same
            # ledger-as-carried-in — trust parity across engines holds
            # by construction (client_ledger guarantees the inputs)
            from colearn_federated_learning_tpu.server.aggregation import (
                reputation_weights,
            )

            trust = reputation_weights(
                jnp.asarray(ledger),
                jnp.asarray(ledger_ids).astype(jnp.int32),
                rep_floor, rep_strength, rep_z_gain, ledger_zmax,
            )
        if on_device_mask:
            import numpy as _np

            from colearn_federated_learning_tpu.data.loader import (
                expand_mask_spec,
            )

            mask = expand_mask_spec(
                _np.asarray(mask), idx.shape[1], idx.shape[2],
                client_cfg.local_epochs,
            )
        k = idx.shape[0]
        keys = jax.random.split(rng, k)
        lr_scale = (
            _decay_scale(client_cfg.lr_decay, server_opt_state)
            if use_decay else None
        )
        extra = (lr_scale,) if use_decay else ()
        deltas, weights, losses, resids = [], [], [], []
        # the weights clients receive this round (identical dither
        # derivation as the sharded engine — parity holds)
        bcast = params
        if downlink:
            bcast = downlink_quantize(
                params, jax.random.fold_in(rng, _DOWNLINK_FOLD),
                downlink_levels,
            )
        if secagg:
            # identical mask derivation + per-client streams as the
            # sharded engine; int32 sums are order-independent mod 2^32,
            # so the two engines agree BITWISE on the aggregate. Ring
            # mode: static full-cohort ring (slot c → c+1 mod K);
            # pairwise mode: host-built seed matrix input.
            if secagg_mode == "pairwise":
                if pair_seeds is None:
                    raise TypeError("secagg_mode='pairwise' requires pair_seeds")
                part_full = jnp.asarray(n_ex) > 0
            else:
                mask_key = jax.random.fold_in(rng, _SECAGG_FOLD)
            q_acc = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.int32), params
            )
            slots = jnp.arange(k, dtype=jnp.int32)
        new_cs = []
        dc_sum = (
            jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
            if stateful else None
        )
        for c in range(k):
            if stateful:
                c_i = jax.tree.map(lambda a: a[c], c_cohort)
                if scaffold:
                    corr = jax.tree.map(lambda cg, ci: cg - ci, c_global, c_i)
                else:  # feddyn linear term
                    corr = jax.tree.map(jnp.negative, c_i)
                w_i, m_i = local_train(params, train_x, train_y, idx[c], mask[c],
                                       keys[c], lr_scale, corr)
                # width-1 block through the SAME update helper as the
                # sharded lane — the oracle can't drift from the engine
                k_valid = jnp.asarray(
                    [(jnp.asarray(mask[c]).sum(-1) > 0).sum()], jnp.float32
                )
                part = ((jnp.asarray(n_ex[c]) > 0) & (k_valid[0] > 0)).astype(
                    jnp.float32
                )[None]
                if scaffold:
                    lr_i = jnp.float32(client_cfg.lr) * (
                        lr_scale.astype(jnp.float32) if lr_scale is not None else 1.0
                    )
                    new_c_block = _scaffold_c_update(
                        jax.tree.map(lambda a: a[None], c_i), c_global, params,
                        jax.tree.map(lambda a: a[None], w_i), k_valid, lr_i, part,
                    )
                else:
                    new_c_block = _feddyn_g_update(
                        jax.tree.map(lambda a: a[None], c_i), params,
                        jax.tree.map(lambda a: a[None], w_i), part,
                        feddyn_alpha,
                    )
                new_c = jax.tree.map(lambda a: a[0], new_c_block)
                new_cs.append(new_c)
                dc_sum = jax.tree.map(
                    lambda a, nc, ci: a + (nc - ci), dc_sum, new_c, c_i
                )
            else:
                w_i, m_i = local_train(bcast, train_x, train_y, idx[c], mask[c],
                                       keys[c], *extra)
            # delta vs the RECEIVED weights (bcast == params unless
            # downlink compression is on), applied to the exact params
            delta_i = jax.tree.map(
                lambda w, p: w.astype(jnp.float32) - p.astype(jnp.float32),
                w_i, bcast,
            )
            # client_ledger resid: raw-vs-shipped on the plain path,
            # the EF accumulator residual below (same rule as the lane)
            pre_i = delta_i if client_ledger else None
            resid_c = None
            if clip_delta_norm > 0.0 or compress is not None:
                # one width-1 block through the SAME operators as the
                # sharded lane (clip first, then EF memory, then
                # compress the wire format)
                block = jax.tree.map(lambda a: a[None], delta_i)
                if clip_delta_norm > 0.0:
                    block = _clip_block(block, clip_delta_norm)
                if error_feedback:
                    e_block = jax.tree.map(
                        lambda a: a[c][None].astype(jnp.float32), c_cohort
                    )
                    acc_block = jax.tree.map(jnp.add, block, e_block)
                    comp_block = compress(acc_block, keys[c][None])
                    if client_ledger:
                        from colearn_federated_learning_tpu.obs.ledger import (
                            upload_residual,
                        )

                        resid_c = upload_residual(acc_block, comp_block)[0]
                    part_c = (jnp.asarray(n_ex[c]) > 0)
                    new_cs.append(jax.tree.map(
                        lambda a, cp, e: jnp.where(part_c, a - cp, e)[0],
                        acc_block, comp_block, e_block,
                    ))
                    block = comp_block
                elif compress is not None:
                    block = compress(block, keys[c][None])
                delta_i = jax.tree.map(lambda a: a[0], block)
            if client_ledger:
                if resid_c is None:
                    from colearn_federated_learning_tpu.obs.ledger import (
                        upload_residual,
                    )

                    resid_c = upload_residual(
                        jax.tree.map(lambda a: a[None], pre_i),
                        jax.tree.map(lambda a: a[None], delta_i),
                    )[0]
                resids.append(resid_c)
            n_c = jnp.asarray(n_ex[c])
            w_c = n_c if agg == "examples" else (n_c > 0).astype(n_c.dtype)
            if reputation:
                # identical multiply to the lane's b_w * b_tr — the
                # loss metric weights identically too
                w_c = w_c * trust[c]
            weights.append(w_c)
            losses.append(m_i.loss)
            if secagg:
                # only the masked int32 accumulator survives the loop —
                # keeping the raw f32 deltas too would retain cohort×
                # params dead memory
                if secagg_mode == "pairwise":
                    up = pairwise_up(
                        jax.tree.map(lambda a: a[None], delta_i),
                        jnp.asarray(weights[-1])[None],
                        slots[c][None], (jnp.asarray(n_ex[c]) > 0)[None],
                        part_full, pair_seeds, params, secagg_quant_step, k,
                    )
                else:
                    up = _secagg_upload(
                        jax.tree.map(lambda a: a[None], delta_i),
                        jnp.asarray(weights[-1])[None],
                        slots[c][None], (jnp.asarray(n_ex[c]) > 0)[None],
                        mask_key, params, secagg_quant_step, k,
                    )
                q_acc = jax.tree.map(lambda a, u: a + u[0], q_acc, up)
            else:
                deltas.append(delta_i)
        n_total = jnp.asarray(n_ex).sum()
        w_sum = jnp.sum(jnp.stack(weights))
        denom = jnp.where(w_sum > 0, w_sum, 1.0)
        # fixed public denominator under client DP (see the sharded lane)
        agg_denom = (
            jnp.float32(dp_fixed_denom or k)
            if client_dp_noise > 0.0 else denom
        )
        fused_out = None
        if robust or attack:
            # the per-client stack path — identical ops to the sharded
            # engine's _mean_delta (shared transform + shared stack
            # aggregation), so attacked/robust rounds agree across
            # engines by construction
            stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *deltas)
            if attack:
                from colearn_federated_learning_tpu.server.attacks import (
                    apply_upload_attack,
                )

                # same scope name as the sharded engine's _wire_stack —
                # the cost-model phase taxonomy (obs/roofline.py) is
                # engine-invariant down to the device-trace labels
                with jax.named_scope("round_attack_transform"):
                    stacked = apply_upload_attack(
                        stacked, jnp.asarray(byz), keys, attack, attack_scale,
                        attack_eps, participation=jnp.asarray(n_ex) > 0,
                    )
            if fused_reduce is not None and aggregator in (
                "weighted_mean", "krum",
            ):
                # fused server chain: the SAME shared weight/one-hot
                # construction as the sharded program feeds the same
                # kernel — fused-path cross-engine parity by
                # construction (ops/pallas_apply.py)
                stack_in, w_in = _fused_stack_inputs(
                    stacked, jnp.asarray(n_ex), trust, aggregator, agg,
                    byzantine_f, k,
                )
                fused_out = fused_reduce(
                    params, server_opt_state, stack_in, w_in
                )
                mean_delta = fused_out[2]
            elif robust:
                from colearn_federated_learning_tpu.server.aggregation import (
                    robust_reduce,
                    scale_deltas_by_trust,
                )

                agg_stack = stacked
                if trust is not None:
                    # same soft suppression as the sharded _mean_delta:
                    # trust scales uploads, order statistics unweighted
                    agg_stack = scale_deltas_by_trust(stacked, trust)
                mean_delta = robust_reduce(
                    agg_stack, jnp.asarray(n_ex) > 0, aggregator, trim_ratio,
                    byzantine_f,
                )
            else:
                from colearn_federated_learning_tpu.server.attacks import (
                    stack_weighted_mean,
                )

                mean_delta = stack_weighted_mean(
                    stacked, jnp.asarray(n_ex), agg, params, trust
                )
        elif secagg:
            # the cohort sum completed the ring: masks cancelled exactly
            mean_delta = jax.tree.map(
                lambda d, p: (
                    d.astype(jnp.float32) * secagg_quant_step / agg_denom
                ).astype(p.dtype),
                q_acc, params,
            )
        else:
            # deltas accumulate in f32; the final cast mirrors the sharded
            # engine's accumulator dtype (= server params dtype)
            acc = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            for d, w in zip(deltas, weights):
                acc = trees.tree_axpy(w, d, acc)
            mean_delta = jax.tree.map(
                lambda d, p: d.astype(p.dtype),
                trees.tree_scale(acc, 1.0 / agg_denom), params,
            )
        if client_dp_noise > 0.0:
            # same key derivation + per-leaf streams as the sharded
            # engine — parity holds on the noisy path too
            std = jnp.float32(
                client_dp_noise * clip_delta_norm
            ) / agg_denom
            mean_delta = _client_dp_noise(
                jax.random.fold_in(rng, _CLIENT_DP_FOLD), mean_delta, std
            )
        new_ledger = None
        if client_ledger:
            # the SAME shared stats + scatter helpers as the sharded
            # program, applied to the same wire stack — ledger parity
            # across engines holds by construction
            from colearn_federated_learning_tpu.obs.ledger import (
                client_round_stats,
                update_ledger,
            )

            wire = (
                stacked if (robust or attack)
                else jax.tree.map(lambda *ls: jnp.stack(ls), *deltas)
            )
            stats = client_round_stats(
                wire, mean_delta, jnp.stack(losses), jnp.stack(resids),
                jnp.asarray(n_ex), ledger_zmax,
            )
            new_ledger = update_ledger(
                jnp.asarray(ledger), jnp.asarray(ledger_ids),
                jnp.asarray(n_ex), stats, ledger_ema,
            )
        mean_loss = sum(w * l for w, l in zip(weights, losses)) / denom
        if stateful:
            new_c_global = jax.tree.map(
                lambda cg, dc: cg + dc / float(num_clients), c_global, dc_sum
            )
            new_c_cohort = jax.tree.map(
                lambda *ls: jnp.stack(ls), *new_cs
            )
            if feddyn:
                new_params = _feddyn_server_step(
                    params, mean_delta, new_c_global, feddyn_alpha
                )
                new_opt_state = dict(
                    server_opt_state, round=server_opt_state["round"] + 1
                )
            else:
                new_params, new_opt_state = update(
                    params, server_opt_state, mean_delta
                )
            return (new_params, new_opt_state, new_c_global, new_c_cohort,
                    RoundMetrics(mean_loss, n_total))
        if fused_out is not None:
            # params/opt state already advanced inside the fused kernel
            # pass (mean_delta above was its third output)
            new_params, new_opt_state = fused_out[0], fused_out[1]
        else:
            new_params, new_opt_state = update(
                params, server_opt_state, mean_delta
            )
        if error_feedback:
            new_e_cohort = jax.tree.map(lambda *ls: jnp.stack(ls), *new_cs)
            if client_ledger:
                return (new_params, new_opt_state, new_e_cohort, new_ledger,
                        RoundMetrics(mean_loss, n_total))
            return (new_params, new_opt_state, new_e_cohort,
                    RoundMetrics(mean_loss, n_total))
        if client_ledger:
            return (new_params, new_opt_state, new_ledger,
                    RoundMetrics(mean_loss, n_total))
        return new_params, new_opt_state, RoundMetrics(mean_loss, n_total)

    return round_fn
