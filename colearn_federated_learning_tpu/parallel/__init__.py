"""Parallelism layer: device mesh + the shard_map/psum round engine (L0b).

The reference's distributed backend is torch.distributed + NCCL
(BASELINE.json:5). The TPU-native equivalent is not a socket library —
it is a ``jax.sharding.Mesh`` whose ``"clients"`` axis spans all chips,
with XLA collectives (``psum``) riding the ICI. Multi-host extension is
``jax.distributed.initialize`` + the same mesh over more processes; no
code in the round engine changes.
"""
