"""Decentralized (serverless) federated learning — gossip averaging.

DFedAvg / consensus-SGD (Lian et al. 2017 "Can Decentralized Algorithms
Outperform Centralized?"; Koloskova et al. 2020): there is NO server.
Every client keeps its OWN model replica; each round every client trains
locally from its own replica, then mixes with its graph neighbours
through a doubly-stochastic gossip matrix W:

    xᵢ ← Σⱼ Wᵢⱼ · xⱼ(after local training)

The TPU-native mapping (spec frame: SURVEY.md §2 C6/C8 — the
aggregation/communication rows; the reference mount is empty so the
citation points at the spec): replicas live as ONE ``[N, ...]`` stacked
tree, mesh-sharded over the ``clients`` axis — each lane owns a
contiguous arc of the ring. Ring mixing is then a **halo exchange**:
only each lane's two boundary rows cross the ICI (two ``ppermute``s of
one params-sized message each, independent of N), while the interior
rows mix with an in-lane shift. Per mixing step the cross-chip traffic
is 2·|params| per lane — compare centralized FedAvg's full psum tree —
which is exactly why gossip methods exist: O(degree) neighbour traffic
instead of all-reduce.

Topologies:

- ``ring``: W = Metropolis ring weights ``xᵢ ← (1−2γ)xᵢ + γ(xᵢ₋₁ +
  xᵢ₊₁)`` (doubly stochastic for any γ; contraction for 0 < γ ≤ 1/2;
  γ = 1/3 is the Metropolis choice). Consensus error contracts by the
  spectral gap 1 − λ₂(W), λ₂ = 1 − 2γ(1 − cos 2π/N).
- ``full``: W = (1/N)·11ᵀ — complete averaging each mixing step. One
  mixing step from a consensus start is EXACTLY centralized FedAvg
  with uniform weights (the parity oracle the tests pin).

Mixing preserves the replica mean exactly (W doubly stochastic), so
the consensus mean ``x̄`` — which the round fn also returns, for
evaluation/checkpoint export — follows the averaged-SGD trajectory.

Participation: a client whose ``n_ex`` is 0 (dropout upstream zeroing)
trains zero valid steps — its local phase is an exact no-op — but still
gossips, which is how an idle node in a real decentralized system
behaves (it keeps relaying its current model).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from colearn_federated_learning_tpu.client.trainer import make_local_train_fn
from colearn_federated_learning_tpu.obs.executables import instrument
from colearn_federated_learning_tpu.parallel.mesh import CLIENT_AXIS, has_batch_axis
from jax.sharding import PartitionSpec as P


class GossipMetrics(NamedTuple):
    train_loss: jnp.ndarray
    examples: jnp.ndarray
    # mean over clients of ‖xᵢ − x̄‖² (post-mixing), summed over leaves —
    # THE health metric of a decentralized run (should contract toward
    # the noise floor set by data heterogeneity × lr)
    consensus_dist: jnp.ndarray


def make_gossip_round_fn(model, client_cfg, dp_cfg, task, mesh,
                         num_clients: int, gamma: float = 1.0 / 3.0,
                         mixing_steps: int = 1, topology: str = "ring",
                         donate: bool = True, local_dtype=None,
                         scan_unroll: int = 1, cohort_size: int = 0,
                         attack: str = "", attack_scale: float = 10.0,
                         attack_eps: float = 1.0):
    """Build the jitted one-program gossip round.

    Signature of the returned fn (full participation,
    ``cohort_size`` 0 or == N)::

        (replicas [N, ...] client-sharded, train_x, train_y,
         idx [N,steps,batch], mask [N,steps,batch], n_ex [N], rng)
        → (new_replicas, mean_params, GossipMetrics)

    **Partial participation** (``cohort_size`` = K < N, r5 — what makes
    gossip schedulable beyond toy N): only the K sampled clients train;
    everyone still mixes. The local phase costs O(K·steps) compute
    instead of O(N·steps): the cohort's replica rows are GATHERED from
    the client-sharded stack in-program (take-with-fill + one psum —
    each row owned by exactly one lane, the state-store pattern from
    round_engine.py), trained cohort-sharded, and scattered back
    (all_gather + windowed in-shard write, OOB drops). Signature gains
    trailing ``cohort_ids [K]`` (replicated) and idx/mask/n_ex/keys
    become ``[K, ...]`` cohort-sharded::

        (replicas, train_x, train_y, idx [K,steps,batch],
         mask [K,steps,batch], n_ex [K], rng, cohort_ids [K])
        → (new_replicas, mean_params, GossipMetrics)

    Replica-stack memory stays O(N·|params|/lanes) — partial
    participation cuts compute, not storage; the driver's HBM
    pre-flight guards the stack itself.

    ``num_clients`` must divide evenly over the mesh's client lanes
    (there are no pad rows to hide); so must ``cohort_size``.

    ``attack`` (server/attacks.py): the decentralized threat model — a
    compromised client gossips a POISONED replica to its neighbours.
    The round fn gains a trailing ``byz`` mask input (``[N]`` under
    full participation, ``[K]`` under partial — aligned with ``n_ex``);
    after local training and before mixing, each compromised client's
    local update ``x_trained − x_pre`` is transformed by the shared
    per-client attack operator (``sign_flip``/``gauss``/``scale``;
    ``alie`` is rejected — it sizes itself from cohort statistics a
    decentralized attacker cannot observe) and its replica rewritten to
    ``x_pre + Δ_attacked``. Honest neighbours then mix the poison in.
    """
    if topology not in ("ring", "full"):
        raise ValueError(f"unknown gossip topology {topology!r}")
    if attack:
        from colearn_federated_learning_tpu.server.attacks import (
            UPLOAD_ATTACKS,
        )

        if attack not in UPLOAD_ATTACKS:
            raise ValueError(f"unknown upload attack {attack!r}")
        if attack == "alie":
            raise ValueError(
                "attack='alie' is incompatible with gossip (no cohort "
                "statistics are observable to a decentralized attacker)"
            )
    if client_cfg.lr_decay != 1.0:
        # mirror config.validate(): no lr_scale is plumbed into
        # local_train here, so decay would be silently dropped for a
        # direct engine caller (ADVICE r4 #1)
        raise ValueError("gossip does not support client.lr_decay")
    if not 0.0 < gamma <= 0.5:
        # γ > 1/2 makes the ring weights non-contractive (negative
        # self-weight); γ ≤ 0 is no mixing at all
        raise ValueError(f"gossip gamma must be in (0, 0.5], got {gamma}")
    if mixing_steps < 1:
        raise ValueError(f"mixing_steps must be >= 1, got {mixing_steps}")
    if has_batch_axis(mesh):
        raise ValueError("gossip does not support a batch axis (yet)")
    n_lanes = mesh.shape[CLIENT_AXIS]
    if num_clients % n_lanes != 0:
        raise ValueError(
            f"num_clients {num_clients} not divisible by {n_lanes} lanes "
            f"(every client trains every round — no pad rows)"
        )
    rows = num_clients // n_lanes
    if cohort_size in (0, num_clients):
        cohort_size = 0  # full participation: the classic path
    elif not 0 < cohort_size < num_clients:
        raise ValueError(
            f"gossip cohort_size {cohort_size} must be in (0, "
            f"num_clients={num_clients}]"
        )
    elif cohort_size % n_lanes != 0:
        raise ValueError(
            f"gossip cohort_size {cohort_size} not divisible by "
            f"{n_lanes} lanes"
        )
    k_rows = cohort_size // n_lanes if cohort_size else 0
    local_train = make_local_train_fn(
        model, client_cfg, dp_cfg, task, local_dtype=local_dtype,
        scan_unroll=scan_unroll,
    )
    # the ring is the global client order: lane l owns rows
    # [l·rows, (l+1)·rows); forward neighbour of the lane's last row is
    # the NEXT lane's first row
    fwd = [(i, (i + 1) % n_lanes) for i in range(n_lanes)]
    bwd = [(i, (i - 1) % n_lanes) for i in range(n_lanes)]

    if attack:
        from colearn_federated_learning_tpu.server.attacks import (
            apply_upload_attack,
        )

    def _poison(trained_t, pre_t, byz_b, keys_b):
        """Rewrite the compromised rows' replicas to ``x_pre +
        attack(Δ)`` where ``Δ = x_trained − x_pre`` — the shared
        per-client upload transform applied at the decentralized
        "upload": the replica about to be gossiped. f32 math, cast back
        to the replica storage dtype."""
        delta = jax.tree.map(
            lambda t, p: t.astype(jnp.float32) - p.astype(jnp.float32),
            trained_t, pre_t,
        )
        delta = apply_upload_attack(
            delta, byz_b, keys_b, attack, attack_scale, attack_eps
        )
        return jax.tree.map(
            lambda p, d: (p.astype(jnp.float32) + d).astype(p.dtype),
            pre_t, delta,
        )

    def lane_fn(replicas, train_x, train_y, idx, mask, n_ex, keys, *rest):
        rest = list(rest)
        cohort_ids = rest.pop(0) if cohort_size else None
        byz = rest.pop(0) if attack else None
        # --- local phase ----------------------------------------------
        def per_row(_, inp):
            r_params, r_idx, r_mask, r_key = inp
            w, m = local_train(r_params, train_x, train_y, r_idx, r_mask, r_key)
            # replicas stay at the storage dtype across rounds even when
            # local training runs bf16
            w = jax.tree.map(
                lambda a, p: a.astype(p.dtype), w, r_params
            )
            return 0.0, (w, m.loss)

        if cohort_size:
            # partial participation: gather the cohort's replica rows
            # (each owned by exactly one lane ⇒ the psum superposition
            # is exact), train only those, scatter back
            lane = jax.lax.axis_index(CLIENT_AXIS)
            pos = cohort_ids - lane * rows  # [K]; OOB = not owned
            pos = jnp.where(pos >= 0, pos, rows)
            gathered = jax.tree.map(
                lambda a: jax.lax.psum(
                    jnp.take(a, pos, axis=0, mode="fill", fill_value=0)
                    .astype(jnp.float32),
                    CLIENT_AXIS,
                ),
                replicas,
            )
            chunk = jax.tree.map(
                lambda a, r: jax.lax.dynamic_slice_in_dim(
                    a, lane * k_rows, k_rows, 0
                ).astype(r.dtype),
                gathered, replicas,
            )
            with jax.named_scope("gossip_local_train"):
                _, (trained_chunk, losses) = jax.lax.scan(
                    per_row, 0.0, (chunk, idx, mask, keys)
                )
            if attack:
                # poison the cohort's uploads before the scatter — the
                # byz mask is cohort-aligned ([K], sharded like n_ex)
                trained_chunk = _poison(trained_chunk, chunk, byz, keys)
            trained_full = jax.tree.map(
                lambda t: jax.lax.all_gather(
                    t, CLIENT_AXIS, axis=0, tiled=True
                ),
                trained_chunk,
            )
            trained = jax.tree.map(
                lambda a, nn: a.at[pos].set(nn.astype(a.dtype), mode="drop"),
                replicas, trained_full,
            )
        else:
            # full participation: every row trains from its own params
            with jax.named_scope("gossip_local_train"):
                _, (trained, losses) = jax.lax.scan(
                    per_row, 0.0, (replicas, idx, mask, keys)
                )
            if attack:
                # byz mask is [N], sharded — this lane poisons its rows
                trained = _poison(trained, replicas, byz, keys)

        # --- gossip phase: mixing_steps sweeps of W -------------------
        def sweep_ring(tree):
            # Halo exchange for the whole tree as TWO collectives: the
            # lane's boundary rows (every leaf's first/last row) pack
            # into one flat f32 buffer each, so a sweep is exactly two
            # params-sized ppermute messages — not two per LEAF, which
            # would pay collective-launch latency on dozens of
            # sliver-sized bias/norm leaves.
            leaves, treedef = jax.tree.flatten(tree)
            firsts = jnp.concatenate(
                [l[0].astype(jnp.float32).reshape(-1) for l in leaves]
            )
            lasts = jnp.concatenate(
                [l[-1].astype(jnp.float32).reshape(-1) for l in leaves]
            )
            prev_last = jax.lax.ppermute(lasts, CLIENT_AXIS, fwd)
            next_first = jax.lax.ppermute(firsts, CLIENT_AXIS, bwd)
            out, off = [], 0
            for l in leaves:
                n = 1
                for d in l.shape[1:]:
                    n *= d
                pl = prev_last[off:off + n].reshape(l.shape[1:]).astype(l.dtype)
                nf = next_first[off:off + n].reshape(l.shape[1:]).astype(l.dtype)
                off += n
                up = jnp.concatenate([pl[None], l[:-1]], axis=0)    # xᵢ₋₁
                down = jnp.concatenate([l[1:], nf[None]], axis=0)   # xᵢ₊₁
                out.append(
                    ((1.0 - 2.0 * gamma) * l + gamma * (up + down)).astype(l.dtype)
                )
            return jax.tree.unflatten(treedef, out)

        def sweep_full(tree):
            return jax.tree.map(
                lambda a: jnp.broadcast_to(
                    (jax.lax.psum(a.sum(0), CLIENT_AXIS)
                     / float(num_clients))[None],
                    a.shape,
                ).astype(a.dtype),
                tree,
            )

        sweep = sweep_ring if topology == "ring" else sweep_full
        # named scopes put the gossip sub-phases (local train vs mixing
        # vs consensus) on the device profile — the round is one XLA
        # program, so in-trace attribution is the only attribution
        with jax.named_scope("gossip_mix"):
            mixed = trained
            for _ in range(mixing_steps):
                mixed = sweep(mixed)

        # --- consensus diagnostics + the mean for eval ----------------
        with jax.named_scope("gossip_consensus"):
            mean_params = jax.tree.map(
                lambda a: jax.lax.psum(a.sum(0), CLIENT_AXIS) / float(num_clients),
                mixed,
            )
            dist = sum(
                jax.lax.psum(
                    jnp.sum((a.astype(jnp.float32)
                             - m[None].astype(jnp.float32)) ** 2),
                    CLIENT_AXIS,
                )
                for a, m in zip(jax.tree.leaves(mixed), jax.tree.leaves(mean_params))
            ) / float(num_clients)
        w = n_ex.astype(jnp.float32)
        w_sum = jax.lax.psum(w.sum(), CLIENT_AXIS)
        l_sum = jax.lax.psum((w * losses).sum(), CLIENT_AXIS)
        denom = jnp.where(w_sum > 0, w_sum, 1.0)
        return mixed, mean_params, {
            "loss": l_sum / denom,
            "n": w_sum,
            "consensus": dist,
        }

    in_specs = (P(CLIENT_AXIS), P(), P(), P(CLIENT_AXIS), P(CLIENT_AXIS),
                P(CLIENT_AXIS), P(CLIENT_AXIS))
    if cohort_size:
        in_specs += (P(),)  # cohort ids, replicated
    if attack:
        in_specs += (P(CLIENT_AXIS),)  # byz mask, aligned with n_ex
    sharded_lane = jax.shard_map(
        lane_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(CLIENT_AXIS), P(), {"loss": P(), "n": P(),
                                         "consensus": P()}),
    )

    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def round_fn(replicas, train_x, train_y, idx, mask, n_ex, rng,
                 cohort_ids=None, byz=None):
        for leaf in jax.tree.leaves(replicas):
            if leaf.shape[0] != num_clients:
                raise ValueError(
                    f"replicas leading dim {leaf.shape[0]} != num_clients "
                    f"{num_clients}"
                )
            break
        keys = jax.random.split(rng, idx.shape[0])
        extra = ()
        if cohort_size:
            if cohort_ids is None:
                raise TypeError("partial gossip requires cohort_ids")
            extra = (cohort_ids,)
        if attack:
            if byz is None:
                raise TypeError(f"attack={attack!r} requires the byz mask input")
            extra += (byz,)
        mixed, mean_params, out = sharded_lane(
            replicas, train_x, train_y, idx, mask, n_ex, keys, *extra
        )
        return mixed, mean_params, GossipMetrics(
            out["loss"], out["n"], out["consensus"]
        )

    return instrument("round.gossip", round_fn)
