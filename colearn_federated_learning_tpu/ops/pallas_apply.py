"""Fused server-apply chain as a pallas TPU kernel (ROADMAP item 2,
lever b; ``server.fused_apply``).

The tail of every round program is a chain of separate XLA ops over the
full parameter set: trust/weight scaling of the upload stack → weighted
reduction → negate (pseudo-gradient) → optax trace (server momentum) →
scale by −lr → apply. Each link is an HBM round trip of |params| bytes
(the profiled headline round spends its non-conv time in exactly this
kind of memory-bound elementwise traffic — BASELINE.md r2 profile), and
on the stacked paths the reduction additionally materializes weighted
``[K, |params|]`` intermediates. This module collapses the chain into
ONE VMEM-resident pass over the flat param vector:

- :func:`fused_reduce_apply` — the stacked-path kernel: per tile it
  loads the ``[K, tile]`` wire-upload block, contracts it with the
  combined ``[K]`` weights (FedAvg weight × reputation trust ×
  1/denominator — or krum's one-hot selection row), and applies the
  server optimizer update to the params (and momentum) tile in the
  same pass. One read of the stack, one read-modify-write of
  params/momentum — no weighted ``[K, N]`` intermediate ever lands in
  HBM.
- :func:`fused_delta_apply` — the psum-path kernel: the reduction
  already happened inside the lane psum, so the kernel fuses
  pseudo-grad → momentum trace → lr scale → apply (four XLA passes →
  one read-modify-write).

Semantics match ``optax.sgd(server_lr, momentum)`` exactly in exact
arithmetic: ``m ← β·m − Δ̄;  p ← p − lr·m`` (β = 0 collapses to
``p ← p + lr·Δ̄``). The kernel computes in f32 like the reference; the
only divergence is floating-point reassociation (the fused FMA orders
differ from optax's separate passes), so the engines pin the fused path
against the unfused reference at a documented tolerance
(tests/test_fused_apply.py) rather than bitwise.

Like ``ops/pallas_attention.py``, the kernel runs in pallas INTERPRET
mode on non-TPU backends — exact, slow, and jax-traceable (so GSPMD and
the CPU CI cover the real kernel code path). Only ``mean`` / ``fedavgm``
server optimizers are supported (config.validate enforces it): fedadam/
fedyogi carry second-moment state the one-pass kernel does not model.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from colearn_federated_learning_tpu.obs.roofline import (
    SERVER_APPLY_PASSES_FUSED,
    round_phase_costs,
)


def reduce_apply_cost(k: int, n_coords: int) -> dict:
    """Analytic cost annotation of one :func:`fused_reduce_apply` call
    (obs/roofline.py is the single source of truth — the driver's
    ``phase_cost`` records and this annotation can never drift apart):
    the ``aggregation`` + ``server_apply`` phases of the fused cost
    model. The kernel's whole point is visible in the byte model: the
    stack is read ONCE, params/momentum are one read-modify-write
    (``SERVER_APPLY_PASSES_FUSED`` = 4 passes), and the mean-delta
    intermediate (2 params-sized HBM passes on the unfused chain)
    never materializes."""
    costs = round_phase_costs(
        k=k, steps=1, batch=1, n_coords=n_coords, compute_bytes=4,
        step_flops=0, aggregator="weighted_mean", fused_apply=True,
    )
    return {
        "flops": costs["aggregation"]["flops"]
        + costs["server_apply"]["flops"],
        "bytes": costs["aggregation"]["bytes"]
        + costs["server_apply"]["bytes"],
    }


def delta_apply_cost(n_coords: int) -> dict:
    """Analytic cost annotation of one :func:`fused_delta_apply` call:
    the psum-path kernel touches the delta once and params/momentum as
    one read-modify-write — ``SERVER_APPLY_PASSES_FUSED`` params-sized
    HBM passes total (vs 6 on the unfused optax chain)."""
    n = int(n_coords)
    return {
        "flops": 4 * n,
        "bytes": SERVER_APPLY_PASSES_FUSED * n * 4,
    }

# one kernel tile of the flat param vector: [_SUB, _LANE] f32 = 32 KiB
# VMEM per operand (the [K, _SUB, _LANE] stack block stays ≤ 2 MiB at
# cohort 64) — the (8, 128)-aligned shape the TPU vector unit wants
_SUB = 64
_LANE = 128
_TILE = _SUB * _LANE


def _flatten_tree(tree):
    """Ravel a pytree into one flat f32 vector. Returns
    ``(flat [N], unflatten)`` where ``unflatten(vec)`` splits a flat
    vector back into the tree's leaf shapes, cast per leaf to the
    ORIGINAL leaf dtypes (handles mixed-dtype trees)."""
    leaves, treedef = jax.tree.flatten(tree)
    sizes = [int(l.size) for l in leaves]
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])

    def unflatten(vec):
        out, off = [], 0
        for sz, shp, dt in zip(sizes, shapes, dtypes):
            out.append(vec[off:off + sz].reshape(shp).astype(dt))
            off += sz
        return jax.tree.unflatten(treedef, out)

    return flat, unflatten


def _flatten_stack(tree, k: int):
    """Ravel a ``[K, ...]`` stacked pytree into one ``[K, N]`` f32
    matrix (row c = client c's flat upload)."""
    return jnp.concatenate(
        [l.reshape(k, -1).astype(jnp.float32) for l in jax.tree.leaves(tree)],
        axis=1,
    )


def _pad_tiles(flat):
    """Pad a flat (or [K, N]) array to a tile multiple on its last dim
    and reshape it to the kernel's ``[..., G·_SUB, _LANE]`` layout —
    every kernel block is then a natively (8, 128)-tileable
    ``[_SUB, _LANE]`` (or ``[K, _SUB, _LANE]``) slab, the shape the TPU
    vector unit wants. Returns (tiled, n, grid)."""
    n = flat.shape[-1]
    g = max(1, -(-n // _TILE))
    pad = g * _TILE - n
    if pad:
        flat = jnp.pad(flat, [(0, 0)] * (flat.ndim - 1) + [(0, pad)])
    return flat.reshape(flat.shape[:-1] + (g * _SUB, _LANE)), n, g


def _interpret(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _tile_struct(g):
    return jax.ShapeDtypeStruct((g * _SUB, _LANE), jnp.float32)


_TILE_SPEC = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0))


def _delta_apply_kernel(d_ref, p_ref, m_ref, po_ref, mo_ref, *,
                        lr: float, beta: float):
    delta = d_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    if mo_ref is not None:
        # optax.sgd: trace m′ = β·m + grad with grad = −Δ̄; then −lr·m′
        m_new = beta * m_ref[...].astype(jnp.float32) - delta
        po_ref[...] = p - lr * m_new
        mo_ref[...] = m_new
    else:
        po_ref[...] = p + lr * delta


def _reduce_apply_kernel(w_ref, s_ref, p_ref, m_ref, po_ref, mo_ref, do_ref,
                         *, lr: float, beta: float):
    # [K] ∙ [K, _SUB, _LANE] → [_SUB, _LANE]: the trust/weight-scaled
    # reduction; the weights already carry the 1/denominator, so the
    # contraction IS the finished weighted mean. Broadcast-multiply +
    # leading-axis sum (vreg adds over the K tile stack) rather than a
    # dot — K is a cohort (tiny), the pass is bandwidth-bound, and the
    # elementwise form lowers on every backend.
    w = w_ref[0].astype(jnp.float32)  # [K]
    s = s_ref[...].astype(jnp.float32)  # [K, _SUB, _LANE]
    delta = jnp.sum(w[:, None, None] * s, axis=0)
    do_ref[...] = delta
    p = p_ref[...].astype(jnp.float32)
    if mo_ref is not None:
        m_new = beta * m_ref[...].astype(jnp.float32) - delta
        po_ref[...] = p - lr * m_new
        mo_ref[...] = m_new
    else:
        po_ref[...] = p + lr * delta


def fused_delta_apply(params, momentum, mean_delta, server_lr: float,
                      server_momentum: float = 0.0, interpret=None):
    """Apply the already-reduced mean delta to the params in one fused
    pass: ``(params, momentum, Δ̄) → (params′, momentum′)``.

    ``momentum`` is the optax trace tree (None when the server optimizer
    is plain ``mean``); ``momentum′`` is None in the same case. Trees
    come back in the input leaves' dtypes; kernel math is f32.
    """
    has_mom = momentum is not None
    flat_d, _ = _flatten_tree(mean_delta)
    flat_p, unflat_p = _flatten_tree(params)
    d_t, n, g = _pad_tiles(flat_d)
    p_t = _pad_tiles(flat_p)[0]
    if has_mom:
        flat_m, unflat_m = _flatten_tree(momentum)
        m_t = _pad_tiles(flat_m)[0]
        kernel = functools.partial(
            _delta_apply_kernel, lr=float(server_lr),
            beta=float(server_momentum),
        )
        p_out, m_out = pl.pallas_call(
            kernel, grid=(g,),
            in_specs=[_TILE_SPEC, _TILE_SPEC, _TILE_SPEC],
            out_specs=[_TILE_SPEC, _TILE_SPEC],
            out_shape=[_tile_struct(g), _tile_struct(g)],
            interpret=_interpret(interpret),
        )(d_t, p_t, m_t)
        return unflat_p(p_out.reshape(-1)[:n]), unflat_m(m_out.reshape(-1)[:n])

    def kernel(d_ref, p_ref, po_ref):
        _delta_apply_kernel(d_ref, p_ref, None, po_ref, None,
                            lr=float(server_lr), beta=0.0)

    p_out = pl.pallas_call(
        kernel, grid=(g,),
        in_specs=[_TILE_SPEC, _TILE_SPEC],
        out_specs=_TILE_SPEC,
        out_shape=_tile_struct(g),
        interpret=_interpret(interpret),
    )(d_t, p_t)
    return unflat_p(p_out.reshape(-1)[:n]), None


def fused_reduce_apply(wire_stack, weights, params, momentum,
                       server_lr: float, server_momentum: float = 0.0,
                       interpret=None):
    """The full stacked-path chain in one pass: ``[K, ...]`` wire
    uploads × combined ``[K]`` weights → Δ̄ → server optimizer → params.

    ``weights`` must already fold in EVERYTHING multiplicative — FedAvg
    example/participation weight, reputation trust, and the reciprocal
    of the weight sum (or krum's one-hot winner row) — so the kernel's
    contraction is the finished aggregate. Returns
    ``(params′, momentum′, mean_delta)``; the delta is emitted as a
    kernel output (one extra tile write) because the client-ledger
    cosine statistic reads the aggregated delta.
    """
    has_mom = momentum is not None
    k = jax.tree.leaves(wire_stack)[0].shape[0]
    flat_s = _flatten_stack(wire_stack, k)  # [K, N]
    flat_p, unflat_p = _flatten_tree(params)
    s_t, n, g = _pad_tiles(flat_s)  # [K, G*_SUB, _LANE]
    p_t = _pad_tiles(flat_p)[0]
    w = weights.astype(jnp.float32).reshape(1, k)
    stack_spec = pl.BlockSpec((k, _SUB, _LANE), lambda i: (0, i, 0))
    w_spec = pl.BlockSpec((1, k), lambda i: (0, 0))
    if has_mom:
        flat_m, unflat_m = _flatten_tree(momentum)
        m_t = _pad_tiles(flat_m)[0]
        kernel = functools.partial(
            _reduce_apply_kernel, lr=float(server_lr),
            beta=float(server_momentum),
        )
        p_out, m_out, d_out = pl.pallas_call(
            kernel, grid=(g,),
            in_specs=[w_spec, stack_spec, _TILE_SPEC, _TILE_SPEC],
            out_specs=[_TILE_SPEC, _TILE_SPEC, _TILE_SPEC],
            out_shape=[_tile_struct(g)] * 3,
            interpret=_interpret(interpret),
        )(w, s_t, p_t, m_t)
        new_mom = unflat_m(m_out.reshape(-1)[:n])
    else:
        def kernel(w_ref, s_ref, p_ref, po_ref, do_ref):
            _reduce_apply_kernel(w_ref, s_ref, p_ref, None, po_ref, None,
                                 do_ref, lr=float(server_lr), beta=0.0)

        p_out, d_out = pl.pallas_call(
            kernel, grid=(g,),
            in_specs=[w_spec, stack_spec, _TILE_SPEC],
            out_specs=[_TILE_SPEC, _TILE_SPEC],
            out_shape=[_tile_struct(g)] * 2,
            interpret=_interpret(interpret),
        )(w, s_t, p_t)
        new_mom = None
    new_params = unflat_p(p_out.reshape(-1)[:n])
    # unflat_p casts per leaf to the params dtypes — exactly the dtype
    # the unfused paths' mean_delta carries (the psum accumulator's)
    mean_delta = unflat_p(d_out.reshape(-1)[:n])
    return new_params, new_mom, mean_delta
