"""Client-update compression (communication-efficiency simulation).

Real cross-device FL compresses each client's model delta before it
leaves the device (uplink is the bottleneck); the simulator applies the
same operator to each client's delta *before* aggregation so compressed
training dynamics — sparsity, quantization noise, their interaction
with server optimizers — are reproduced exactly, even though on TPU the
"network" is the ICI psum. Operators (both classic FL baselines):

- ``topk``  — keep the ``ratio`` largest-magnitude coordinates per
  parameter tensor, zero the rest (Aji & Heafield 2017 style;
  deterministic, biased). Tie rule: threshold at the k-th largest
  |value|, so exact ties at the threshold are all kept.
- ``qsgd``  — stochastic uniform quantization to ``levels`` levels per
  tensor (Alistarh et al. 2017): x → sign(x)·‖x‖₂·ξ/s with
  ξ = ⌊s·|x|/‖x‖₂ + u⌋, u ~ U[0,1). UNBIASED: E[output] = input — the
  property the unit test pins.

Operators act leaf-wise on ``[width, ...]`` blocks of per-client deltas
(one norm / one top-k budget per client per tensor, matching the
per-tensor compression real systems use). All math f32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_compressor(kind: str, topk_ratio: float = 0.01, qsgd_levels: int = 256):
    """Build ``fn(delta_block_tree, client_keys) -> compressed tree`` or None.

    ``delta_block_tree`` leaves are ``[width, ...]`` (a block of clients'
    deltas); ``client_keys`` is the ``[width]`` array of the clients'
    per-round PRNG keys — qsgd derives its dither from them PER CLIENT
    (fold_in with a fixed tag + leaf index), so the result is identical
    no matter how clients are blocked into vmap widths or lanes; topk
    ignores the keys entirely.
    """
    if not kind:
        return None
    if kind == "topk":
        if not 0.0 < topk_ratio <= 1.0:
            raise ValueError(f"topk_ratio must be in (0, 1], got {topk_ratio}")

        def topk(delta, client_keys):
            del client_keys

            def leaf(d):
                flat = d.astype(jnp.float32).reshape(d.shape[0], -1)
                n = flat.shape[1]
                k = max(1, int(round(topk_ratio * n)))
                mag = jnp.abs(flat)
                # exact k-th-largest threshold via full sort — a
                # MEASURED choice, not an oversight (BASELINE.md r4
                # late): swapping lax.top_k in for small k looked 2×
                # faster on the big-leaf microbench but nets only ~6%
                # e2e (3.02 vs 3.20 s/round, ResNet-18 cohort 16, k=1%)
                # while blowing the round program's compile time from
                # ~40 s to ~395 s (60 top_k lowerings); approx_max_k is
                # slower still at FL-sized k. Sort is ratio-independent
                # and compile-cheap.
                thresh = -jnp.sort(-mag, axis=1)[:, k - 1 : k]
                return jnp.where(mag >= thresh, flat, 0.0).reshape(d.shape)

            return jax.tree.map(leaf, delta)

        return topk
    if kind == "qsgd":
        if qsgd_levels < 1:
            raise ValueError(f"qsgd_levels must be >= 1, got {qsgd_levels}")

        def qsgd(delta, client_keys):
            leaves, treedef = jax.tree.flatten(delta)
            out = []
            for i, d in enumerate(leaves):
                flat = d.astype(jnp.float32).reshape(d.shape[0], -1)
                norm = jnp.linalg.norm(flat, axis=1, keepdims=True)
                safe = jnp.maximum(norm, 1e-30)
                scaled = jnp.abs(flat) / safe * qsgd_levels
                # 0x71c is an arbitrary fixed tag separating this stream
                # from the local trainer's per-step key splits
                ks = jax.vmap(
                    lambda ck: jax.random.fold_in(jax.random.fold_in(ck, 0x71C), i)
                )(client_keys)
                u = jax.vmap(
                    lambda kk: jax.random.uniform(kk, flat.shape[1:], jnp.float32)
                )(ks)
                q = jnp.floor(scaled + u)
                out.append(
                    (jnp.sign(flat) * norm * q / qsgd_levels).reshape(d.shape)
                )
            return jax.tree.unflatten(treedef, out)

        return qsgd
    raise ValueError(f"unknown compression kind {kind!r}")


def downlink_quantize(params, key, levels: int):
    """Simulated downlink (server→client) broadcast compression:
    QSGD-style unbiased stochastic quantization of the GLOBAL params.
    One shared dither stream per leaf — the broadcast is one message,
    every client decodes the identical quantized weights (unlike the
    uplink operators, which are per-client). The server's own copy
    stays exact: clients train FROM the quantized weights, their deltas
    are taken against those weights, and the aggregate applies to the
    exact server params — the real comm-constrained system's shape.
    """
    if levels < 1:
        raise ValueError(f"downlink levels must be >= 1, got {levels}")
    leaves, treedef = jax.tree.flatten(params)
    out = []
    for i, p in enumerate(leaves):
        flat = p.astype(jnp.float32).reshape(-1)
        norm = jnp.linalg.norm(flat)
        safe = jnp.maximum(norm, 1e-30)
        scaled = jnp.abs(flat) / safe * levels
        u = jax.random.uniform(
            jax.random.fold_in(key, i), flat.shape, jnp.float32
        )
        q = jnp.floor(scaled + u)
        out.append(
            (jnp.sign(flat) * norm * q / levels).reshape(p.shape).astype(p.dtype)
        )
    return jax.tree.unflatten(treedef, out)
