"""Client-update compression (communication-efficiency simulation).

Real cross-device FL compresses each client's model delta before it
leaves the device (uplink is the bottleneck); the simulator applies the
same operator to each client's delta *before* aggregation so compressed
training dynamics — sparsity, quantization noise, their interaction
with server optimizers — are reproduced exactly, even though on TPU the
"network" is the ICI psum. Operators (both classic FL baselines):

- ``topk``  — keep the ``ratio`` largest-magnitude coordinates per
  parameter tensor, zero the rest (Aji & Heafield 2017 style;
  deterministic, biased). Tie rule: threshold at the k-th largest
  |value|, so exact ties at the threshold are all kept. For leaves of
  at least ``2×_TOPK_SAMPLE`` coordinates (the stride floor — below
  that "sampling" would degenerate to a prefix) the threshold is
  estimated from a strided coordinate subsample (one small sort + an
  O(n) apply) instead of a full sort — see ``_TOPK_SAMPLE`` below for
  the accuracy/cost analysis; ``exact=True`` restores the full sort.
- ``qsgd``  — stochastic uniform quantization to ``levels`` levels per
  tensor (Alistarh et al. 2017): x → sign(x)·‖x‖₂·ξ/s with
  ξ = ⌊s·|x|/‖x‖₂ + u⌋, u ~ U[0,1). UNBIASED: E[output] = input — the
  property the unit test pins.

Operators act leaf-wise on ``[width, ...]`` blocks of per-client deltas
(one norm / one top-k budget per client per tensor, matching the
per-tensor compression real systems use). All math f32.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

_log = logging.getLogger(__name__)

# warn exactly once per process when top-k switches to the sampled-
# quantile approximate threshold (ADVICE r5 #2: the semantics change —
# selected counts within ±10% of k instead of exact — must be
# observable, not silent)
_approx_warned = False


# Coordinate-subsample size for the estimated top-k threshold. The
# selected-count error of a sample-quantile threshold concentrates as
# count/k ≈ 1 ± z·sqrt((1-r)/(r·m)) (binomial tail over m draws at keep
# ratio r): at m=65536, r=0.01 that is ±7.8% at 2σ, r=0.1 ±2.3% — inside
# the ±10% band the regression test pins. Chosen over the measured-and-
# rejected alternatives (BASELINE.md r4 late: lax.top_k compiles 60
# lowerings → ~395 s; approx_max_k slower at FL-sized k; full sort costs
# 10× the training step it compresses): ONE [width, 65536] sort replaces
# the [width, n] sort (n up to 2.36M/leaf on ResNet-18) and the apply
# stays a single O(n) elementwise pass.
_TOPK_SAMPLE = 65536


def make_compressor(kind: str, topk_ratio: float = 0.01, qsgd_levels: int = 256,
                    topk_exact: bool = False):
    """Build ``fn(delta_block_tree, client_keys) -> compressed tree`` or None.

    ``delta_block_tree`` leaves are ``[width, ...]`` (a block of clients'
    deltas); ``client_keys`` is the ``[width]`` array of the clients'
    per-round PRNG keys — qsgd derives its dither from them PER CLIENT
    (fold_in with a fixed tag + leaf index), so the result is identical
    no matter how clients are blocked into vmap widths or lanes; topk
    (including its strided threshold sample) ignores the keys entirely,
    so the same invariance holds trivially.
    """
    if not kind:
        return None
    if kind == "topk":
        if not 0.0 < topk_ratio <= 1.0:
            raise ValueError(f"topk_ratio must be in (0, 1], got {topk_ratio}")

        def topk(delta, client_keys):
            leaves, treedef = jax.tree.flatten(delta)
            out = []
            for i, d in enumerate(leaves):
                flat = d.astype(jnp.float32).reshape(d.shape[0], -1)
                n = flat.shape[1]
                k = max(1, int(round(topk_ratio * n)))
                mag = jnp.abs(flat)
                if k == n:
                    # ratio 1.0 (or tiny leaf): keep everything — the
                    # sampled threshold must never drop coordinates here
                    out.append(flat.reshape(d.shape))
                    continue
                if topk_exact or n < 2 * _TOPK_SAMPLE:
                    # exact k-th-largest threshold via full sort: always
                    # for leaves below TWICE the sample size — under 2×,
                    # stride = n // m floors to 1 and "sampling" would
                    # silently degenerate to the leaf's PREFIX (worst
                    # case for position-structured deltas); a ≤131k sort
                    # is cheap anyway, and exactness keeps the
                    # small-model test oracles bitwise
                    thresh = -jnp.sort(-mag, axis=1)[:, k - 1 : k]
                else:
                    global _approx_warned
                    if not _approx_warned:
                        _approx_warned = True
                        _log.warning(
                            "topk compression: leaf with %d coords >= %d "
                            "uses the sampled-quantile APPROXIMATE "
                            "threshold (selected count within ~±10%% of "
                            "k, worse if |delta| has stride-aligned "
                            "periodic structure); set "
                            "server.compression_topk_exact=true for the "
                            "exact full-sort threshold",
                            n, 2 * _TOPK_SAMPLE,
                        )
                    # estimated threshold: the (m·k/n)-th largest of a
                    # STRIDED coordinate sample. Strided (not random-
                    # gather) is a measured choice: a 65k random gather
                    # per client-row costs ~0.32 s/leaf on ResNet-18's
                    # big convs (random HBM access; stratified and
                    # rolled variants lower to the same gather) vs
                    # 0.037 s — the elementwise floor — for the slice.
                    # Caveat: systematic sampling of one residue class
                    # biases the estimate iff |Δ| has periodic structure
                    # aligned with the stride; the per-leaf offset
                    # decorrelates leaves, EF retries any starved
                    # coordinates, and `topk_exact` remains for the
                    # paranoid. Count accuracy pinned within ±10% of k.
                    m = _TOPK_SAMPLE
                    k_s = max(1, int(round(m * (k / n))))
                    stride = n // m
                    off = (i * 2654435761) % stride  # Knuth-hash offset
                    samp = mag[:, off::stride][:, :m]
                    thresh = -jnp.sort(-samp, axis=1)[:, k_s - 1 : k_s]
                out.append(
                    jnp.where(mag >= thresh, flat, 0.0).reshape(d.shape))
            return jax.tree.unflatten(treedef, out)

        return topk
    if kind == "qsgd":
        if qsgd_levels < 1:
            raise ValueError(f"qsgd_levels must be >= 1, got {qsgd_levels}")

        def qsgd(delta, client_keys):
            leaves, treedef = jax.tree.flatten(delta)
            out = []
            for i, d in enumerate(leaves):
                flat = d.astype(jnp.float32).reshape(d.shape[0], -1)
                norm = jnp.linalg.norm(flat, axis=1, keepdims=True)
                safe = jnp.maximum(norm, 1e-30)
                scaled = jnp.abs(flat) / safe * qsgd_levels
                # 0x71c is an arbitrary fixed tag separating this stream
                # from the local trainer's per-step key splits
                ks = jax.vmap(
                    lambda ck: jax.random.fold_in(jax.random.fold_in(ck, 0x71C), i)
                )(client_keys)
                u = jax.vmap(
                    lambda kk: jax.random.uniform(kk, flat.shape[1:], jnp.float32)
                )(ks)
                q = jnp.floor(scaled + u)
                out.append(
                    (jnp.sign(flat) * norm * q / qsgd_levels).reshape(d.shape)
                )
            return jax.tree.unflatten(treedef, out)

        return qsgd
    raise ValueError(f"unknown compression kind {kind!r}")


def downlink_quantize(params, key, levels: int):
    """Simulated downlink (server→client) broadcast compression:
    QSGD-style unbiased stochastic quantization of the GLOBAL params.
    One shared dither stream per leaf — the broadcast is one message,
    every client decodes the identical quantized weights (unlike the
    uplink operators, which are per-client). The server's own copy
    stays exact: clients train FROM the quantized weights, their deltas
    are taken against those weights, and the aggregate applies to the
    exact server params — the real comm-constrained system's shape.
    """
    if levels < 1:
        raise ValueError(f"downlink levels must be >= 1, got {levels}")
    leaves, treedef = jax.tree.flatten(params)
    out = []
    for i, p in enumerate(leaves):
        flat = p.astype(jnp.float32).reshape(-1)
        norm = jnp.linalg.norm(flat)
        safe = jnp.maximum(norm, 1e-30)
        scaled = jnp.abs(flat) / safe * levels
        u = jax.random.uniform(
            jax.random.fold_in(key, i), flat.shape, jnp.float32
        )
        q = jnp.floor(scaled + u)
        out.append(
            (jnp.sign(flat) * norm * q / levels).reshape(p.shape).astype(p.dtype)
        )
    return jax.tree.unflatten(treedef, out)
