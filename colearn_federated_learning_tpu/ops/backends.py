"""Attention-backend registry shared by the transformer models.

One name→callable mapping so BERT-tiny and ViT dispatch identically and
a new backend (or kwarg) lands in exactly one place. All backends are
exact; they differ in memory/communication shape:

- ``full``      — T×T scores on one chip (XLA-fused; fastest at short T)
- ``blockwise`` — flash-style online-softmax scan of k/v blocks from HBM;
                  O(T·block) memory (single-chip long-context)
- ``pallas``    — the blockwise recurrence as a hand-tiled pallas TPU
                  kernel (ops/pallas_attention.py); interpret mode off-TPU
- ``ring``      — sequence-parallel over the "seq" mesh axis via ppermute
                  (ops/ring_attention.py); only valid inside
                  parallel/sequence.py's shard_map wrapper
- ``ulysses``   — sequence-parallel via two all_to_alls (heads sharded
                  during attention, DeepSpeed-Ulysses recipe); same
                  shard_map requirement as ``ring``; needs heads
                  divisible by the seq-axis size
"""

from __future__ import annotations

from functools import partial

from colearn_federated_learning_tpu.ops.attention import (
    causal_attention,
    full_attention,
)

_ALL = ("full", "blockwise", "pallas", "ring", "ulysses")


def resolve_attention(name: str, *, causal: bool, block_size: int = 128,
                      supported=_ALL):
    """(q, k, v, heads) → out callable for a backend name."""
    if name not in supported:
        raise ValueError(
            f"unknown attention backend {name!r}; supported: {list(supported)}"
        )
    if name == "full":
        return causal_attention if causal else full_attention
    if name == "blockwise":
        from colearn_federated_learning_tpu.ops.ring_attention import (
            blockwise_attention,
        )

        return partial(blockwise_attention, block_size=block_size, causal=causal)
    if name == "pallas":
        from colearn_federated_learning_tpu.ops.pallas_attention import (
            flash_attention,
        )

        return partial(flash_attention, causal=causal,
                       block_q=block_size, block_kv=block_size)
    if name == "ulysses":
        from colearn_federated_learning_tpu.ops.ring_attention import (
            ulysses_attention,
        )

        return partial(ulysses_attention, axis_name="seq", causal=causal)
    from colearn_federated_learning_tpu.ops.ring_attention import ring_attention

    return partial(ring_attention, axis_name="seq", causal=causal)
