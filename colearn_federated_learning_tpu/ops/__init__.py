"""Hot-path ops: attention entry points and (later rounds) pallas kernels."""
