"""Fused flash-attention as a pallas TPU kernel.

The hot op of the transformer configs (BERT-tiny LM, ViT silo) as a
hand-tiled kernel instead of XLA's default fusion: one grid step owns a
``[block_q, head_dim]`` query tile in VMEM and streams key/value blocks
through the online-softmax recurrence (the same math as
``ops.ring_attention.blockwise_attention``) without ever materializing
the T×T score matrix in HBM. Scores and accumulators stay in f32 on the
MXU (``preferred_element_type``), inputs may be bf16.

Causality is exploited at the *grid* level: query tile ``i`` runs its
k/v loop only up to block ``i`` — for long sequences this halves the
work, which XLA's fused-but-dense attention does not do.

Backward: rematerialized through the XLA blockwise implementation via
``jax.custom_vjp`` — the forward value comes from the kernel, gradients
from re-running the mathematically identical online-softmax in XLA (the
standard remat trade: no T×T residuals saved, +1 recompute).

Model opt-in: ``build_model("bert_tiny", attention="pallas")``. On
non-TPU backends the kernel runs in pallas interpret mode (exact, slow)
so CPU tests cover the real kernel code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from colearn_federated_learning_tpu.ops.ring_attention import (
    _merge_heads,
    _split_heads,
    blockwise_attention,
)

_NEG_BIG = -1e30


def _out_shape_struct(shape, dtype, inputs):
    """``ShapeDtypeStruct`` for the kernel output, carrying the union of
    the inputs' vma sets on vma-aware jax; plain shape/dtype on pre-vma
    jax (no ``vma=`` kwarg there, and no checker for it to inform)."""
    vma = frozenset().union(*(
        getattr(jax.typeof(x), "vma", frozenset()) for x in inputs
    ))
    if not vma:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_kv: int,
                 n_kv: int, kv_len: int, causal: bool, scale: float):
    # kv_len: number of REAL keys (< padded length when the sequence was
    # padded up to a block multiple); keys past it are masked out. Real
    # causal queries never see padded keys (q_pos < kv_len ⇒ k_pos ≤
    # q_pos < kv_len), and padded query rows are sliced off outside.
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, hd]
    hd = q.shape[-1]
    padded = kv_len < n_kv * block_kv
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0
    )

    def body(j, carry):
        acc, m, l = carry
        k_blk = k_ref[0, pl.ds(j * block_kv, block_kv), :]  # [block_kv, hd]
        v_blk = v_ref[0, pl.ds(j * block_kv, block_kv), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_kv]
        keep = None
        if causal or padded:
            k_pos = j * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1
            )
            keep = q_pos >= k_pos if causal else k_pos < kv_len
            if causal and padded:
                keep = keep & (k_pos < kv_len)
            s = jnp.where(keep, s, _NEG_BIG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc_new, m_new, l_new

    # causal: query tile qi only attends to k/v blocks overlapping
    # positions ≤ (qi+1)·block_q — skip the rest at the loop bound
    if causal:
        upper = pl.cdiv((qi + 1) * block_q, block_kv)
    else:
        upper = n_kv
    acc0 = jnp.zeros((block_q, hd), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, heads: int, causal: bool, block_q: int,
                    block_kv: int, interpret):
    qh = _split_heads(q, heads)  # [B, H, T, hd]
    kh = _split_heads(k, heads)
    vh = _split_heads(v, heads)
    b, h, t, hd = qh.shape
    bq = min(block_q, t)
    bkv = min(block_kv, t)
    # Non-divisible sequence lengths (e.g. ViT's 197 tokens) are padded up
    # to a block multiple; padded keys are masked inside the kernel via
    # kv_len and padded query rows are sliced off below. When padding is
    # needed both block sizes collapse to the smaller one so the pad is
    # bounded by one block — lcm of clamped ragged blocks (e.g. 50 and 32)
    # could otherwise blow the sequence up many-fold.
    if t % bq or t % bkv:
        bq = bkv = min(bq, bkv)
    tp = ((t + bkv - 1) // bkv) * bkv
    if tp != t:
        pad = [(0, 0), (0, 0), (0, tp - t), (0, 0)]
        qh, kh, vh = (jnp.pad(x, pad) for x in (qh, kh, vh))
    qh = qh.reshape(b * h, tp, hd)
    kh = kh.reshape(b * h, tp, hd)
    vh = vh.reshape(b * h, tp, hd)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    kernel = functools.partial(
        _attn_kernel, block_q=bq, block_kv=bkv, n_kv=tp // bkv, kv_len=t,
        causal=causal, scale=hd**-0.5,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, tp // bq),
        in_specs=[
            pl.BlockSpec((1, bq, hd), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, tp, hd), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, tp, hd), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, hd), lambda bh, i: (bh, i, 0)),
        # Inside shard_map (the round engine's clients mesh) inputs are
        # device-varying; the kernel output varies the same way, and
        # shard_map's vma checker requires that stated explicitly.
        # (Pre-vma jax has no `vma=` kwarg and no checker to inform —
        # only pass it when the inputs actually carry a vma set.)
        out_shape=_out_shape_struct((b * h, tp, hd), q.dtype,
                                    (qh, kh, vh)),
        interpret=interpret,
    )(qh, kh, vh)
    return _merge_heads(out.reshape(b, h, tp, hd)[:, :, :t])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, heads: int, causal: bool = True,
                    block_q: int = 128, block_kv: int = 128, interpret=None):
    """[B, T, D] packed q/k/v → [B, T, D]; pallas-fused forward."""
    return _flash_fwd_impl(q, k, v, heads, causal, block_q, block_kv, interpret)


def _flash_fwd(q, k, v, heads, causal, block_q, block_kv, interpret):
    out = _flash_fwd_impl(q, k, v, heads, causal, block_q, block_kv, interpret)
    return out, (q, k, v)


def _flash_bwd(heads, causal, block_q, block_kv, interpret, residuals, g):
    q, k, v = residuals
    t = q.shape[1]
    block = min(block_q, block_kv, t)
    if t % block == 0:
        # long-context path: O(T·block) memory recompute
        def ref(q_, k_, v_):
            return blockwise_attention(q_, k_, v_, heads, block_size=block,
                                       causal=causal)

        _, vjp = jax.vjp(ref, q, k, v)
        return vjp(g)
    if causal:
        # Non-divisible causal lengths keep the O(T·block) recompute by
        # zero-padding to a block multiple: padded keys sit at positions
        # ≥ t so no real query attends them, and the padded query rows'
        # cotangents are zero, so sliced gradients are exact.
        tp = ((t + block - 1) // block) * block
        pad = [(0, 0), (0, tp - t), (0, 0)]
        qp, kp, vp = (jnp.pad(x, pad) for x in (q, k, v))
        gp = jnp.pad(g, pad)

        def ref(q_, k_, v_):
            return blockwise_attention(q_, k_, v_, heads, block_size=block,
                                       causal=True)

        _, vjp = jax.vjp(ref, qp, kp, vp)
        return tuple(x[:, :t] for x in vjp(gp))
    # Non-causal non-divisible (ViT's 197 tokens): zero-padded keys WOULD
    # attract real attention weight, so recompute with plain attention —
    # T×T scores are fine at the scales where such lengths occur.
    from colearn_federated_learning_tpu.ops.attention import full_attention

    def ref(q_, k_, v_):
        return full_attention(q_, k_, v_, heads)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
