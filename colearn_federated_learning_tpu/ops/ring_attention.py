"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support (first-class per the project brief, even though the
reference's own models are short-sequence — SURVEY.md §5 records that):
queries stay put, key/value blocks rotate around the ring of devices via
``jax.lax.ppermute`` while a blockwise online-softmax (the flash-
attention recurrence) accumulates exact results — memory per device is
O(T/n) with no T×T materialization, and the rotation rides the ICI.

Liu et al. 2023 (Ring Attention with Blockwise Transformers) is the
published recipe; this is an independent implementation on
``shard_map``/``ppermute``.

Intended use: inside ``jax.shard_map`` with the sequence axis sharded
over ``axis_name``, e.g.::

    mesh = Mesh(devices, ("seq",))
    attn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, heads, "seq", causal=True),
        mesh=mesh, in_specs=P(None, "seq", None), out_specs=P(None, "seq", None),
    )

``parallel/sequence.py`` wires this into a full transformer forward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_BIG = -1e30  # finite stand-in for -inf: keeps the online softmax NaN-free


def _split_heads(x, heads: int):
    b, t, d = x.shape
    return x.reshape(b, t, heads, d // heads).transpose(0, 2, 1, 3)  # [B,H,T,hd]


def _merge_heads(x):
    b, h, t, hd = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * hd)


def ring_attention(q, k, v, heads: int, axis_name: str, causal: bool = True):
    """Exact multi-head attention with q/k/v sequence-sharded on ``axis_name``.

    q, k, v: [B, T_local, D] (this device's sequence block).
    Returns [B, T_local, D] — identical (up to float reassociation) to
    full attention over the gathered sequence.
    """
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    qh = _split_heads(q, heads)
    kh = _split_heads(k, heads)
    vh = _split_heads(v, heads)
    b, h, t_loc, hd = qh.shape
    scale = hd**-0.5
    # All recurrence math in f32: bf16 einsums inside the scan backward
    # miscompile to NaN on this TPU backend (values are fine in isolation
    # but not when fused into a larger differentiated graph — see
    # tests/test_pallas_attention.py::test_bf16_lm_gradients_finite).
    # k/v stay in their wire dtype for the ppermute (half the ICI bytes)
    # and are upcast per-use.
    qh = qh.astype(jnp.float32) * scale
    q_pos = me * t_loc + jnp.arange(t_loc)  # global positions of our queries

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, step):
        o, m, l, kh_cur, vh_cur = carry
        # the block we currently hold originated at lane (me - step) mod n
        src = jax.lax.rem(me - step + n, n)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh_cur.astype(jnp.float32))
        if causal:
            k_pos = src * t_loc + jnp.arange(t_loc)
            keep = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
            s = jnp.where(keep[None, None], s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(-1))
        # correction for previously accumulated numerator/denominator
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(keep[None, None], p, 0.0)  # kill exp(0) on dead rows
        l_new = l * corr + p.sum(-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vh_cur.astype(jnp.float32)
        )
        kh_next = jax.lax.ppermute(kh_cur, axis_name, perm)
        vh_next = jax.lax.ppermute(vh_cur, axis_name, perm)
        return (o_new, m_new, l_new, kh_next, vh_next), None

    # +0·Σq ties the accumulators' device-varying type to the data, so the
    # scan carry type-checks inside any enclosing shard_map (seq-sharded
    # here, and also the clients axis when nested in the round engine)
    zero = (0.0 * qh.sum()).astype(jnp.float32)
    o0 = jnp.zeros((b, h, t_loc, hd), jnp.float32) + zero
    m0 = jnp.full((b, h, t_loc), _NEG_BIG, jnp.float32) + zero
    l0 = jnp.zeros((b, h, t_loc), jnp.float32) + zero
    (o, m, l, _, _), _ = jax.lax.scan(
        body, (o0, m0, l0, kh, vh), jnp.arange(n)
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return _merge_heads(out.astype(q.dtype))


def ulysses_attention(q, k, v, heads: int, axis_name: str, causal: bool = True):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses recipe, Jacobs
    et al. 2023; independent implementation on ``shard_map``): one
    ``all_to_all`` re-shards q/k/v from sequence-sharded ``[B, T/n, D]``
    to head-sharded ``[B, H/n, T, hd]``, each device computes FULL-
    sequence attention for its H/n heads, and a second ``all_to_all``
    swaps back. Exact. Communication is two all-to-alls riding the ICI
    instead of the ring's n−1 ppermute hops — the better trade when T×T
    scores fit per device and latency (not memory) binds; the ring stays
    the O(T/n)-memory option for extreme T. Requires ``heads % n == 0``.
    """
    n = jax.lax.axis_size(axis_name)
    if heads % n:
        raise ValueError(f"ulysses needs heads ({heads}) divisible by "
                         f"{n} seq lanes")
    qh = _split_heads(q, heads)  # [B, H, T/n, hd]
    kh = _split_heads(k, heads)
    vh = _split_heads(v, heads)

    def to_heads(x):  # heads → sharded, sequence → gathered
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qf, kf, vf = to_heads(qh), to_heads(kh), to_heads(vh)
    b, hn, t, hd = qf.shape
    # f32 score/softmax math — same backend NaN workaround as the ring
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", qf.astype(jnp.float32) * hd**-0.5,
        kf.astype(jnp.float32),
    )
    if causal:
        pos = jnp.arange(t)
        keep = pos[:, None] >= pos[None, :]
        s = jnp.where(keep[None, None], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    of = jnp.einsum("bhqk,bhkd->bhqd", p, vf.astype(jnp.float32))
    out = jax.lax.all_to_all(  # sequence → sharded, heads → gathered
        of.astype(q.dtype), axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    return _merge_heads(out)


def blockwise_attention(q, k, v, heads: int, block_size: int, causal: bool = True):
    """Single-device blockwise (flash-style) attention: same online-softmax
    recurrence as the ring, scanning k/v blocks from HBM instead of the
    ICI. Exact; O(T·block) memory. Used for long sequences on one chip
    and as the numerics oracle for the ring version."""
    qh = _split_heads(q, heads)
    kh = _split_heads(k, heads)
    vh = _split_heads(v, heads)
    b, h, t, hd = qh.shape
    if t % block_size:
        raise ValueError(
            f"blockwise_attention requires the sequence length to be a "
            f"block_size multiple, got t={t}, block_size={block_size}"
        )
    n_blocks = t // block_size
    scale = hd**-0.5
    # f32 recurrence math — same backend NaN workaround as ring_attention.
    # k/v keep their storage dtype in the scan xs (no full-sequence f32
    # copy on the memory-savings path) and are upcast per block.
    qh = qh.astype(jnp.float32) * scale
    kb = kh.reshape(b, h, n_blocks, block_size, hd)
    vb = vh.reshape(b, h, n_blocks, block_size, hd)
    q_pos = jnp.arange(t)

    def body(carry, blk):
        o, m, l = carry
        k_blk, v_blk, blk_idx = blk
        k_blk = k_blk.astype(jnp.float32)
        v_blk = v_blk.astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, k_blk)
        if causal:
            k_pos = blk_idx * block_size + jnp.arange(block_size)
            keep = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(keep[None, None], s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(keep[None, None], p, 0.0)
        l_new = l * corr + p.sum(-1)
        o_new = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        return (o_new, m_new, l_new), None

    zero = (0.0 * qh.sum()).astype(jnp.float32)  # see ring_attention
    o0 = jnp.zeros((b, h, t, hd), jnp.float32) + zero
    m0 = jnp.full((b, h, t), _NEG_BIG, jnp.float32) + zero
    l0 = jnp.zeros((b, h, t), jnp.float32) + zero
    (o, m, l), _ = jax.lax.scan(
        body,
        (o0, m0, l0),
        (kb.transpose(2, 0, 1, 3, 4), vb.transpose(2, 0, 1, 3, 4), jnp.arange(n_blocks)),
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return _merge_heads(out.astype(q.dtype))
