"""Attention ops.

``causal_attention`` is the single entry point every transformer in the
zoo calls, so swapping in a fused pallas kernel or a ring/sequence-
parallel variant for long-context configs is a one-site change. The
default is plain XLA attention — at BERT-tiny/ViT scale XLA's fusion is
already near-roofline, and SURVEY.md §5 records long-context sequence
parallelism as out of scope for the reference's capability surface.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.nn import softmax


def _split_heads(x, heads: int):
    b, t, d = x.shape
    return x.reshape(b, t, heads, d // heads).transpose(0, 2, 1, 3)  # [B,H,T,hd]


def _merge_heads(x):
    b, h, t, hd = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * hd)


def multihead_attention(q, k, v, heads: int, mask=None):
    """[B,T,D] q/k/v → [B,T,D]; mask broadcastable to [B,H,T,T] (True=keep)."""
    q, k, v = _split_heads(q, heads), _split_heads(k, heads), _split_heads(v, heads)
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q * scale, k)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
    att = softmax(scores.astype(jnp.float32), axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", att, v)
    return _merge_heads(out)


def causal_attention(q, k, v, heads: int):
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))[None, None, :, :]
    return multihead_attention(q, k, v, heads, mask)


def full_attention(q, k, v, heads: int):
    return multihead_attention(q, k, v, heads, None)
