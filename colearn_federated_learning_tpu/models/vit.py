"""ViT-B/16 for federated ImageNet (BASELINE config #5, BASELINE.json:11).

Standard ViT-Base/16: 12 layers, hidden 768, 12 heads, MLP 3072, CLS
token, learned positional embeddings. Patchify is a strided Conv (maps
straight onto the MXU). LayerNorm params are pure pytree leaves, so the
cross-silo FedAvg/DP path aggregates everything uniformly.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from colearn_federated_learning_tpu.models import _INPUT_SPECS, model_registry
from colearn_federated_learning_tpu.ops.attention import full_attention
from colearn_federated_learning_tpu.ops.backends import resolve_attention


class ViTBlock(nn.Module):
    hidden: int
    heads: int
    mlp_dim: int
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    attention_fn: Callable = full_attention  # (q, k, v, heads) → out

    @nn.compact
    def __call__(self, x):
        dense = partial(nn.Dense, dtype=self.compute_dtype, param_dtype=self.param_dtype)
        ln = partial(nn.LayerNorm, dtype=self.compute_dtype, param_dtype=self.param_dtype)
        h = ln()(x)
        qkv = dense(3 * self.hidden)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        att = self.attention_fn(q, k, v, self.heads)
        x = x + dense(self.hidden)(att)
        h = ln()(x)
        h = nn.gelu(dense(self.mlp_dim)(h))
        x = x + dense(self.hidden)(h)
        return x


class ViT(nn.Module):
    num_classes: int = 1000
    image_size: int = 224
    patch_size: int = 16
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    mlp_dim: int = 3072
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    attention_fn: Callable = full_attention

    @nn.compact
    def __call__(self, x, train: bool = False):
        if x.shape[1] != self.image_size or x.shape[2] != self.image_size:
            raise ValueError(
                f"ViT(image_size={self.image_size}) got input {x.shape[1:3]}; "
                "config geometry and data geometry must agree"
            )
        x = x.astype(self.compute_dtype)
        x = nn.Conv(self.hidden, (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    padding="VALID", dtype=self.compute_dtype,
                    param_dtype=self.param_dtype)(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.hidden),
                         self.param_dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, self.hidden)).astype(x.dtype), x], axis=1)
        pos = self.param("pos_embedding", nn.initializers.normal(0.02),
                         (1, x.shape[1], self.hidden), self.param_dtype)
        x = x + pos.astype(x.dtype)
        for _ in range(self.layers):
            x = ViTBlock(self.hidden, self.heads, self.mlp_dim,
                         self.compute_dtype, self.param_dtype,
                         self.attention_fn)(x)
        x = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=self.param_dtype)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=self.param_dtype)(x[:, 0])


@model_registry.register("vit_b16")
def _build(num_classes: int = 1000, image_size: int = 224, patch_size: int = 16,
           hidden: int = 768, layers: int = 12, heads: int = 12, mlp_dim: int = 3072,
           attention: str = "full", block_size: int = 128,
           compute_dtype=jnp.float32, param_dtype=jnp.float32, **_):
    # geometry kwargs are overridable so tests/small studies can shrink the
    # model while exercising the identical DP+silo code path.
    # attention="pallas" uses the fused kernel; the 197-token sequence is
    # padded to a block multiple with masked keys inside the kernel.
    # (blockwise/ring are causal-oriented and need divisible T — not
    # offered here.)
    attn = resolve_attention(attention, causal=False, block_size=block_size,
                             supported=("full", "pallas"))
    return ViT(num_classes=num_classes, image_size=image_size, patch_size=patch_size,
               hidden=hidden, layers=layers, heads=heads, mlp_dim=mlp_dim,
               attention_fn=attn,
               compute_dtype=compute_dtype, param_dtype=param_dtype)


def _vit_spec(image_size: int = 224, **_):
    return (image_size, image_size, 3), jnp.float32


_INPUT_SPECS["vit_b16"] = _vit_spec
