"""Stacked character LSTM — the canonical LEAF Shakespeare model.

The LEAF benchmark (Caldas et al. 2018, "LEAF: A Benchmark for
Federated Settings") models Shakespeare next-character prediction with
an embedding + 2-layer stacked LSTM (hidden 256); the spec frame's
Shakespeare row (BASELINE.json:10, SURVEY.md §2 C9 — the reference
mount is empty, so citations point at the spec) is covered by
``bert_tiny`` as the judged config, and this module adds the classic
recurrent architecture so LEAF users find the model they expect.

TPU-idiomatic: the time recurrence is ``flax.linen.RNN`` —
``nn.scan``-lifted ``OptimizedLSTMCell`` (fused gate matmuls: one
[E+H → 4H] contraction per step hits the MXU once instead of four
sliver matmuls), compiled as a single ``lax.scan`` with static length.
An LSTM is inherently causal, so next-token prediction needs no mask;
sequence parallelism does NOT apply to recurrence (the carry is a
serial dependency — ring/Ulysses attention models are the long-context
path, SURVEY.md §5), which is why this model takes no ``pos_offset``.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from colearn_federated_learning_tpu.models import _INPUT_SPECS, model_registry


class StackedCharLSTM(nn.Module):
    vocab_size: int = 90
    embed_dim: int = 8
    hidden: int = 256
    layers: int = 2
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        # tokens: [B, T] int32 → logits [B, T, V] (next-token prediction)
        del train  # no dropout in the LEAF reference architecture
        x = nn.Embed(
            self.vocab_size, self.embed_dim,
            embedding_init=nn.initializers.normal(0.02),
            param_dtype=self.param_dtype,
        )(tokens).astype(self.compute_dtype)
        for _ in range(self.layers):
            # vma discipline: under the engine's shard_map the scan
            # carry must match the device-VARYING activations, but a
            # fresh zeros carry is device-invariant and the lifted scan
            # rejects the mismatch — tie the initial carry to x with an
            # exact-zero mix (same trick as the engine's _pcast_varying,
            # expressed without axis names so the model stays
            # context-free)
            zero = jnp.zeros(
                (x.shape[0], self.hidden), self.compute_dtype
            ) + 0.0 * x[:, 0, :1]
            x = nn.RNN(
                nn.OptimizedLSTMCell(
                    self.hidden, dtype=self.compute_dtype,
                    param_dtype=self.param_dtype,
                )
            )(x, initial_carry=(zero, zero))
        # logits in f32 for stable cross-entropy (zoo-wide invariant)
        return nn.Dense(
            self.vocab_size, dtype=self.compute_dtype,
            param_dtype=self.param_dtype,
        )(x).astype(jnp.float32)


@model_registry.register("stacked_lstm")
def _build(num_classes: int = 0, vocab_size: int = 90, seq_len: int = 80,
           embed_dim: int = 8, hidden: int = 256, layers: int = 2,
           compute_dtype=jnp.float32, param_dtype=jnp.float32, **_):
    del num_classes, seq_len  # LM: output dim == vocab_size; length-free
    return StackedCharLSTM(
        vocab_size=vocab_size, embed_dim=embed_dim, hidden=hidden,
        layers=layers, compute_dtype=compute_dtype, param_dtype=param_dtype,
    )


def _lm_spec(vocab_size: int = 90, seq_len: int = 80, **_):
    return (seq_len,), jnp.int32


_INPUT_SPECS["stacked_lstm"] = _lm_spec
