"""MobileNetV2 for FEMNIST (BASELINE config #3, BASELINE.json:9).

Inverted-residual bottlenecks with GroupNorm (same FL/functional
rationale as resnet.py). FEMNIST is 28×28 grayscale with 62 classes; the
stem stride adapts to small inputs so the net doesn't collapse spatial
dims to zero.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from colearn_federated_learning_tpu.models import _INPUT_SPECS, model_registry


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _gn(ch: int, dtype, param_dtype=jnp.float32):
    # group count must divide channels; channels here are multiples of 8
    return nn.GroupNorm(num_groups=min(8, ch), dtype=dtype, param_dtype=param_dtype)


class InvertedResidual(nn.Module):
    filters: int
    strides: int
    expand: int
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       param_dtype=self.param_dtype)
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        y = x
        if self.expand != 1:
            y = conv(hidden, (1, 1))(y)
            y = nn.relu6(_gn(hidden, self.compute_dtype, self.param_dtype)(y))
        # depthwise
        y = conv(hidden, (3, 3), strides=(self.strides, self.strides),
                 padding="SAME", feature_group_count=hidden)(y)
        y = nn.relu6(_gn(hidden, self.compute_dtype, self.param_dtype)(y))
        y = conv(self.filters, (1, 1))(y)
        y = _gn(self.filters, self.compute_dtype, self.param_dtype)(y)
        if self.strides == 1 and in_ch == self.filters:
            y = y + x
        return y


class MobileNetV2(nn.Module):
    num_classes: int = 62
    width_mult: float = 1.0
    small_inputs: bool = True
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # (expand, filters, repeats, stride)
    blocks: Sequence[Tuple[int, int, int, int]] = (
        (1, 16, 1, 1),
        (6, 24, 2, 2),
        (6, 32, 3, 2),
        (6, 64, 4, 2),
        (6, 96, 3, 1),
        (6, 160, 3, 2),
        (6, 320, 1, 1),
    )

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.compute_dtype)
        stem_stride = 1 if self.small_inputs else 2
        ch = _make_divisible(32 * self.width_mult)
        x = nn.Conv(ch, (3, 3), strides=(stem_stride, stem_stride), padding="SAME",
                    use_bias=False, dtype=self.compute_dtype,
                    param_dtype=self.param_dtype)(x)
        x = nn.relu6(_gn(ch, self.compute_dtype, self.param_dtype)(x))
        for i, (t, c, n, s) in enumerate(self.blocks):
            filters = _make_divisible(c * self.width_mult)
            for b in range(n):
                stride = s if b == 0 else 1
                # avoid over-striding 28×28 inputs: drop the last two downsamples
                if self.small_inputs and i >= 5:
                    stride = 1
                x = InvertedResidual(filters, stride, t, self.compute_dtype, self.param_dtype)(x)
        head = _make_divisible(1280 * max(1.0, self.width_mult))
        x = nn.Conv(head, (1, 1), use_bias=False, dtype=self.compute_dtype,
                    param_dtype=self.param_dtype)(x)
        x = nn.relu6(_gn(head, self.compute_dtype, self.param_dtype)(x))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=self.param_dtype)(x)


@model_registry.register("mobilenetv2")
def _build(num_classes: int = 62, width_mult: float = 1.0, small_inputs: bool = True,
           compute_dtype=jnp.float32, param_dtype=jnp.float32, **_):
    return MobileNetV2(num_classes=num_classes, width_mult=width_mult,
                       small_inputs=small_inputs, compute_dtype=compute_dtype,
                       param_dtype=param_dtype)


_INPUT_SPECS["mobilenetv2"] = ((28, 28, 1), jnp.float32)
