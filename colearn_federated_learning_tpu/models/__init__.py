"""Model zoo (SURVEY.md §2 C9, layer L0a).

Capability parity targets (BASELINE.json:7-11): LeNet-5, ResNet-18,
MobileNetV2, BERT-tiny (causal LM), ViT-B/16. All are ``flax.linen``
modules with pure-pytree params so FedAvg's weighted-sum is plain tree
arithmetic, and all use static shapes + GroupNorm-style normalization
(no batch statistics crossing client boundaries — BatchNorm is both bad
FL practice and a running-stats headache for functional aggregation).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from colearn_federated_learning_tpu.utils.registry import Registry

model_registry = Registry("model")


def _allowed_kwargs(factory) -> set:
    """Named parameters of a zoo factory (its real knob surface — every
    builder also takes a ``**_`` sink so shared driver kwargs like
    ``compute_dtype`` flow everywhere, which is exactly why a TYPO'd
    kwarg used to vanish silently and surface as a shape error deep in
    Flax init)."""
    import inspect

    return {
        p.name
        for p in inspect.signature(factory).parameters.values()
        if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)
    }


def build_model(name: str, num_classes: int, **kwargs):
    """Instantiate a model module from the zoo.

    Unknown ``name`` and unknown ``kwargs`` both raise a ValueError
    naming the allowed set — a config typo fails at construction with
    the fix in the message, not minutes later inside Flax init."""
    try:
        factory = model_registry.get(name)
    except KeyError:
        raise ValueError(
            f"unknown model.name {name!r}; known models: "
            f"{', '.join(model_registry.names())}"
        ) from None
    unknown = set(kwargs) - _allowed_kwargs(factory)
    if unknown:
        allowed = sorted(
            _allowed_kwargs(factory) - {"num_classes"}
        )
        raise ValueError(
            f"unknown model.kwargs for {name!r}: "
            f"{', '.join(sorted(unknown))}; allowed kwargs: "
            f"{', '.join(allowed)}"
        )
    return factory(num_classes=num_classes, **kwargs)


def model_input_spec(name: str, **kwargs) -> Tuple[Tuple[int, ...], Any]:
    """(example input shape without batch dim, dtype) for a model family."""
    try:
        spec = _INPUT_SPECS[name]
    except KeyError:
        raise ValueError(
            f"unknown model.name {name!r}; known models: "
            f"{', '.join(sorted(_INPUT_SPECS))}"
        ) from None
    if callable(spec):
        return spec(**kwargs)
    return spec


def init_params(model, input_shape, seed: int = 0, input_dtype=jnp.float32):
    """Initialize a params pytree with a dummy batch of one."""
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1,) + tuple(input_shape), input_dtype)
    variables = model.init(rng, dummy, train=False)
    return variables["params"]


# populated by the module imports below
_INPUT_SPECS = {}

from colearn_federated_learning_tpu.models import lenet  # noqa: E402,F401
from colearn_federated_learning_tpu.models import resnet  # noqa: E402,F401
from colearn_federated_learning_tpu.models import mobilenet  # noqa: E402,F401
from colearn_federated_learning_tpu.models import bert  # noqa: E402,F401
from colearn_federated_learning_tpu.models import vit  # noqa: E402,F401
from colearn_federated_learning_tpu.models import lstm  # noqa: E402,F401
