"""ResNet-18 for CIFAR-10 (BASELINE config #2, BASELINE.json:8).

TPU/FL-first choices:
- **GroupNorm instead of BatchNorm** — batch statistics are per-client
  state that poisons FedAvg's weighted parameter mean and forces mutable
  collections through the functional round engine; GroupNorm is the
  standard FL substitute (SURVEY.md §7 "hard parts") and keeps params a
  pure pytree.
- CIFAR stem (3×3 conv, no maxpool) when ``small_inputs=True`` — the
  standard ResNet-18 adaptation for 32×32 inputs; the ImageNet stem
  (7×7/2 + maxpool) is kept for 224×224.
- NHWC layout and bfloat16-friendly compute dtype for the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from colearn_federated_learning_tpu.models import _INPUT_SPECS, model_registry


class ResNetBlock(nn.Module):
    filters: int
    strides: int = 1
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       param_dtype=self.param_dtype)
        norm = partial(nn.GroupNorm, num_groups=min(32, self.filters),
                       dtype=self.compute_dtype, param_dtype=self.param_dtype)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides), padding="SAME")(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), padding="SAME")(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet18(nn.Module):
    num_classes: int = 10
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    width: int = 64
    small_inputs: bool = True
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.compute_dtype,
                       param_dtype=self.param_dtype)
        x = x.astype(self.compute_dtype)
        if self.small_inputs:
            x = conv(self.width, (3, 3), padding="SAME")(x)
        else:
            x = conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)])(x)
        x = nn.relu(nn.GroupNorm(num_groups=min(32, self.width), dtype=self.compute_dtype,
                                 param_dtype=self.param_dtype)(x))
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            filters = self.width * (2**i)
            for b in range(n_blocks):
                strides = 2 if (i > 0 and b == 0) else 1
                x = ResNetBlock(filters, strides, self.compute_dtype, self.param_dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        param_dtype=self.param_dtype)(x)


@model_registry.register("resnet18")
def _build(num_classes: int = 10, small_inputs: bool = True, width: int = 64,
           compute_dtype=jnp.float32, param_dtype=jnp.float32, **_):
    # width is overridable so tests can shrink the model while exercising
    # the identical blocks/stages/GroupNorm code path
    return ResNet18(num_classes=num_classes, small_inputs=small_inputs, width=width,
                    compute_dtype=compute_dtype, param_dtype=param_dtype)


_INPUT_SPECS["resnet18"] = ((32, 32, 3), jnp.float32)
