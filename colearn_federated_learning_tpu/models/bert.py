"""BERT-tiny-scale transformer used as a causal next-token LM
(BASELINE config #4, BASELINE.json:10 — "BERT-tiny next-token on Shakespeare").

BERT-tiny geometry (L=2, H=128, A=2, FF=512) with a causal mask, learned
positional embeddings, and weight-tied output head. LEAF Shakespeare is
char-level (~90 symbols, 80-char crops) so sequences are tiny; attention
is plain full attention on one chip (SURVEY.md §5 records ring/sequence
parallelism as a non-goal at this scale). The attention entry point is
factored into ``ops.attention`` so a pallas/ring kernel can slot in for
long-sequence configs without touching the model.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from colearn_federated_learning_tpu.models import _INPUT_SPECS, model_registry
from colearn_federated_learning_tpu.ops.attention import causal_attention


class TransformerBlock(nn.Module):
    hidden: int
    heads: int
    ff: int
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        qkv = nn.Dense(3 * self.hidden, dtype=self.compute_dtype)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        att = causal_attention(q, k, v, self.heads)
        x = x + nn.Dense(self.hidden, dtype=self.compute_dtype)(att)
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        h = nn.Dense(self.ff, dtype=self.compute_dtype)(h)
        h = nn.gelu(h)
        x = x + nn.Dense(self.hidden, dtype=self.compute_dtype)(h)
        return x


class BertTinyLM(nn.Module):
    vocab_size: int = 90
    seq_len: int = 80
    hidden: int = 128
    heads: int = 2
    layers: int = 2
    ff: int = 512
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        # tokens: [B, T] int32 → logits [B, T, V] (next-token prediction)
        embed = nn.Embed(self.vocab_size, self.hidden,
                         embedding_init=nn.initializers.normal(0.02))
        x = embed(tokens).astype(self.compute_dtype)
        pos = self.param("pos_embedding", nn.initializers.normal(0.02),
                         (self.seq_len, self.hidden))
        x = x + pos[None, : x.shape[1], :].astype(self.compute_dtype)
        for _ in range(self.layers):
            x = TransformerBlock(self.hidden, self.heads, self.ff, self.compute_dtype)(x)
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        # weight-tied head
        logits = embed.attend(x.astype(jnp.float32))
        return logits


@model_registry.register("bert_tiny")
def _build(num_classes: int = 0, vocab_size: int = 90, seq_len: int = 80,
           compute_dtype=jnp.float32, **_):
    del num_classes  # LM: output dim == vocab_size
    return BertTinyLM(vocab_size=vocab_size, seq_len=seq_len, compute_dtype=compute_dtype)


def _lm_spec(vocab_size: int = 90, seq_len: int = 80, **_):
    return (seq_len,), jnp.int32


_INPUT_SPECS["bert_tiny"] = _lm_spec
