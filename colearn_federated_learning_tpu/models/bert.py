"""BERT-tiny-scale transformer used as a causal next-token LM
(BASELINE config #4, BASELINE.json:10 — "BERT-tiny next-token on Shakespeare").

BERT-tiny geometry (L=2, H=128, A=2, FF=512) with a causal mask, learned
positional embeddings, and weight-tied output head. LEAF Shakespeare is
char-level (~90 symbols, 80-char crops) so sequences are tiny; attention
is plain full attention on one chip (SURVEY.md §5 records ring/sequence
parallelism as a non-goal at this scale). The attention entry point is
factored into ``ops.attention`` so a pallas/ring kernel can slot in for
long-sequence configs without touching the model.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp

from typing import Callable

from colearn_federated_learning_tpu.models import _INPUT_SPECS, model_registry
from colearn_federated_learning_tpu.ops.attention import causal_attention
from colearn_federated_learning_tpu.ops.backends import resolve_attention


class TransformerBlock(nn.Module):
    hidden: int
    heads: int
    ff: int
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    attention_fn: Callable = causal_attention  # (q, k, v, heads) → out

    @nn.compact
    def __call__(self, x):
        dense = partial(nn.Dense, dtype=self.compute_dtype, param_dtype=self.param_dtype)
        ln = partial(nn.LayerNorm, dtype=self.compute_dtype, param_dtype=self.param_dtype)
        h = ln()(x)
        qkv = dense(3 * self.hidden)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        att = self.attention_fn(q, k, v, self.heads)
        x = x + dense(self.hidden)(att)
        h = ln()(x)
        h = dense(self.ff)(h)
        h = nn.gelu(h)
        x = x + dense(self.hidden)(h)
        return x


class BertTinyLM(nn.Module):
    vocab_size: int = 90
    seq_len: int = 80
    hidden: int = 128
    heads: int = 2
    layers: int = 2
    ff: int = 512
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    attention_fn: Callable = causal_attention

    @nn.compact
    def __call__(self, tokens, train: bool = False, pos_offset=0):
        # tokens: [B, T] int32 → logits [B, T, V] (next-token prediction).
        # pos_offset: global position of tokens[:, 0] — nonzero only when
        # the token axis is sharded (parallel/sequence.py), where each
        # shard holds a block of a longer sequence.
        embed = nn.Embed(self.vocab_size, self.hidden,
                         embedding_init=nn.initializers.normal(0.02),
                         param_dtype=self.param_dtype)
        x = embed(tokens).astype(self.compute_dtype)
        pos = self.param("pos_embedding", nn.initializers.normal(0.02),
                         (self.seq_len, self.hidden), self.param_dtype)
        pos_block = jax.lax.dynamic_slice(
            pos, (pos_offset, 0), (x.shape[1], self.hidden)
        )
        x = x + pos_block[None].astype(self.compute_dtype)
        for _ in range(self.layers):
            x = TransformerBlock(self.hidden, self.heads, self.ff,
                                 self.compute_dtype, self.param_dtype,
                                 self.attention_fn)(x)
        x = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=self.param_dtype)(x)
        # weight-tied head
        logits = embed.attend(x.astype(embed.embedding.dtype)).astype(jnp.float32)
        return logits


@model_registry.register("bert_tiny")
def _build(num_classes: int = 0, vocab_size: int = 90, seq_len: int = 80,
           hidden: int = 128, heads: int = 2, layers: int = 2, ff: int = 512,
           attention: str = "full", block_size: int = 128,
           compute_dtype=jnp.float32, param_dtype=jnp.float32, **_):
    del num_classes  # LM: output dim == vocab_size
    # causal attention backend: full | blockwise | pallas | ring —
    # see ops/backends.py for what each one is
    attn = resolve_attention(attention, causal=True, block_size=block_size)
    return BertTinyLM(vocab_size=vocab_size, seq_len=seq_len,
                      hidden=hidden, heads=heads, layers=layers, ff=ff,
                      attention_fn=attn,
                      compute_dtype=compute_dtype, param_dtype=param_dtype)


def _lm_spec(vocab_size: int = 90, seq_len: int = 80, **_):
    return (seq_len,), jnp.int32


_INPUT_SPECS["bert_tiny"] = _lm_spec
