"""LeNet-5 for MNIST (BASELINE config #1, BASELINE.json:7).

Classic 2-conv/3-fc LeNet. NHWC layout (TPU-native); average pooling as
in the original. ~61k params — the CPU-smoke model.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax.numpy as jnp

from colearn_federated_learning_tpu.models import _INPUT_SPECS, model_registry


class LeNet5(nn.Module):
    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, dtype=self.compute_dtype, param_dtype=self.param_dtype)
        dense = partial(nn.Dense, dtype=self.compute_dtype, param_dtype=self.param_dtype)
        x = x.astype(self.compute_dtype)
        x = conv(6, (5, 5), padding="SAME")(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = conv(16, (5, 5), padding="VALID")(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.tanh(dense(120)(x))
        x = nn.tanh(dense(84)(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=self.param_dtype)(x)
        return x


@model_registry.register("lenet5")
def _build(num_classes: int = 10, compute_dtype=jnp.float32, param_dtype=jnp.float32, **_):
    return LeNet5(num_classes=num_classes, compute_dtype=compute_dtype,
                  param_dtype=param_dtype)


_INPUT_SPECS["lenet5"] = ((28, 28, 1), jnp.float32)
