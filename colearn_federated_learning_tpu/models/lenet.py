"""LeNet-5 for MNIST (BASELINE config #1, BASELINE.json:7).

Classic 2-conv/3-fc LeNet. NHWC layout (TPU-native); average pooling as
in the original. ~61k params — the CPU-smoke model.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from colearn_federated_learning_tpu.models import _INPUT_SPECS, model_registry


class LeNet5(nn.Module):
    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.compute_dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.compute_dtype)(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.compute_dtype)(x)
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.tanh(nn.Dense(120, dtype=self.compute_dtype)(x))
        x = nn.tanh(nn.Dense(84, dtype=self.compute_dtype)(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


@model_registry.register("lenet5")
def _build(num_classes: int = 10, compute_dtype=jnp.float32, **_):
    return LeNet5(num_classes=num_classes, compute_dtype=compute_dtype)


_INPUT_SPECS["lenet5"] = ((28, 28, 1), jnp.float32)
