"""LoRA adapter plane (ROADMAP item 3): parameter-efficient transformer
federation over the million-client store.

Low-rank adaptation (Hu et al. 2021): every targeted dense kernel
``W [d_in, d_out]`` gains a rank-r adapter pair ``A [d_in, r]``,
``B [r, d_out]`` and the effective weight becomes ``W + (alpha/r)·A·B``.
The base model is FROZEN; clients train, upload, and the server
aggregates ONLY the adapter factors — which is what makes transformer
federation wire-feasible at population scale (the per-client upload
drops by ``|W| / |A|+|B| ≈ d/(2r)`` per target, 100–1000× end to end;
the analytic wire counters log the realized ratio as
``wire_reduction_vs_full``).

Design: the whole round stack (engines, aggregation, compression,
attacks, ledger, reputation, checkpointing, wire counters) operates on
ONE opaque params pytree. :class:`LoRAModel` therefore makes the
adapters BE that pytree — ``model.init`` returns adapters only,
``model.apply`` merges them into the frozen base before the underlying
forward — so every subsystem runs in adapter space *by construction*:
the ``[K, ·]`` wire stack carries adapter deltas, krum/median order
statistics rank flattened factors, the forensic ledger's norm/cosine
stats are adapter-space, and eval/checkpoints see the merged
``W + (alpha/r)·BA`` model through the same ``apply``. No engine code
knows LoRA exists; with ``model.lora.enabled=false`` no wrapper is
constructed anywhere and runs are bitwise the pre-LoRA build
(test-pinned).

Targets: the dense kernels inside the repeated transformer blocks of
the two transformer families (``bert_tiny``'s ``TransformerBlock_*``,
``vit_b16``'s ``ViTBlock_*``). Within a block, ``Dense_0`` (the fused
qkv projection) and ``Dense_1`` (the attention output projection) are
the ``"attention"`` target set; ``Dense_2``/``Dense_3`` (the MLP
in/out projections) are ``"mlp"``; ``"all"`` is both. Embeddings, the
weight-tied LM head, LayerNorms, patchify conv, and the classifier
head stay frozen — the Hu et al. recipe. Non-transformer zoo members
have no injection map and are rejected with a clear error
(``LORA_SUPPORTED``).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# model families with a defined injection map (config.validate() and
# the wrapper both check against this)
LORA_SUPPORTED = ("bert_tiny", "vit_b16")

LORA_TARGETS = ("attention", "mlp", "all")

# block-module prefixes whose Dense kernels are adapter targets, and
# which Dense index within a block belongs to which target set
_BLOCK_PREFIXES = ("TransformerBlock_", "ViTBlock_")
_ATTENTION_DENSE = ("Dense_0", "Dense_1")  # qkv proj, attention out
_MLP_DENSE = ("Dense_2", "Dense_3")  # MLP in, MLP out

Path = Tuple[str, ...]


def lora_target_paths(base_params, target: str) -> List[Path]:
    """Paths (tuples of pytree keys ending in ``"kernel"``) of every
    dense kernel the configured ``target`` set adapts, in deterministic
    sorted order. Raises with a clear message when the model has no
    transformer blocks (no injection map) or the target set is empty."""
    if target not in LORA_TARGETS:
        raise ValueError(
            f"unknown model.lora.target {target!r}; "
            f"allowed: {', '.join(LORA_TARGETS)}"
        )
    wanted = set()
    if target in ("attention", "all"):
        wanted.update(_ATTENTION_DENSE)
    if target in ("mlp", "all"):
        wanted.update(_MLP_DENSE)
    paths: List[Path] = []
    flat = jax.tree_util.tree_flatten_with_path(base_params)[0]
    for keypath, leaf in flat:
        keys = tuple(
            k.key if hasattr(k, "key") else str(k) for k in keypath
        )
        if len(keys) < 3 or keys[-1] != "kernel":
            continue
        block, dense = keys[-3], keys[-2]
        if not block.startswith(_BLOCK_PREFIXES):
            continue
        if dense in wanted and getattr(leaf, "ndim", 0) == 2:
            paths.append(keys)
    if not paths:
        raise ValueError(
            "model.lora found no adapter targets: the model has no "
            f"transformer-block dense kernels (LoRA supports "
            f"{', '.join(LORA_SUPPORTED)}; target={target!r})"
        )
    return sorted(paths)


def _get_path(tree, path: Path):
    for k in path:
        tree = tree[k]
    return tree


def init_lora_params(base_params, rank: int, target: str, rng) -> Dict:
    """Build the adapter pytree for ``base_params``: a nested dict
    mirroring the targeted blocks, each target kernel ``W [d_in,
    d_out]`` contributing ``{"lora_a": [d_in, r], "lora_b": [r,
    d_out]}``. ``A ~ N(0, 1/d_in)`` (per-path key folded from ``rng``),
    ``B = 0`` — so the merged model INITIALLY equals the base exactly
    (the standard LoRA init; the first update already moves through
    both factors because ∂/∂B ∝ Aᵀx ≠ 0). Dtypes follow the base
    kernels (``run.param_dtype``)."""
    if rank < 1:
        raise ValueError(f"model.lora.rank must be >= 1, got {rank}")
    paths = lora_target_paths(base_params, target)
    adapters: Dict = {}
    for i, path in enumerate(paths):
        w = _get_path(base_params, path)
        d_in, d_out = int(w.shape[0]), int(w.shape[1])
        if rank >= min(d_in, d_out):
            raise ValueError(
                f"model.lora.rank={rank} is not low-rank for kernel "
                f"{'/'.join(path)} [{d_in}, {d_out}] (needs rank < "
                f"{min(d_in, d_out)}) — the adapter would be as large "
                f"as the weight it replaces"
            )
        k = jax.random.fold_in(rng, i)
        a = jax.random.normal(k, (d_in, rank), jnp.float32) * (
            1.0 / np.sqrt(d_in)
        )
        node = adapters
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node["lora_a"] = a.astype(w.dtype)
        node["lora_b"] = jnp.zeros((rank, d_out), w.dtype)
    return adapters


def merge_lora_params(base_params, adapters, alpha: float, rank: int):
    """The eval/train-time merge: a copy of ``base_params`` where every
    adapted kernel becomes ``W + (alpha/rank)·A·B``. The product is
    computed at the ADAPTER dtype (bf16 under run.local_param_dtype —
    the local-training cast applies to the factors like any other
    leaf) and added at the base kernel's dtype, so the frozen base
    never loses precision. Non-adapted leaves are returned by
    reference (zero copy)."""
    scale = float(alpha) / float(rank)

    def walk(base, ad):
        if not isinstance(ad, dict):
            return base
        if "lora_a" in ad:
            delta = (ad["lora_a"] @ ad["lora_b"]) * jnp.asarray(
                scale, ad["lora_a"].dtype
            )
            return {
                k: (v + delta.astype(v.dtype) if k == "kernel" else v)
                for k, v in base.items()
            }
        out = dict(base)
        for k, sub in ad.items():
            out[k] = walk(base[k], sub)
        return out

    return walk(base_params, adapters)


def count_params(tree) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


class LoRAModel:
    """Model-like facade whose params pytree IS the adapter set.

    Presents the zoo contract the trainer/driver/engines consume —
    ``init(rng, x, train=...) -> {"params": adapters}``,
    ``apply({"params": adapters}, x, ...)``, a ``compute_dtype``
    attribute — while the frozen base params live as a captured
    constant inside ``apply`` (XLA embeds them once per compiled
    program; they are never shipped, aggregated, donated, or
    checkpointed).

    Binding contract: the base params are a pure function of the rng
    passed to ``init`` (exactly ``base.init``'s output), so they are
    NOT checkpointed — a resume/restore re-derives them from
    ``run.seed`` via the driver's ``init_state`` template and gets the
    identical base. The first CONCRETE ``init`` call binds them;
    abstract calls (``jax.eval_shape`` — the wire-counter/HBM
    pre-flight path) trace through without binding. Calling ``apply``
    before any concrete ``init`` raises rather than training against
    an undefined base. Re-``init`` with a different rng rebinds —
    build a fresh Experiment rather than reusing compiled round
    programs across bindings.
    """

    def __init__(self, base, rank: int, alpha: float, target: str):
        if rank < 1:
            raise ValueError(f"model.lora.rank must be >= 1, got {rank}")
        if alpha <= 0.0:
            raise ValueError(
                f"model.lora.alpha must be > 0, got {alpha}"
            )
        if target not in LORA_TARGETS:
            raise ValueError(
                f"unknown model.lora.target {target!r}; "
                f"allowed: {', '.join(LORA_TARGETS)}"
            )
        self.base = base
        self.rank = int(rank)
        self.alpha = float(alpha)
        self.target = target
        # the trainer reads the model's compute dtype at factory time
        self.compute_dtype = getattr(base, "compute_dtype", jnp.float32)
        self._base_params = None

    def init(self, rng, x, train: bool = False):
        variables = self.base.init(rng, x, train=train)
        base_params = variables["params"]
        adapters = init_lora_params(
            base_params, self.rank, self.target,
            jax.random.fold_in(rng, 0x10_8A),
        )
        if not isinstance(x, jax.core.Tracer):
            # concrete init: bind the frozen base (deterministic in the
            # rng — the driver's init_state re-derives it on resume)
            self._base_params = base_params
        return {"params": adapters}

    def apply(self, variables, *args, **kwargs):
        if self._base_params is None:
            raise RuntimeError(
                "LoRAModel.apply before any concrete init: the frozen "
                "base params are bound by the first non-abstract "
                "init(rng, x) call (Experiment.init_state does this)"
            )
        merged = merge_lora_params(
            self._base_params, variables["params"], self.alpha, self.rank
        )
        return self.base.apply({"params": merged}, *args, **kwargs)

    def apply_decomposed(self, variables, *args, **kwargs):
        """The merge-free forward: run the FROZEN base with its own
        params and add each target's low-rank side-path ``(x·A)·B ·
        (alpha/r)`` to that Dense's output via a method interceptor —
        ``W·x + s·(x·A)·B`` instead of ``(W + s·A·B)·x``. Same map up
        to GEMM reassociation (distributivity; test-pinned tolerance),
        but the base kernels stay closure constants: under the
        megabatch layout's per-client ``vmap`` only A/B batch, so the
        dominant base contractions see the flattened ``[C·batch, ·]``
        rows against ONE un-batched weight in EVERY local step — the
        merged ``apply`` would materialize C merged kernels and batch
        every GEMM. The trainer routes the megabatch block through this
        when present (client/trainer.py); every other consumer keeps
        the merged ``apply`` bitwise-unchanged."""
        if self._base_params is None:
            raise RuntimeError(
                "LoRAModel.apply_decomposed before any concrete init: "
                "the frozen base params are bound by the first "
                "non-abstract init(rng, x) call"
            )
        adapters = variables["params"]
        # module paths of the adapted Dense layers — the kernel paths
        # minus the trailing "kernel" key are exactly flax's
        # context.module.path tuples
        targets = {
            p[:-1] for p in lora_target_paths(self._base_params, self.target)
        }
        scale = self.alpha / self.rank

        def interceptor(next_fun, iargs, ikwargs, context):
            if context.method_name != "__call__":
                return next_fun(*iargs, **ikwargs)
            path = tuple(context.module.path)
            if path not in targets:
                return next_fun(*iargs, **ikwargs)
            x = iargs[0]
            y = next_fun(*iargs, **ikwargs)
            node = _get_path(adapters, path)
            # rank-r side path in full f32 (the factors' stored dtype):
            # under bf16 compute the merged apply folds s·A·B into W at
            # f32 BEFORE the one cast, so a low-precision residual here
            # would drift the trajectory well past reassociation level.
            # The r-wide GEMMs are negligible next to the base
            # contraction, so the upcast costs nothing that matters.
            a = node["lora_a"].astype(jnp.float32)
            b = node["lora_b"].astype(jnp.float32)
            r = (x.astype(jnp.float32) @ a) @ b * jnp.float32(scale)
            return (y.astype(jnp.float32) + r).astype(y.dtype)

        with nn.intercept_methods(interceptor):
            return self.base.apply({"params": self._base_params}, *args, **kwargs)

    def merged_params(self, adapters):
        """The deployable full-model params: ``W + (alpha/r)·A·B`` over
        the bound base — what ``colearn export`` writes for a LoRA run
        so downstream consumers never need the adapter structure."""
        if self._base_params is None:
            raise RuntimeError(
                "LoRAModel.merged_params before any concrete init"
            )
        return merge_lora_params(
            self._base_params, adapters, self.alpha, self.rank
        )


def build_lora_model(base, model_name: str, rank: int, alpha: float,
                     target: str) -> LoRAModel:
    """Wrap a zoo model for adapter-space federation, rejecting model
    families with no injection map (clear error at construction, not a
    silent no-adapter run)."""
    if model_name not in LORA_SUPPORTED:
        raise ValueError(
            f"model.lora is not supported for model {model_name!r}: no "
            f"transformer-block injection map; supported: "
            f"{', '.join(LORA_SUPPORTED)}"
        )
    return LoRAModel(base, rank=rank, alpha=alpha, target=target)
