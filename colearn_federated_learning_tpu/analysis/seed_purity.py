"""Seed-purity AST lint (`colearn check` analyzer b).

The repo's observability contract says obs records are engine-invariant
because every analytic model is a pure function of config + shapes and
every schedule is a pure function of ``(seed, round[, snapshot])``.
That contract is only as strong as the absence of impure calls in the
program-path and record-producing modules, so this lint walks them for:

- ``wallclock``: wall-clock reads (``time.time``/``perf_counter``/
  ``monotonic``/``datetime.now``...) — calls AND bare references (the
  spans tracer takes its clock as a default argument). Genuine timing
  sites (spans, pager ``sync_ms``, store gather ``ms``, the record
  timestamp) are documented in the checked-in allowlist.
- ``unseeded_rng``: module-level ``np.random.*`` draws (everything but
  the explicitly-seeded ``default_rng``/``Generator``/``SeedSequence``
  constructors), ``os.urandom``, and any import of the stdlib
  ``random``/``secrets`` modules (their global state is process-seeded
  — nothing in library code may draw from it).
- ``bare_assert``: ``assert`` in library code — stripped under
  ``python -O``, so invariants guarded by it silently vanish; use
  typed exceptions with messages.

Findings are keyed ``(rule, file, qualname, symbol)``; the allowlist
(analysis/seed_purity_allowlist.json) suppresses a finding only when an
entry matches that key AND carries a non-empty ``reason`` — and every
allowlist entry must match at least one live finding (stale entries
fail, so the allowlist can't rot into a blanket waiver).
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

# lint scope, relative to the package directory: the program-path and
# record-producing modules (ISSUE 13) — a directory means every .py in it
DEFAULT_SCOPE = (
    "parallel",
    "server",
    "client",
    "obs",
    "data/store.py",
    "utils/metrics.py",
)

ALLOWLIST_FILE = os.path.join(os.path.dirname(__file__),
                              "seed_purity_allowlist.json")

# wall-clock attribute tails: (module-ish, function) — matched against
# the LAST TWO components of a dotted attribute chain so both
# ``time.time`` and ``datetime.datetime.now`` hit
_WALLCLOCK_TAILS = {
    ("time", "time"),
    ("time", "time_ns"),
    ("time", "monotonic"),
    ("time", "monotonic_ns"),
    ("time", "perf_counter"),
    ("time", "perf_counter_ns"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
    ("date", "today"),
}

# np.random constructors that take an explicit seed — NOT flagged
_SEEDED_NP_RANDOM = {
    "default_rng", "Generator", "SeedSequence", "BitGenerator",
    "Philox", "PCG64", "PCG64DXSM", "MT19937",
}

# stdlib modules whose import is itself the violation (global
# process-seeded RNG state)
_RNG_MODULES = {"random", "secrets"}


def _attr_chain(node: ast.AST) -> List[str]:
    """Flatten ``a.b.c`` into ``["a", "b", "c"]`` (empty when the base
    is not a plain name — e.g. a call result)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


class _Linter(ast.NodeVisitor):
    def __init__(self, rel_file: str):
        self.rel_file = rel_file
        self.stack: List[str] = []
        self.findings: List[Dict[str, Any]] = []

    # ---- helpers ----

    def _qualname(self) -> str:
        return ".".join(self.stack) or "<module>"

    def _add(self, rule: str, node: ast.AST, symbol: str, detail: str):
        self.findings.append({
            "rule": rule,
            "file": self.rel_file,
            "line": node.lineno,
            "qualname": self._qualname(),
            "symbol": symbol,
            "detail": detail,
        })

    # ---- scoping ----

    def visit_FunctionDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    # ---- rules ----

    def visit_Import(self, node):
        for alias in node.names:
            root = alias.name.split(".")[0]
            if root in _RNG_MODULES:
                self._add(
                    "unseeded_rng", node, f"import {alias.name}",
                    f"stdlib {root!r} draws from process-global RNG "
                    f"state; use np.random.default_rng(seed)",
                )
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        root = (node.module or "").split(".")[0]
        if root in _RNG_MODULES:
            self._add(
                "unseeded_rng", node, f"from {node.module} import ...",
                f"stdlib {root!r} draws from process-global RNG state; "
                f"use np.random.default_rng(seed)",
            )
        self.generic_visit(node)

    def visit_Attribute(self, node):
        chain = _attr_chain(node)
        if len(chain) >= 2:
            tail = (chain[-2], chain[-1])
            symbol = ".".join(chain)
            if tail in _WALLCLOCK_TAILS:
                self._add(
                    "wallclock", node, symbol,
                    "wall-clock read in a program-path/record-producing "
                    "module; allowlist genuine timing sites with a reason",
                )
            elif tail == ("os", "urandom"):
                self._add(
                    "unseeded_rng", node, symbol,
                    "os.urandom is unseeded by construction",
                )
            elif (len(chain) >= 3 and chain[-2] == "random"
                    and chain[-3] in ("np", "numpy")
                    and chain[-1] not in _SEEDED_NP_RANDOM):
                self._add(
                    "unseeded_rng", node, symbol,
                    "module-level np.random.* draws from the global "
                    "NumPy RNG; use np.random.default_rng(seed)",
                )
        self.generic_visit(node)

    def visit_Assert(self, node):
        self._add(
            "bare_assert", node, "assert",
            "bare assert is stripped under python -O; raise a typed "
            "exception with a message",
        )
        self.generic_visit(node)


def _scope_files(pkg_dir: str, scope: Sequence[str]) -> List[str]:
    files: List[str] = []
    for entry in scope:
        path = os.path.join(pkg_dir, entry)
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                if name.endswith(".py"):
                    files.append(os.path.join(path, name))
        elif os.path.isfile(path):
            files.append(path)
    return files


def lint_files(files: Sequence[str], rel_to: str) -> List[Dict[str, Any]]:
    """Run the lint over explicit file paths; ``rel_to`` anchors the
    ``file`` key of each finding (repo root for the real run, a tmp dir
    in the fixture tests)."""
    findings: List[Dict[str, Any]] = []
    for path in files:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        linter = _Linter(os.path.relpath(path, rel_to))
        linter.visit(tree)
        findings.extend(linter.findings)
    return findings


def load_allowlist(path: Optional[str] = None) -> List[Dict[str, Any]]:
    with open(path or ALLOWLIST_FILE) as f:
        return json.load(f)


def _entry_matches(entry: Dict[str, Any], finding: Dict[str, Any]) -> bool:
    if entry.get("rule") != finding["rule"]:
        return False
    if entry.get("file") != finding["file"]:
        return False
    if entry.get("qualname") != finding["qualname"]:
        return False
    if "symbol" in entry and entry["symbol"] != finding["symbol"]:
        return False
    return True


def apply_allowlist(
    findings: List[Dict[str, Any]], allowlist: List[Dict[str, Any]],
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]], int]:
    """Split findings into (violations, allowlist_problems, suppressed).

    ``allowlist_problems`` carries entries with no reason and entries
    matching no live finding (stale) — both are violations too: the
    allowlist documents timing sites, it never silently waives them.
    """
    problems: List[Dict[str, Any]] = []
    used = [False] * len(allowlist)
    kept: List[Dict[str, Any]] = []
    suppressed = 0
    for entry in allowlist:
        if not str(entry.get("reason", "")).strip():
            problems.append({
                "kind": "allowlist_missing_reason",
                "entry": entry,
            })
    for finding in findings:
        hit = False
        for i, entry in enumerate(allowlist):
            if _entry_matches(entry, finding):
                used[i] = True
                hit = True
        if hit and str_reason_ok(allowlist, finding):
            suppressed += 1
        else:
            kept.append(finding)
    for i, entry in enumerate(allowlist):
        if not used[i]:
            problems.append({"kind": "allowlist_stale_entry", "entry": entry})
    return kept, problems, suppressed


def str_reason_ok(allowlist: List[Dict[str, Any]],
                  finding: Dict[str, Any]) -> bool:
    """A finding is only suppressed by an entry that has a reason —
    a reason-less entry is itself flagged and suppresses nothing."""
    return any(
        _entry_matches(e, finding) and str(e.get("reason", "")).strip()
        for e in allowlist
    )


def lint_repo(root: str, allowlist_path: Optional[str] = None,
              scope: Sequence[str] = DEFAULT_SCOPE) -> Dict[str, Any]:
    """The `colearn check` entry: lint the package's scope modules under
    ``root`` and apply the shipped allowlist."""
    pkg_dir = os.path.join(root, "colearn_federated_learning_tpu")
    files = _scope_files(pkg_dir, scope)
    findings = lint_files(files, root)
    allowlist = load_allowlist(allowlist_path)
    violations, problems, suppressed = apply_allowlist(findings, allowlist)
    return {
        "files_scanned": len(files),
        "findings": len(findings),
        "suppressed": suppressed,
        "violations": violations,
        "allowlist_problems": problems,
    }
