"""Capability-matrix extractor + validate()↔engine-mirror drift detector
(`colearn check` analyzer a).

The config exclusion matrix lives in TWO hand-maintained places:
``config.ExperimentConfig.validate()`` (the authoritative, config-level
superset) and ``parallel.round_engine._check_engine_compat`` (the
engine-level mirror that protects direct ``make_*_round_fn`` callers).
PRs 6–12 each added clauses with no machine check that the two agree.

This module enumerates a curated FEATURE catalog (each feature = the
canonical-valid override set that turns one subsystem on), evaluates
every feature singleton and pairing through both layers, and emits the
machine-readable ``capability_matrix.json`` — the contract artifact the
ROADMAP item-2 round-program refactor must preserve or shrink.

Verdicts per pairing: ``validate`` (ok / the rejection reason) and
``mirror`` (ok / reason / ``n/a`` when the pairing never builds a
centralized engine — gossip/fedbuff own their own factories). A pairing
DRIFTS when (1) validate accepts but the mirror rejects — the config
layer would admit a run that dies at engine construction — or (2)
validate rejects, the mirror accepts, and BOTH features are in the
mirror's vocabulary (``mirror_visible``) — a direct engine caller could
build the unsound combination the mirror exists to refuse. Rejections
without a reason string fail outright.

Reconciliations this analyzer has already forced are listed in
``RECONCILIATIONS`` (shipped in the matrix artifact for provenance).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

from colearn_federated_learning_tpu.config import ExperimentConfig

MATRIX_FILENAME = "capability_matrix.json"
MATRIX_VERSION = 1

# mirror reconciliations shipped with the analyzer (ISSUE 13 satellite):
# one line each, naming the pairing class and the fix
RECONCILIATIONS = [
    "scaffold x example_dp: mirror gained example_dp (DP noise would "
    "enter the persistent c state; validate() already rejected it)",
    "feddyn x example_dp: mirror gained example_dp (same stateful-"
    "trajectory reasoning; validate() already rejected it)",
    "upload-attack x example_dp: mirror gained example_dp (a Byzantine "
    "client does not run the DP-SGD mechanism; validate() already "
    "rejected it)",
    "feddyn x robust/compression/clip: guard lifted from "
    "_feddyn_prepare into the shared mirror so the extractor compares "
    "one contract surface (the prepare-time guard stays for direct "
    "callers)",
]


class Feature:
    """One subsystem in its canonical-valid form: the dotted overrides
    that enable it, and whether the engine-compat mirror can see it
    (``mirror_visible`` — drives drift enforceability)."""

    def __init__(self, overrides: Dict[str, Any], mirror_visible: bool,
                 note: str = ""):
        self.overrides = overrides
        self.mirror_visible = mirror_visible
        self.note = note


# The feature catalog. Every singleton MUST validate clean (checked at
# extraction — a failing singleton means the catalog itself is broken).
FEATURES: Dict[str, Feature] = {
    "sequential_engine": Feature({"run.engine": "sequential"}, False,
                                 "the bit-parity oracle engine"),
    "scaffold": Feature({"algorithm": "scaffold", "client.momentum": 0.0},
                        True, "client control variates"),
    "feddyn": Feature({"algorithm": "feddyn"}, True,
                      "dynamic regularization"),
    "fedbuff": Feature({"algorithm": "fedbuff"}, False,
                       "async buffered aggregation (own engine)"),
    "gossip": Feature({"algorithm": "gossip"}, False,
                      "decentralized DFedAvg (own engine)"),
    "example_dp": Feature({"dp.enabled": True}, True,
                          "example-level local DP-SGD"),
    "client_dp": Feature({"server.dp_client_noise_multiplier": 1.0,
                          "server.clip_delta_norm": 1.0}, True,
                         "central client-level DP (DP-FedAvg)"),
    "secagg": Feature({"server.secure_aggregation": True,
                       "server.clip_delta_norm": 1.0}, True,
                      "ring-mask secure aggregation"),
    "secagg_pairwise": Feature({"server.secure_aggregation": True,
                                "server.clip_delta_norm": 1.0,
                                "server.secagg_mode": "pairwise"}, True,
                               "Bonawitz pairwise-mask protocol shape"),
    "attack_sign_flip": Feature({"attack.kind": "sign_flip"}, True,
                                "boosted sign-flip upload attack"),
    "attack_alie": Feature({"attack.kind": "alie"}, True,
                           "colluding a-little-is-enough attack"),
    "attack_label_flip": Feature({"attack.kind": "label_flip"}, False,
                                 "host-side data poisoning (never "
                                 "reaches the engine)"),
    "robust_median": Feature({"server.aggregator": "median"}, True,
                             "coordinate-wise median"),
    "robust_trimmed_mean": Feature({"server.aggregator": "trimmed_mean"},
                                   True, "coordinate-wise trimmed mean"),
    "robust_krum": Feature({"server.aggregator": "krum",
                            "server.krum_byzantine": 1}, True,
                           "whole-update krum selection"),
    "compression_topk": Feature({"server.compression": "topk"}, True,
                                "sparse top-k uplink compression"),
    "compression_qsgd": Feature({"server.compression": "qsgd"}, True,
                                "dense unbiased quantization"),
    "error_feedback": Feature({"server.compression": "qsgd",
                               "server.error_feedback": True}, True,
                              "EF-SGD residual memory (needs a "
                              "compressor; qsgd is the canonical pick)"),
    "downlink_qsgd": Feature({"server.downlink_compression": "qsgd"}, True,
                             "broadcast quantization"),
    "client_ledger": Feature({"run.obs.client_ledger.enabled": True}, True,
                             "per-client forensic ledger"),
    "paged_ledger": Feature({"run.obs.client_ledger.enabled": True,
                             "run.obs.client_ledger.hot_capacity": 8},
                            False, "hot/cold paged ledger store "
                            "(paging is driver-level, not engine-level)"),
    "reputation": Feature({"run.obs.client_ledger.enabled": True,
                           "server.reputation.enabled": True}, True,
                          "ledger-driven trust weighting"),
    "sampling_weighted": Feature({"server.sampling": "weighted"}, False,
                                 "size-proportional cohort draw"),
    "sampling_poisson": Feature({"server.sampling": "poisson"}, False,
                                "Poisson subsampling (exact DP q)"),
    "sampling_adaptive": Feature({"server.sampling": "adaptive",
                                  "run.obs.client_ledger.enabled": True,
                                  "run.obs.client_ledger.log_every": 1},
                                 False, "Oort-style utility-aware draw "
                                 "(needs periodic ledger snapshots)"),
    "sampling_streaming_ledger": Feature(
        {"server.sampling": "streaming",
         "run.obs.client_ledger.enabled": True,
         "run.obs.client_ledger.log_every": 1}, False,
        "million-client streaming draw with ledger-fed sketch"),
    "fuse_rounds": Feature({"run.fuse_rounds": 2}, False,
                           "multi-round fused scan"),
    "shape_buckets": Feature({"run.shape_buckets.enabled": True}, False,
                             "cohort-shaped step ladder"),
    "megabatch": Feature({"run.cohort_layout": "megabatch"}, True,
                         "cohort axis collapsed into the GEMM batch"),
    "fused_apply": Feature({"server.fused_apply": True}, True,
                           "pallas fused server-apply kernel"),
    "stragglers": Feature({"server.straggler_rate": 0.5}, False,
                          "partial-work straggler simulation"),
    "churn": Feature({"run.churn.enabled": True,
                      "run.churn.dropout_hazard": 0.1,
                      "run.churn.crash_rate": 0.1}, False,
                     "seed-pure diurnal availability / dropout hazard / "
                     "crash-mid-round model (driver + sampler level; "
                     "never reaches the engine)"),
    "batch_shards": Feature({"run.batch_shards": 2}, False,
                            "intra-client batch mesh axis"),
    "stream_placement": Feature({"data.placement": "stream"}, False,
                                "O(cohort) host-RAM slab path"),
    "client_store": Feature({"data.store.dir": "<store>"}, False,
                            "on-disk mmap client store (dir is a "
                            "validate-level sentinel; existence is "
                            "checked at construction)"),
    "store_gather_pool": Feature({"data.store.dir": "<store>",
                                  "data.store.gather_workers": 4}, False,
                                 "sharded parallel gather pool: rows "
                                 "split by owning shard, per-shard "
                                 "copies on a shared worker pool — "
                                 "bitwise row order at every worker "
                                 "count (data level; the engine never "
                                 "sees it)"),
    "native_pipeline": Feature({"run.host_pipeline": "native"}, False,
                               "C++ threaded host pipeline"),
    "lora": Feature({"model.name": "bert_tiny", "model.num_classes": 0,
                     "model.kwargs": {"vocab_size": 32, "seq_len": 8},
                     "model.lora.enabled": True, "model.lora.rank": 2},
                    False, "adapter-plane uploads (params ARE the "
                    "adapters; engine-transparent by construction)"),
    "hierarchy": Feature({"server.hierarchy.num_edges": 2}, True,
                         "two-tier edge/core federation (the engine "
                         "reused recursively, one tier down)"),
    "multi_version": Feature({"algorithm": "fedbuff",
                              "server.async_versions": 2}, False,
                             "concurrent model versions, one async "
                             "buffer each (fedbuff scheduler level)"),
    "churn_trace": Feature({"run.churn.enabled": True,
                            "run.churn.trace": "<trace>"}, False,
                           "trace-replay availability (recorded on/off "
                           "bitmap; dir is a validate-level sentinel, "
                           "existence checked at model construction)"),
    "digest": Feature({"run.obs.digest.enabled": True}, False,
                      "determinism flight recorder (driver-level digest "
                      "of fetched state; never reaches the engine)"),
    "control_plane_device": Feature(
        {"run.control_plane": "device"}, False,
        "device-resident control plane (server/device_plane.py): "
        "cohort/churn/slab derivation lowered into the round program; "
        "driver-level — the engines run unchanged under the wrapper"),
    "executables": Feature(
        {"run.obs.executables": True}, False,
        "compiled-program observatory (obs/executables.py): AOT "
        "lower/compile registry harvesting XLA cost/memory analysis, "
        "HBM watermarks and retrace forensics; observational like "
        "digest — the lowering is the one jit would produce, params "
        "are bitwise identical with it off"),
}


def base_config() -> ExperimentConfig:
    """The probe base every feature overlays: a small valid federation
    sized so every catalog feature can turn on (krum's Blanchard bound,
    paged-ledger capacity, fuse divisibility...)."""
    cfg = ExperimentConfig()
    cfg.name = "capability_probe"
    cfg.data.num_clients = 16
    cfg.data.synthetic_train_size = 256
    cfg.data.synthetic_test_size = 64
    cfg.server.cohort_size = 8
    cfg.server.num_rounds = 8
    cfg.server.eval_every = 2
    return cfg


def _merge(a: Dict[str, Any], b: Dict[str, Any]
           ) -> Optional[Dict[str, Any]]:
    """Union of two override sets; None when they set the same knob to
    different values (the pairing is ill-posed, not rejected)."""
    out = dict(a)
    for k, v in b.items():
        if k in out and out[k] != v:
            return None
        out[k] = v
    return out


def _validate_verdict(overrides: Dict[str, Any]) -> Tuple[str, Optional[str]]:
    cfg = base_config()
    cfg.apply_overrides(dict(overrides))
    try:
        cfg.validate()
        return "ok", None
    except ValueError as e:
        return "rejected", str(e.args[0]) if e.args else ""


def mirror_kwargs(cfg: ExperimentConfig) -> Dict[str, Any]:
    """Derive the ``_check_engine_compat`` call exactly as the driver's
    centralized-engine construction does (server/round_driver.py):
    label_flip never reaches the engine, feddyn rides feddyn_alpha,
    example-DP is dp_cfg.enabled."""
    from colearn_federated_learning_tpu.server.attacks import UPLOAD_ATTACKS

    return dict(
        scaffold=cfg.algorithm == "scaffold",
        aggregator=cfg.server.aggregator,
        compression=cfg.server.compression,
        clip_delta_norm=cfg.server.clip_delta_norm,
        secagg=cfg.server.secure_aggregation,
        feddyn=cfg.algorithm == "feddyn",
        client_dp=cfg.server.dp_client_noise_multiplier,
        downlink=cfg.server.downlink_compression,
        secagg_quant_step=cfg.server.secagg_quant_step,
        error_feedback=cfg.server.error_feedback,
        attack=cfg.attack.kind if cfg.attack.kind in UPLOAD_ATTACKS else "",
        client_ledger=cfg.run.obs.client_ledger.enabled,
        reputation=cfg.server.reputation.enabled,
        fused_apply=cfg.server.fused_apply,
        cohort_layout=cfg.run.cohort_layout,
        example_dp=cfg.dp.enabled,
        hierarchy=cfg.server.hierarchy.num_edges > 0,
    )


def _mirror_verdict(overrides: Dict[str, Any],
                    mirror_fn: Optional[Callable] = None,
                    ) -> Tuple[str, Optional[str]]:
    cfg = base_config()
    cfg.apply_overrides(dict(overrides))
    if cfg.algorithm in ("gossip", "fedbuff"):
        # those engines never route through the centralized factories'
        # shared mirror — there is nothing to compare against
        return "n/a", None
    if mirror_fn is None:
        from colearn_federated_learning_tpu.parallel.round_engine import (
            _check_engine_compat,
        )

        mirror_fn = _check_engine_compat
    try:
        mirror_fn(**mirror_kwargs(cfg))
        return "ok", None
    except ValueError as e:
        return "rejected", str(e.args[0]) if e.args else ""


def _entry(name_a: str, name_b: Optional[str], overrides: Dict[str, Any],
           enforceable: bool, mirror_fn: Optional[Callable],
           ) -> Dict[str, Any]:
    vres, vreason = _validate_verdict(overrides)
    mres, mreason = _mirror_verdict(overrides, mirror_fn)
    drift = False
    if mres != "n/a":
        if vres == "ok" and mres == "rejected":
            drift = True
        elif vres == "rejected" and mres == "ok" and enforceable:
            drift = True
    entry: Dict[str, Any] = {
        "pair": name_a if name_b is None else f"{name_a}+{name_b}",
        "validate": vres,
        "mirror": mres,
        "drift": drift,
    }
    if vreason is not None:
        entry["reason"] = vreason
    if mreason is not None:
        entry["mirror_reason"] = mreason
    return entry


def extract_matrix(mirror_fn: Optional[Callable] = None) -> Dict[str, Any]:
    """Build the full matrix: every singleton + every non-conflicting
    pairing, both verdicts, drift flags. ``mirror_fn`` is injectable so
    the drift detector itself is testable (a permissive stub must light
    up the enforceable pairings)."""
    names = sorted(FEATURES)
    singletons: List[Dict[str, Any]] = []
    for name in names:
        entry = _entry(name, None, FEATURES[name].overrides,
                       FEATURES[name].mirror_visible, mirror_fn)
        if entry["validate"] != "ok":
            raise ValueError(
                f"capability catalog is broken: singleton {name!r} does "
                f"not validate: {entry.get('reason')}"
            )
        singletons.append(entry)
    pairs: List[Dict[str, Any]] = []
    skipped = 0
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            merged = _merge(FEATURES[a].overrides, FEATURES[b].overrides)
            if merged is None:
                skipped += 1
                continue
            enforceable = (FEATURES[a].mirror_visible
                           and FEATURES[b].mirror_visible)
            pairs.append(_entry(a, b, merged, enforceable, mirror_fn))
    supported = sum(1 for p in pairs if p["validate"] == "ok")
    return {
        "version": MATRIX_VERSION,
        "base": "16 clients / cohort 8 / 8 rounds / eval_every 2 "
                "(capability.base_config)",
        "features": {
            n: {"overrides": FEATURES[n].overrides,
                "mirror_visible": FEATURES[n].mirror_visible,
                "note": FEATURES[n].note}
            for n in names
        },
        "reconciliations": RECONCILIATIONS,
        "counts": {
            "features": len(names),
            "pairs": len(pairs),
            "supported": supported,
            "rejected": len(pairs) - supported,
            "skipped_conflicts": skipped,
            "drift": sum(1 for p in pairs if p["drift"]),
        },
        "singletons": singletons,
        "pairs": pairs,
    }


def matrix_path(root: str) -> str:
    return os.path.join(root, MATRIX_FILENAME)


def write_matrix(root: str, matrix: Optional[Dict[str, Any]] = None) -> str:
    matrix = matrix or extract_matrix()
    path = matrix_path(root)
    with open(path, "w") as f:
        json.dump(matrix, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def check_capability(root: str,
                     mirror_fn: Optional[Callable] = None,
                     ) -> Dict[str, Any]:
    """The `colearn check` entry: extract, detect drift + reason-less
    rejections, and diff against the checked-in artifact."""
    matrix = extract_matrix(mirror_fn)
    violations: List[Dict[str, Any]] = []
    for entry in matrix["singletons"] + matrix["pairs"]:
        if entry["drift"]:
            violations.append({
                "kind": "mirror_drift", "where": entry["pair"],
                "message": (
                    f"validate()={entry['validate']} but engine mirror="
                    f"{entry['mirror']} for pairing {entry['pair']} "
                    f"(reason: {entry.get('reason') or entry.get('mirror_reason') or 'n/a'})"
                ),
            })
        if entry["validate"] == "rejected" and not (entry.get("reason")
                                                    or "").strip():
            violations.append({
                "kind": "rejection_without_reason", "where": entry["pair"],
                "message": f"pairing {entry['pair']} is rejected with an "
                           f"empty reason string",
            })
        if entry["mirror"] == "rejected" and not (entry.get("mirror_reason")
                                                  or "").strip():
            violations.append({
                "kind": "rejection_without_reason", "where": entry["pair"],
                "message": f"pairing {entry['pair']} is mirror-rejected "
                           f"with an empty reason string",
            })
    path = matrix_path(root)
    if not os.path.isfile(path):
        violations.append({
            "kind": "matrix_missing", "where": MATRIX_FILENAME,
            "message": f"checked-in {MATRIX_FILENAME} is missing — run "
                       f"`colearn check --update-matrix`",
        })
    else:
        with open(path) as f:
            committed = json.load(f)
        if committed != matrix:
            changed = _diff_pairs(committed, matrix)
            violations.append({
                "kind": "matrix_drift", "where": MATRIX_FILENAME,
                "message": (
                    f"checked-in {MATRIX_FILENAME} disagrees with the "
                    f"code ({len(changed)} pairing(s) changed: "
                    f"{', '.join(changed[:5])}"
                    f"{'...' if len(changed) > 5 else ''}) — run "
                    f"`colearn check --update-matrix` and review the diff"
                ),
            })
    return {
        "matrix": matrix,
        "counts": matrix["counts"],
        "violations": violations,
    }


def _diff_pairs(old: Dict[str, Any], new: Dict[str, Any]) -> List[str]:
    def index(m):
        return {e["pair"]: e for e in m.get("singletons", []) + m.get("pairs", [])}

    oi, ni = index(old), index(new)
    changed = sorted(
        p for p in set(oi) | set(ni) if oi.get(p) != ni.get(p)
    )
    return changed or ["<metadata>"]
