"""`colearn check` orchestration: run all three static analyzers on the
repo and fold their findings into one violations report (exit 1 names
each violation; ``--json`` for tooling). Pure host — validate() and the
engine-compat mirror are plain function calls; nothing initializes a
jax backend or builds an engine.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

# bump when an analyzer's rules or the matrix schema change — BENCH_r*
# extras carry this (+ the clean bit) as provenance
ANALYZER_VERSION = 1


def detect_root(root: Optional[str] = None) -> str:
    """Repo root = the directory holding the package directory (where
    capability_matrix.json and the docs live)."""
    if root:
        return os.path.abspath(root)
    import colearn_federated_learning_tpu as pkg

    return os.path.dirname(os.path.dirname(os.path.abspath(pkg.__file__)))


def run_check(root: Optional[str] = None,
              update_matrix: bool = False) -> Dict[str, Any]:
    """Run capability + seed-purity + schema analysis. Returns a report
    dict; ``report["violations"]`` empty means the repo is clean."""
    from colearn_federated_learning_tpu.analysis import (
        capability,
        schema,
        seed_purity,
    )

    root = detect_root(root)
    violations: List[Dict[str, Any]] = []

    if update_matrix:
        capability.write_matrix(root)
    cap = capability.check_capability(root)
    for v in cap["violations"]:
        violations.append(dict(v, analyzer="capability"))

    lint = seed_purity.lint_repo(root)
    for f in lint["violations"]:
        violations.append({
            "analyzer": "seed_purity",
            "kind": f["rule"],
            "where": f"{f['file']}:{f['line']}",
            "message": f"{f['symbol']} in {f['qualname']}: {f['detail']}",
        })
    for p in lint["allowlist_problems"]:
        e = p["entry"]
        violations.append({
            "analyzer": "seed_purity",
            "kind": p["kind"],
            "where": f"{e.get('file', '?')} ({e.get('qualname', '?')})",
            "message": f"allowlist entry {e.get('symbol', e.get('rule'))!r}: "
                       f"{p['kind'].replace('_', ' ')}",
        })

    sch = schema.check_schema(root)
    for v in sch["violations"]:
        violations.append(dict(v, analyzer="schema"))

    return {
        "analyzer_version": ANALYZER_VERSION,
        "root": root,
        "clean": not violations,
        "violations": violations,
        "capability": cap["counts"],
        "seed_purity": {
            "files_scanned": lint["files_scanned"],
            "findings": lint["findings"],
            "suppressed": lint["suppressed"],
        },
        "schema": {
            "registered_types": sch["registered_types"],
            "emit_sites": sch["emit_sites"],
            "emit_sites_resolved": sch["emit_sites_resolved"],
            "consumed_types": sch["consumed_types"],
            "consumed_fields": len(sch["consumed_fields"]),
        },
    }


def format_report(report: Dict[str, Any]) -> str:
    lines = [
        f"colearn check v{report['analyzer_version']} @ {report['root']}",
        f"capability: {report['capability']['features']} features, "
        f"{report['capability']['pairs']} pairings "
        f"({report['capability']['supported']} supported / "
        f"{report['capability']['rejected']} rejected), "
        f"{report['capability']['drift']} drift",
        f"seed purity: {report['seed_purity']['files_scanned']} files, "
        f"{report['seed_purity']['findings']} findings, "
        f"{report['seed_purity']['suppressed']} allowlisted",
        f"schema: {len(report['schema']['registered_types'])} record types, "
        f"{report['schema']['emit_sites']} emit sites "
        f"({report['schema']['emit_sites_resolved']} resolved), "
        f"{len(report['schema']['consumed_types'])} consumed types",
    ]
    if report["clean"]:
        lines.append("OK — no violations")
    else:
        lines.append(f"FAIL — {len(report['violations'])} violation(s):")
        for v in report["violations"]:
            lines.append(
                f"  [{v['analyzer']}/{v['kind']}] {v['where']}: "
                f"{v['message']}"
            )
    return "\n".join(lines)


def bench_provenance() -> Dict[str, Any]:
    """The `static_check` extra BENCH_r* entries carry: analyzer
    version + whether the repo passed clean at bench time (best-effort;
    a crash in the analyzers must never take the bench down)."""
    try:
        report = run_check()
        return {"analyzer_version": report["analyzer_version"],
                "clean": bool(report["clean"]),
                "violations": len(report["violations"])}
    except Exception as e:  # pragma: no cover - defensive
        return {"analyzer_version": ANALYZER_VERSION, "clean": False,
                "error": repr(e)[:200]}
