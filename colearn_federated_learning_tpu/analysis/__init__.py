"""Static invariant analysis (`colearn check` — docs/DESIGN.md
"Static invariants & capability matrix").

Three pure-host analyzers turn the repo's hand-maintained correctness
disciplines into checked artifacts:

- :mod:`analysis.capability` — enumerates the config pairing space,
  runs ``config.validate()`` and the engine-compat mirror
  (``parallel.round_engine._check_engine_compat``) on every pairing,
  emits the checked-in ``capability_matrix.json``, and fails on any
  validate()↔mirror disagreement or reason-less rejection.
- :mod:`analysis.seed_purity` — AST lint of the program-path and
  record-producing modules for wall-clock reads, unseeded RNG, and
  bare ``assert`` in library code, against the checked-in
  ``seed_purity_allowlist.json`` that documents each genuine timing
  site.
- :mod:`analysis.schema` — the JSONL record-type registry, statically
  cross-checked against the MetricsLogger emit sites and the
  summarize/watch/mfu/population/clients consumers (plus a runtime
  validator the tier-1 tests run over a live fit's JSONL).

:mod:`analysis.check` orchestrates all three; ``colearn check`` is the
CLI entry (exit 1 names each violation, ``--json`` for tooling).
"""

from colearn_federated_learning_tpu.analysis.check import (  # noqa: F401
    ANALYZER_VERSION,
    run_check,
)
