"""JSONL record-schema registry + emit/consume cross-check
(`colearn check` analyzer c).

Five pure-host CLIs (summarize / watch / mfu / population / clients)
consume the metrics JSONL that the driver and obs modules emit — three
hand-maintained shapes with no machine check that they agree. This
module is the single registry of every record type plus two static
extractors that cross-check it against the code:

- **emit sites**: AST-walk the emitting modules for ``logger.log({...})``
  calls (dict literals AND locally-assigned dicts with their
  ``rec["k"] = ...`` / ``rec.update({...})`` augmentations) and for
  record-constructor dict literals carrying an ``"event"`` key
  (obs/health.py, obs/population.py return records the driver logs).
  Unregistered record types, emitted-but-unregistered fields, and
  statically-missing required fields all fail with file:line.
- **consumers**: AST-walk the report modules for record-variable field
  accesses (``rec.get("x")`` / ``rec["x"]`` / ``"x" in rec``), where
  record variables are inferred from iteration over the records list,
  ``next(...)`` over filtered generators, filtered-list subscripts, and
  propagation through local assignment + record-returning helpers.
  Consumed-but-never-registered types and fields fail with file:line.

``validate_records`` is the runtime twin: the tier-1 suite runs it over
a live tiny-fit's JSONL so dynamically-keyed records (comm stats, the
ledger columns, ``run_summary`` spreads) are held to the registry too.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from colearn_federated_learning_tpu.obs.ledger import LEDGER_COLS

# fields MetricsLogger.log stamps onto every record
UNIVERSAL_FIELDS = frozenset({"time", "schema"})


class RecordSpec:
    """One record type's contract: required + optional field names, and
    whether runtime extras beyond them are legal (``open_fields`` —
    used only for records whose keys are inherently dynamic, e.g. the
    backend-defined ``device_memory`` gauges)."""

    def __init__(self, required: Iterable[str],
                 optional: Iterable[str] = (),
                 open_fields: bool = False, doc: str = ""):
        self.required = frozenset(required)
        self.optional = frozenset(optional)
        self.open_fields = open_fields
        self.doc = doc

    @property
    def fields(self) -> frozenset:
        return self.required | self.optional


_COMM_FIELDS = (
    "upload_bytes", "upload_bytes_raw", "download_bytes",
    "download_bytes_raw", "upload_bytes_full", "wire_reduction_vs_full",
    "host_input_bytes",
)

# The registry. "round" is the event-less per-round metrics record; all
# others are keyed by their "event" value.
REGISTRY: Dict[str, RecordSpec] = {
    "round": RecordSpec(
        required=("round", "train_loss", "examples"),
        optional=_COMM_FIELDS + (
            "padded_step_fraction", "padded_example_fraction",
            "shape_bucket_steps", "dropped_clients", "straggler_clients",
            "dp_epsilon", "dp_client_epsilon", "mean_staleness",
            "max_staleness", "staleness_p50", "staleness_p90",
            "staleness_clamped", "backpressure_dropped",
            "backpressure_rejected", "churn_unavailable", "churn_dropped",
            "churn_crashed",
            # multi-version / hierarchy planes (ISSUE 16)
            "async_version", "version_readmitted", "hier_edge_crashed",
            "hier_edge_excluded", "hier_core_upload_bytes",
            "byzantine_count", "consensus_dist", "rounds_per_sec",
            "client_updates_per_sec_per_chip", "eval_loss", "eval_acc",
        ),
        doc="per-round metrics (driver flush windows)",
    ),
    "spans": RecordSpec(
        required=("round", "phases", "process_index"),
        doc="per-phase timing aggregates at each metrics flush",
    ),
    "device_memory": RecordSpec(
        required=("round",), open_fields=True,
        doc="jax device memory gauges (backend-defined keys)",
    ),
    "health": RecordSpec(
        required=("kind", "round"),
        optional=("loss", "best_loss", "factor"),
        doc="NaN/divergence monitor events (obs/health.py)",
    ),
    "retry": RecordSpec(
        required=("attempt", "round", "error"),
        doc="failure-recovery attempts (run.max_retries)",
    ),
    "run_summary": RecordSpec(
        required=("rounds", "wall_time_sec", "compiles", "compile_ms"),
        optional=_COMM_FIELDS + (
            "host_prefetched", "placed_prefetched", "prefetch_dropped",
            "slab_prefetched",
            "ledger_evictions", "ledger_page_syncs",
            "population_unique_clients", "population_coverage_pct",
            "population_participations", "pager_hit_rate",
            # store data plane (PR 19): wall throughput + pool width
            "store_gather_bytes", "store_gather_mbps",
            "store_gather_workers",
            # production-traffic totals (run.churn / fedbuff promotion)
            "staleness_clamped", "backpressure_dropped",
            "backpressure_rejected", "churn_unavailable", "churn_dropped",
            "churn_crashed", "async_updates_absorbed",
            "async_updates_per_sec", "async_staleness_bound",
            # multi-version / hierarchy planes (ISSUE 16)
            "async_staleness_p50", "async_staleness_p90",
            "async_staleness_max", "async_per_version",
            "version_readmitted", "hier_edges", "hier_edge_absorbed",
            "hier_edge_crashed", "hier_edge_excluded",
            "hier_core_upload_bytes",
            # compiled-program observatory (PR 20): run HBM peak
            "hbm_peak_bytes", "hbm_peak_program", "executables_compiled",
        ),
        doc="end-of-fit totals (every exit path, aborts included)",
    ),
    "trace": RecordSpec(
        required=("path",), optional=("merged_fragments",),
        doc="Chrome-trace export provenance",
    ),
    "churn": RecordSpec(
        required=("diurnal_period", "diurnal_amplitude",
                  "base_availability", "min_availability",
                  "dropout_hazard", "crash_rate"),
        optional=("trace", "trace_rounds", "trace_rows"),
        doc="churn hazard-model provenance at fit start (run.churn)",
    ),
    "hierarchy": RecordSpec(
        required=("num_edges", "core_aggregator", "edge_aggregator",
                  "edge_dropout_rate", "core_trust_decay"),
        doc="two-tier topology provenance at fit start "
            "(server.hierarchy)",
    ),
    "async_versions": RecordSpec(
        required=("versions", "retire_rounds", "retire_updates",
                  "readmit_decay", "strict_versions"),
        doc="multi-version scheduler provenance at fit start "
            "(server.async_versions > 1)",
    ),
    "resumed": RecordSpec(
        required=("round", "host_pipeline"),
        doc="checkpoint-resume provenance",
    ),
    "precision": RecordSpec(
        required=("param_dtype", "compute_dtype", "local_param_dtype",
                  "fused_apply", "double_buffer", "control_plane"),
        doc="dtype/fusion/control-plane provenance at fit start",
    ),
    "phase_cost_model": RecordSpec(
        required=("step_flops", "flop_source", "n_coords", "n_coords_full",
                  "param_bytes", "compute_bytes", "mfu_basis", "peak_flops",
                  "peak_hbm_bytes_per_sec", "n_chips", "process_index",
                  "cohort_layout", "clients_per_lane", "gemm_rows",
                  "lora_all_steps", "mxu_tile_pad_fraction"),
        doc="static half of the roofline cost model (obs/roofline.py)",
    ),
    "phase_cost": RecordSpec(
        required=("round", "process_index", "phases"),
        doc="per-round analytic FLOP/HBM phase costs",
    ),
    "poisson_sampling": RecordSpec(
        required=("q", "cap", "dp_delta_abort"),
        doc="poisson-sampling provenance (cap + abort probability)",
    ),
    "shape_buckets": RecordSpec(
        required=("ladder", "full_steps_per_epoch",
                  "max_compiles_per_engine"),
        doc="bucket-ladder provenance at fit start",
    ),
    "shape_bucket": RecordSpec(
        required=("round", "bucket_steps", "ladder_steps", "compiles"),
        optional=("compile_ms",),
        doc="per-dispatch bucket-rung attribution",
    ),
    "attack": RecordSpec(
        required=("kind", "fraction", "scale", "eps", "n_compromised",
                  "compromised"),
        doc="adversary provenance (ground truth for `colearn clients`)",
    ),
    "warning": RecordSpec(
        required=("warning", "detail"), optional=("round",),
        doc="structured run-log warnings",
    ),
    "partition_repair": RecordSpec(
        required=("moved",),
        doc="extreme-alpha Dirichlet partition repair provenance",
    ),
    "profile": RecordSpec(
        required=("round", "dir"),
        doc="jax.profiler trace provenance (run.profile_round)",
    ),
    "client_ledger": RecordSpec(
        required=("round", "num_clients", "ema", "zmax", "ids")
        + LEDGER_COLS[:2],
        optional=LEDGER_COLS[2:],
        doc="columnar forensic-ledger snapshot (obs/ledger.py)",
    ),
    "population_health": RecordSpec(
        required=("round", "window_rounds", "participants", "coverage",
                  "fairness", "staleness"),
        optional=("draws", "sketch", "pager", "store", "async", "churn"),
        doc="per-window federation health record (obs/population.py)",
    ),
    "round_digest": RecordSpec(
        required=("round", "prev_round", "prev", "self", "params",
                  "params_leaves", "opt", "ledger", "schedule", "wire",
                  "rng"),
        doc="determinism flight-recorder chain link (obs/digest.py): "
            "per-component state digests + the hash-chain self/prev",
    ),
    "digest_resume": RecordSpec(
        required=("round", "ok", "head_round", "head", "detail"),
        doc="checkpoint digest-head vs log chain verification at resume "
            "(run.obs.digest.verify_resume)",
    ),
    "executable_compiled": RecordSpec(
        required=("round", "name", "fingerprint", "compile_ms"),
        optional=("flops", "bytes_accessed", "argument_bytes",
                  "output_bytes", "temp_bytes", "generated_code_bytes",
                  "peak_bytes", "donated_args", "rounds_per_call",
                  "backend", "preflight"),
        doc="per-compiled-program XLA cost/memory truth "
            "(obs/executables.py; run.obs.executables)",
    ),
    "retrace": RecordSpec(
        required=("round", "name", "fingerprint", "prev_fingerprint",
                  "n_changed", "changed"),
        doc="recompile forensics: which argument of an already-seen "
            "program changed shape/dtype/sharding",
    ),
    "hbm_watermark": RecordSpec(
        required=("round", "watermark_bytes"),
        optional=("program", "resident_bytes", "temp_bytes", "programs",
                  "peak_bytes"),
        doc="per-flush predicted HBM high-water mark over the window's "
            "dispatched programs (+ running run peak)",
    ),
}

# modules whose logger.log(...) calls are emit sites (repo-root relative)
EMIT_LOG_MODULES = (
    "colearn_federated_learning_tpu/server/round_driver.py",
)
# modules whose "event"-keyed dict literals are record constructors the
# driver logs (returned, not logged in place)
EVENT_DICT_MODULES = (
    "colearn_federated_learning_tpu/obs/health.py",
    "colearn_federated_learning_tpu/obs/population.py",
    "colearn_federated_learning_tpu/obs/executables.py",
)
# the pure-host report modules `colearn summarize/watch/mfu/population/
# clients` run (bench-report reads BENCH_r*.json, a different artifact)
CONSUMER_MODULES = (
    "colearn_federated_learning_tpu/obs/summary.py",
    "colearn_federated_learning_tpu/obs/population.py",
    "colearn_federated_learning_tpu/obs/roofline.py",
    "colearn_federated_learning_tpu/obs/ledger.py",
    "colearn_federated_learning_tpu/obs/digest.py",
)


def all_registered_fields() -> Set[str]:
    out: Set[str] = set(UNIVERSAL_FIELDS) | {"event", "round"}
    for spec in REGISTRY.values():
        out |= spec.fields
    return out


# ---------------------------------------------------------------------------
# emit-site extraction
# ---------------------------------------------------------------------------


class _DictInfo:
    """Statically-known shape of one emitted dict: literal keys, the
    constant "event" value (if any), and whether dynamic writes (** /
    .update(expr) / var[expr] = ...) make it open-ended."""

    def __init__(self, line: int):
        self.line = line
        self.keys: Set[str] = set()
        self.event: Optional[str] = None
        self.open = False

    def absorb_literal(self, node: ast.Dict):
        for k, v in zip(node.keys, node.values):
            if k is None:  # ** spread
                self.open = True
            elif isinstance(k, ast.Constant) and isinstance(k.value, str):
                self.keys.add(k.value)
                if k.value == "event":
                    if isinstance(v, ast.Constant) and isinstance(v.value, str):
                        self.event = v.value
                    else:
                        self.open = True  # event not statically known
            else:
                self.open = True


def _collect_fn_dicts(fn: ast.AST) -> Dict[str, _DictInfo]:
    """var name → dict shape, from ``v = {...}`` assignments plus
    ``v["k"] = ...`` / ``v.update(...)`` augmentations in one function
    (nested defs included — the driver's flush closures)."""
    infos: Dict[str, _DictInfo] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(
                node.targets[0], ast.Subscript):
            sub = node.targets[0]
            if isinstance(sub.value, ast.Name) and sub.value.id in infos:
                idx = sub.slice
                if isinstance(idx, ast.Constant) and isinstance(idx.value, str):
                    infos[sub.value.id].keys.add(idx.value)
                else:
                    infos[sub.value.id].open = True
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    info = infos.setdefault(tgt.id, _DictInfo(node.lineno))
                    info.absorb_literal(node.value)
        elif (isinstance(node, ast.AnnAssign)
                and isinstance(node.value, ast.Dict)
                and isinstance(node.target, ast.Name)):
            # `rec: Dict[str, Any] = {...}` — the driver's preferred style
            info = infos.setdefault(node.target.id, _DictInfo(node.lineno))
            info.absorb_literal(node.value)
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "update"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in infos):
            info = infos[node.func.value.id]
            if node.args and isinstance(node.args[0], ast.Dict):
                info.absorb_literal(node.args[0])
            else:
                info.open = True
    return infos


def _iter_functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def extract_emit_sites(root: str,
                       log_modules: Sequence[str] = EMIT_LOG_MODULES,
                       dict_modules: Sequence[str] = EVENT_DICT_MODULES,
                       ) -> List[Dict[str, Any]]:
    """Every statically-resolvable emit site:
    ``{file, line, type, keys, open, resolved}`` — ``type`` is None for
    ``.log(expr)`` calls whose dict could not be resolved (dynamic
    sites; the runtime validator covers them)."""
    sites: List[Dict[str, Any]] = []

    def _site(rel, info: _DictInfo, line=None):
        rtype = info.event
        if rtype is None and "round" in info.keys:
            rtype = "round"
        sites.append({
            "file": rel, "line": line or info.line, "type": rtype,
            "keys": sorted(info.keys), "open": info.open, "resolved": True,
        })

    for rel in log_modules:
        path = os.path.join(root, rel)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for fn in _iter_functions(tree):
            infos = None
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "log"
                        and "logger" in _attr_base_names(node.func)):
                    continue
                arg = node.args[0] if node.args else None
                if isinstance(arg, ast.Dict):
                    info = _DictInfo(node.lineno)
                    info.absorb_literal(arg)
                    _site(rel, info, node.lineno)
                elif isinstance(arg, ast.Name):
                    if infos is None:
                        infos = _collect_fn_dicts(fn)
                    if arg.id in infos:
                        _site(rel, infos[arg.id], node.lineno)
                    else:
                        sites.append({
                            "file": rel, "line": node.lineno, "type": None,
                            "keys": [], "open": True, "resolved": False,
                        })
                else:
                    sites.append({
                        "file": rel, "line": node.lineno, "type": None,
                        "keys": [], "open": True, "resolved": False,
                    })
    for rel in dict_modules:
        path = os.path.join(root, rel)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        assigned_dicts: Set[int] = set()
        for fn in _iter_functions(tree):
            infos = _collect_fn_dicts(fn)
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                        and isinstance(node.value, ast.Dict):
                    assigned_dicts.add(id(node.value))
            for name, info in infos.items():
                if info.event is not None:
                    _site(rel, info)
        # record-constructor dicts used inline (e.g. `return {...}`)
        for node in ast.walk(tree):
            if (isinstance(node, ast.Dict) and id(node) not in assigned_dicts
                    and any(isinstance(k, ast.Constant) and k.value == "event"
                            for k in node.keys if k is not None)):
                info = _DictInfo(node.lineno)
                info.absorb_literal(node)
                if info.event is not None:
                    _site(rel, info)
    # module walks visit nested defs through their parents too — one
    # site per (file, line), first wins
    seen: Set[Tuple[str, int]] = set()
    unique: List[Dict[str, Any]] = []
    for site in sites:
        key = (site["file"], site["line"])
        if key not in seen:
            seen.add(key)
            unique.append(site)
    return unique


def _attr_base_names(node: ast.Attribute) -> Set[str]:
    names: Set[str] = set()
    cur = node.value
    while isinstance(cur, ast.Attribute):
        names.add(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        names.add(cur.id)
    return names


def check_emit_sites(root: str,
                     log_modules: Sequence[str] = EMIT_LOG_MODULES,
                     dict_modules: Sequence[str] = EVENT_DICT_MODULES,
                     ) -> Tuple[List[Dict[str, Any]],
                                List[Dict[str, Any]]]:
    """Cross-check emit sites against the registry. Returns
    (violations, sites). Module lists are injectable so seeded-violation
    fixtures can be scanned."""
    sites = extract_emit_sites(root, log_modules, dict_modules)
    violations: List[Dict[str, Any]] = []
    for site in sites:
        where = f"{site['file']}:{site['line']}"
        rtype = site["type"]
        if not site["resolved"]:
            continue  # dynamic site — the runtime validator owns it
        if rtype is None:
            violations.append({
                "kind": "emit_untyped_record", "where": where,
                "message": "emitted record has neither a constant "
                           "'event' nor a 'round' key",
            })
            continue
        spec = REGISTRY.get(rtype)
        if spec is None:
            violations.append({
                "kind": "emit_unregistered_type", "where": where,
                "message": f"record type {rtype!r} is emitted here but "
                           f"not registered in analysis/schema.py",
            })
            continue
        legal = spec.fields | UNIVERSAL_FIELDS | {"event", "round"}
        for key in site["keys"]:
            if key not in legal and not spec.open_fields:
                violations.append({
                    "kind": "emit_unregistered_field", "where": where,
                    "message": f"record type {rtype!r} emits field "
                               f"{key!r} not registered in its schema",
                })
        if not site["open"]:
            missing = spec.required - set(site["keys"]) - {"event"}
            for key in sorted(missing):
                violations.append({
                    "kind": "emit_missing_required", "where": where,
                    "message": f"record type {rtype!r} emit site lacks "
                               f"required field {key!r}",
                })
    return violations, sites


# ---------------------------------------------------------------------------
# consumer extraction
# ---------------------------------------------------------------------------

_RECORD_LIST_PARAMS = {"records", "recs"}


class _ConsumerScan:
    """Per-function record-variable inference (see module docstring)."""

    def __init__(self, record_returning: Set[str]):
        self.record_returning = record_returning
        self.types: List[Tuple[str, int]] = []     # (type literal, line)
        self.fields: List[Tuple[str, int]] = []    # (field literal, line)

    def scan(self, fn: ast.AST):
        record_vars: Set[str] = set()
        list_vars: Set[str] = set(
            a.arg for a in getattr(fn, "args", ast.arguments(
                args=[], posonlyargs=[], kwonlyargs=[], kw_defaults=[],
                defaults=[])).args
            if a.arg in _RECORD_LIST_PARAMS
        )
        event_vars: Set[str] = set()

        def is_list_expr(node) -> bool:
            if isinstance(node, ast.Name):
                return node.id in list_vars
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                if node.func.id in ("reversed", "sorted", "list") and node.args:
                    return is_list_expr(node.args[0])
            return False

        def is_record_expr(node) -> bool:
            if isinstance(node, ast.Name):
                return node.id in record_vars
            if isinstance(node, ast.Subscript) and is_list_expr(node.value):
                return True
            if isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Name)
                        and node.func.id == "next" and node.args
                        and isinstance(node.args[0], ast.GeneratorExp)
                        and is_list_expr(node.args[0].generators[0].iter)):
                    return True
                if (isinstance(node.func, ast.Name)
                        and node.func.id in self.record_returning):
                    return True
            return False

        # two fixpoint passes: comprehension targets + assignments can
        # chain (recs = [r for r in records ...]; led = recs[-1])
        for _ in range(3):
            for node in ast.walk(fn):
                if isinstance(node, (ast.For,)):
                    if is_list_expr(node.iter) and isinstance(
                            node.target, ast.Name):
                        record_vars.add(node.target.id)
                elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                       ast.SetComp)):
                    for gen in node.generators:
                        if is_list_expr(gen.iter) and isinstance(
                                gen.target, ast.Name):
                            record_vars.add(gen.target.id)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    tgt = node.targets[0].id
                    val = node.value
                    if is_record_expr(val):
                        record_vars.add(tgt)
                    elif isinstance(val, (ast.ListComp,)) and is_list_expr(
                            val.generators[0].iter):
                        list_vars.add(tgt)
                    elif (isinstance(val, ast.Call)
                            and isinstance(val.func, ast.Attribute)
                            and val.func.attr == "get"
                            and isinstance(val.func.value, ast.Name)
                            and val.func.value.id in record_vars
                            and val.args
                            and isinstance(val.args[0], ast.Constant)
                            and val.args[0].value == "event"):
                        event_vars.add(tgt)
                elif isinstance(node, ast.BoolOp):
                    # `cov = r.get("coverage") or {}` — handled above via
                    # Assign; BoolOp values don't create record vars
                    pass

        def is_event_expr(node) -> bool:
            if isinstance(node, ast.Name) and node.id in event_vars:
                return True
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in record_vars
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == "event"):
                return True
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in record_vars
                    and isinstance(node.slice, ast.Constant)
                    and node.slice.value == "event"):
                return True
            return False

        for node in ast.walk(fn):
            # consumed record types: `<event-expr> == "lit"` (+ tuples)
            if isinstance(node, ast.Compare) and is_event_expr(node.left):
                for comp in node.comparators:
                    if isinstance(comp, ast.Constant) and isinstance(
                            comp.value, str):
                        self.types.append((comp.value, node.lineno))
                    elif isinstance(comp, ast.Tuple):
                        for el in comp.elts:
                            if isinstance(el, ast.Constant) and isinstance(
                                    el.value, str):
                                self.types.append((el.value, node.lineno))
            # consumed fields
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in record_vars
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                self.fields.append((node.args[0].value, node.lineno))
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in record_vars
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                self.fields.append((node.slice.value, node.lineno))
            elif (isinstance(node, ast.Compare)
                    and isinstance(node.left, ast.Constant)
                    and isinstance(node.left.value, str)
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))
                    and isinstance(node.comparators[0], ast.Name)
                    and node.comparators[0].id in record_vars):
                self.fields.append((node.left.value, node.lineno))


def _record_returning_functions(tree: ast.Module) -> Set[str]:
    """Module functions whose return value is a record (``recs[-1]``
    style) — their callers' assignment targets become record vars."""
    out: Set[str] = set()
    for fn in _iter_functions(tree):
        scan = _ConsumerScan(set())
        # reuse the record-var inference by checking returns manually
        record_vars: Set[str] = set()
        list_vars: Set[str] = {
            a.arg for a in fn.args.args if a.arg in _RECORD_LIST_PARAMS
        }
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.ListComp) \
                    and isinstance(node.value.generators[0].iter, ast.Name) \
                    and node.value.generators[0].iter.id in list_vars:
                list_vars.add(node.targets[0].id)
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                if (isinstance(v, ast.Subscript)
                        and isinstance(v.value, ast.Name)
                        and v.value.id in list_vars):
                    out.add(fn.name)
        del scan, record_vars
    return out


def extract_consumed(root: str,
                     modules: Sequence[str] = CONSUMER_MODULES,
                     ) -> Tuple[List[Tuple[str, str, int]],
                                List[Tuple[str, str, int]]]:
    """Returns (types, fields) as lists of (literal, file, line)."""
    types: List[Tuple[str, str, int]] = []
    fields: List[Tuple[str, str, int]] = []
    for rel in modules:
        path = os.path.join(root, rel)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        returning = _record_returning_functions(tree)
        for fn in _iter_functions(tree):
            scan = _ConsumerScan(returning)
            scan.scan(fn)
            types.extend((t, rel, ln) for t, ln in scan.types)
            fields.extend((fld, rel, ln) for fld, ln in scan.fields)
    return types, fields


def check_consumers(root: str,
                    modules: Sequence[str] = CONSUMER_MODULES,
                    ) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Cross-check consumer modules against the registry."""
    types, fields = extract_consumed(root, modules)
    legal_fields = all_registered_fields()
    violations: List[Dict[str, Any]] = []
    for t, rel, ln in types:
        if t not in REGISTRY:
            violations.append({
                "kind": "consume_unregistered_type",
                "where": f"{rel}:{ln}",
                "message": f"consumer filters on record type {t!r}, "
                           f"which no emit site registers",
            })
    for fld, rel, ln in fields:
        if fld not in legal_fields:
            violations.append({
                "kind": "consume_unregistered_field",
                "where": f"{rel}:{ln}",
                "message": f"consumer reads field {fld!r}, which no "
                           f"registered record type emits",
            })
    summary = {
        "consumed_types": sorted({t for t, _, _ in types}),
        "consumed_fields": sorted({f for f, _, _ in fields}),
    }
    return violations, summary


# ---------------------------------------------------------------------------
# runtime validation (live JSONL → registry)
# ---------------------------------------------------------------------------


def validate_records(records: Iterable[Dict[str, Any]],
                     ) -> List[Dict[str, Any]]:
    """Hold a live run's JSONL to the registry: every record must carry
    a registered type, its required fields, and (unless the spec is
    open) only registered fields. The tier-1 suite runs this over a
    tiny-fit log so dynamically-keyed emits can't drift unregistered."""
    violations: List[Dict[str, Any]] = []
    for i, rec in enumerate(records):
        rtype = rec.get("event")
        if rtype is None:
            rtype = "round" if "round" in rec else None
        if rtype is None:
            violations.append({
                "kind": "record_untyped", "where": f"record[{i}]",
                "message": f"record carries neither 'event' nor 'round': "
                           f"{sorted(rec)[:8]}",
            })
            continue
        spec = REGISTRY.get(rtype)
        if spec is None:
            violations.append({
                "kind": "record_unregistered_type", "where": f"record[{i}]",
                "message": f"record type {rtype!r} is not registered",
            })
            continue
        keys = set(rec) - UNIVERSAL_FIELDS - {"event"}
        missing = spec.required - keys
        for key in sorted(missing):
            violations.append({
                "kind": "record_missing_required", "where": f"record[{i}]",
                "message": f"{rtype!r} record lacks required field {key!r}",
            })
        if not spec.open_fields:
            extras = keys - spec.fields - {"round"}
            for key in sorted(extras):
                violations.append({
                    "kind": "record_unregistered_field",
                    "where": f"record[{i}]",
                    "message": f"{rtype!r} record carries unregistered "
                               f"field {key!r}",
                })
    return violations


def check_schema(root: str) -> Dict[str, Any]:
    """The `colearn check` entry: both static cross-checks."""
    emit_violations, sites = check_emit_sites(root)
    consume_violations, consumed = check_consumers(root)
    return {
        "registered_types": sorted(REGISTRY),
        "emit_sites": len(sites),
        "emit_sites_resolved": sum(1 for s in sites if s["resolved"]),
        "violations": emit_violations + consume_violations,
        **consumed,
    }
