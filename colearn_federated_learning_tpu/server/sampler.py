"""Cohort sampler (SURVEY.md §2 C4).

Stateless-by-construction: the cohort for round ``r`` is a pure function
of ``(seed, r)`` — resume after checkpoint restore replays the exact
same schedule with no sampler state to persist (SURVEY.md §5
checkpoint/resume).
"""

from __future__ import annotations

import numpy as np


class CohortSampler:
    def __init__(self, num_clients: int, cohort_size: int, seed: int,
                 weights: np.ndarray | None = None):
        if cohort_size > num_clients:
            raise ValueError(f"cohort {cohort_size} > clients {num_clients}")
        self.num_clients = num_clients
        self.cohort_size = cohort_size
        self.seed = seed
        if weights is not None:
            w = np.asarray(weights, np.float64)
            self.probs = w / w.sum()
        else:
            self.probs = None

    def sample(self, round_idx: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, round_idx))
        return np.sort(
            rng.choice(self.num_clients, size=self.cohort_size,
                       replace=False, p=self.probs)
        )
