"""Cohort sampler (SURVEY.md §2 C4).

Stateless-by-construction for the classic modes: the cohort for round
``r`` is a pure function of ``(seed, r)`` — resume after checkpoint
restore replays the exact same schedule with no sampler state to
persist (SURVEY.md §5 checkpoint/resume).

``mode="adaptive"`` (Oort-style utility-aware selection, Lai et al.
OSDI'21; ``server.sampling="adaptive"``) relaxes that to *pure in
``(seed, r, ledger_snapshot)``*: the draw probabilities are a
deterministic function of the last client-ledger snapshot observed via
:meth:`observe_snapshot`, and the snapshot itself refreshes only at
fixed round boundaries (``run.obs.client_ledger.log_every`` multiples,
driven by the round driver) and rides the checkpoint — so a resumed
run still replays the straight run's schedule exactly, including
through a snapshot boundary (test-pinned).

``mode="streaming"`` (``server.sampling="streaming"``) is the
million-client mode: every draw is O(cohort·log), never touching a
dense ``[num_clients]`` structure. Without ledger evidence it is a
uniform without-replacement rejection draw; once the driver feeds it a
compact *score sketch* (the columnar ``{ids, count, flagged,
ema_loss}`` table of observed clients — bounded by
``server.adaptive.sketch_size``), draws score the SAME Oort formula as
"adaptive" over the sketch rows plus a closed-form optimistic pool for
the (num_clients − sketch) unseen clients. Pure in ``(seed, r,
sketch)`` — same resume-replay contract as adaptive — but a different
deterministic sequence than the dense modes (different draw
algorithm; documented, and the parity pins always compare runs using
the same mode).

Snapshots are COLUMN-SLIMMED (PR 9): the sampler consumes only the
three ledger columns it scores — :data:`SNAPSHOT_COLS` = (count,
flagged, ema_loss) — as a dense ``[num_clients, 3]`` block (adaptive)
or the columnar sketch dict (streaming), never the full
``[num_clients, LEDGER_WIDTH]`` row block. The driver's snapshot fetch
and the checkpointed sampler state shrink accordingly.
"""

from __future__ import annotations

from typing import Dict, Optional, Union

import numpy as np

# the ledger columns the sampler scores, in snapshot-column order
# (obs/ledger.py LEDGER_COLS names; the driver slices these out of the
# fetched ledger — the sampler never sees the other stat columns)
SNAPSHOT_COLS = ("count", "flagged", "ema_loss")
_COUNT, _FLAGGED, _EMA_LOSS = 0, 1, 2

# streaming rejection draws: expected retries are ~1 at K << N; this is
# a pure safety net against adversarial (explore≈0, mass-concentrated)
# corners — the deterministic sweep below it keeps sample() total
_MAX_DRAW_TRIES_PER_SLOT = 512

Snapshot = Union[np.ndarray, Dict[str, np.ndarray], None]


class CohortSampler:
    def __init__(self, num_clients: int, cohort_size: int, seed: int,
                 weights: np.ndarray | None = None,
                 mode: str = "fixed",
                 explore: float = 0.1,
                 staleness_gain: float = 1.0,
                 flag_suppress: float = 4.0,
                 sketch_size: int = 4096,
                 availability_fn=None,
                 id_base: int = 0):
        if cohort_size > num_clients:
            raise ValueError(f"cohort {cohort_size} > clients {num_clients}")
        if mode not in ("fixed", "poisson", "adaptive", "streaming"):
            raise ValueError(f"unknown sampler mode {mode!r}")
        # Sub-population partition offset (server.hierarchy): the
        # sampler draws over ``num_clients`` LOCAL slots but returns
        # (and gates availability on) GLOBAL ids ``local + id_base`` —
        # an edge aggregator's sampler covers exactly its contiguous
        # block of the universe. 0 = the whole-population sampler,
        # bitwise-unchanged.
        if id_base and mode != "fixed":
            raise ValueError(
                f"id_base partitioning supports mode='fixed' only "
                f"(per-edge blocks re-parameterize poisson q / adaptive "
                f"scores / streaming sketches), not {mode!r}"
            )
        self.id_base = int(id_base)
        # Churn gating (run.churn, server/churn.py): a PURE predicate
        # ``(round_idx, ids) -> bool[len(ids)]`` — offline clients are
        # rejected from the draw. Purity is what keeps the schedule a
        # function of (seed, round[, sketch]) so resume/prefetch
        # replay it; config.validate() restricts the pairing to the
        # uniform and streaming modes.
        if availability_fn is not None and mode not in ("fixed", "streaming"):
            raise ValueError(
                f"availability gating supports mode='fixed' (uniform) "
                f"or 'streaming', not {mode!r}"
            )
        if availability_fn is not None and weights is not None:
            raise ValueError(
                "availability gating is incompatible with static "
                "sampling weights (the gated draw is uniform over the "
                "online set)"
            )
        self.availability_fn = availability_fn
        self.num_clients = num_clients
        self.cohort_size = cohort_size
        self.seed = seed
        self.mode = mode
        self.explore = float(explore)
        self.staleness_gain = float(staleness_gain)
        self.flag_suppress = float(flag_suppress)
        self.sketch_size = int(sketch_size)
        # adaptive: the last observed ledger snapshot (None until the
        # driver feeds one — the all-unseen prior is a uniform draw);
        # streaming keeps the columnar sketch instead
        self.snapshot_round: int = 0
        self._sketch: Optional[Dict[str, np.ndarray]] = None
        # Draw-provenance tally (obs/population.py sampler-health
        # plane): which pool each round's accepted draws came from —
        # {explore, scored, unseen, backstop, uniform}. Purely
        # observational (tallied AFTER the rng consumption the draw
        # already did, so schedules are bitwise-unchanged); keyed by
        # round because the prefetch/native paths sample ahead, bounded
        # so an unconsumed tail can never grow with the run. Repeat
        # sample() calls for the same round (native lookahead) overwrite
        # with identical values — draws are pure in (seed, round, state).
        self._draw_stats: Dict[int, Dict[str, int]] = {}
        if weights is not None:
            if mode in ("poisson", "adaptive", "streaming"):
                raise ValueError(
                    "static weights only apply to mode='fixed' (poisson "
                    "is unweighted q = K/N; adaptive/streaming derive "
                    "their own scores from the ledger)"
                )
            w = np.asarray(weights, np.float64)
            # a silent NaN here used to surface rounds later as an
            # opaque rng.choice "probabilities do not sum to 1" error —
            # reject the malformed weights where they enter instead
            if w.shape != (num_clients,):
                raise ValueError(
                    f"sampler weights shape {w.shape} != ({num_clients},)"
                )
            if not np.all(np.isfinite(w)):
                raise ValueError(
                    "sampler weights must be finite (got NaN/Inf entries)"
                )
            if (w < 0).any():
                raise ValueError(
                    f"sampler weights must be non-negative "
                    f"(min {w.min():.3g})"
                )
            if w.sum() <= 0.0:
                raise ValueError(
                    "sampler weights sum to zero — every client would "
                    "have an undefined draw probability"
                )
            self.probs = w / w.sum()
        else:
            self.probs = None

    @property
    def q(self) -> float:
        """Per-client per-round participation probability (poisson)."""
        return self.cohort_size / self.num_clients

    # ---- adaptive scoring (modes "adaptive" and "streaming") ---------

    def observe_snapshot(self, snapshot: Snapshot, round_idx: int) -> None:
        """Refresh the draw scores from a ledger snapshot. Accepts a
        dense ``[num_clients, 3]`` block in :data:`SNAPSHOT_COLS` order
        (the adaptive checkpoint form), a columnar dict ``{"ids",
        "count", "flagged", "ema_loss"}`` of observed clients only (the
        streaming sketch form — O(observed), never O(num_clients)), or
        None (reset to the uniform all-unseen prior). Deterministic:
        the same (snapshot, round) always yields the same draw
        distribution, so the schedule stays replayable across resume."""
        if self.mode not in ("adaptive", "streaming"):
            raise ValueError(
                f"observe_snapshot only applies to mode='adaptive' or "
                f"'streaming' (this sampler is {self.mode!r})"
            )
        self.snapshot_round = int(round_idx)
        if snapshot is None:
            self.probs = None
            self._sketch = None
            return
        if isinstance(snapshot, dict):
            ids = np.asarray(snapshot["ids"], np.int64)
            cols = {
                c: np.asarray(snapshot[c], np.float64) for c in SNAPSHOT_COLS
            }
            if any(v.shape != ids.shape for v in cols.values()):
                raise ValueError(
                    "snapshot columns must all match ids in shape"
                )
            if ids.size and (ids.min() < 0 or ids.max() >= self.num_clients):
                raise ValueError(
                    f"snapshot ids out of range [0, {self.num_clients})"
                )
            if self.mode == "streaming":
                self._sketch = self._cap_sketch(ids, cols)
                return
            dense = np.zeros((self.num_clients, len(SNAPSHOT_COLS)))
            for j, c in enumerate(SNAPSHOT_COLS):
                dense[ids, j] = cols[c]
        else:
            dense = np.asarray(snapshot, np.float64)
            if dense.shape != (self.num_clients, len(SNAPSHOT_COLS)):
                raise ValueError(
                    f"dense snapshot must be [num_clients, "
                    f"{len(SNAPSHOT_COLS)}] in {SNAPSHOT_COLS} order; got "
                    f"shape {dense.shape} for {self.num_clients} clients"
                )
            if self.mode == "streaming":
                ids = np.flatnonzero(dense[:, _COUNT] > 0)
                self._sketch = self._cap_sketch(ids, {
                    c: dense[ids, j] for j, c in enumerate(SNAPSHOT_COLS)
                })
                return
        self.probs = self._adaptive_probs(dense, self.snapshot_round)

    def _cap_sketch(self, ids, cols) -> Optional[Dict[str, np.ndarray]]:
        """Bound the sketch at ``sketch_size`` rows, keeping the
        highest-participation clients (ties broken by id — the same
        deterministic priority the driver's checkpointed sketch uses)."""
        if ids.size == 0:
            return None
        if len(ids) > self.sketch_size:
            order = np.lexsort((ids, -cols["count"]))[: self.sketch_size]
            keep = np.sort(ids[order])
            sel = np.isin(ids, keep)
            ids = ids[sel]
            cols = {c: v[sel] for c, v in cols.items()}
        return {"ids": ids, **cols}

    def _adaptive_probs(self, led: np.ndarray,
                        snap_round: int) -> Optional[np.ndarray]:
        """Oort-style scores → draw probabilities (dense adaptive mode).
        Per client: loss-utility EMA (unseen clients take the max seen
        utility — optimistic initialization, so exploration is eager
        rather than starved) × a participation-staleness boost (deficit
        vs the uniform expectation ``round·K/N``) × exponential
        suppression of high-flag-rate clients; then the exploration
        floor mixes ``explore/N`` uniformly so no client's probability
        ever reaches zero."""
        count = led[:, _COUNT]
        seen = count > 0
        if not seen.any():
            return None  # all-unseen prior: uniform draw
        util = np.where(seen, np.maximum(led[:, _EMA_LOSS], 0.0), 0.0)
        max_seen = float(util[seen].max())
        util = np.where(seen, util, max(max_seen, 1e-6))
        flag_rate = np.where(seen, led[:, _FLAGGED] / np.maximum(count, 1.0),
                             0.0)
        expected = snap_round * self.cohort_size / self.num_clients
        deficit = np.maximum(expected - count, 0.0)
        staleness = 1.0 + self.staleness_gain * deficit / max(expected, 1.0)
        score = (
            (util + 1e-6) * staleness * np.exp(-self.flag_suppress * flag_rate)
        )
        total = score.sum()
        if not np.isfinite(total) or total <= 0.0:
            return None
        probs = (
            (1.0 - self.explore) * score / total
            + self.explore / self.num_clients
        )
        return probs / probs.sum()  # exact renormalization for rng.choice

    def _sketch_scores(self):
        """(per-row scores, unseen-pool per-client score) — the SAME
        Oort formula as :meth:`_adaptive_probs`, evaluated only over
        the sketch's observed rows plus one closed-form score shared by
        every unseen client (count 0 ⇒ optimistic max-seen utility, the
        full staleness boost, flag rate 0)."""
        sk = self._sketch
        count = sk["count"]
        util = np.maximum(sk["ema_loss"], 0.0)
        opt = max(float(util.max()) if len(util) else 0.0, 1e-6)
        flag_rate = sk["flagged"] / np.maximum(count, 1.0)
        expected = self.snapshot_round * self.cohort_size / self.num_clients
        deficit = np.maximum(expected - count, 0.0)
        staleness = 1.0 + self.staleness_gain * deficit / max(expected, 1.0)
        scores = (
            (util + 1e-6) * staleness * np.exp(-self.flag_suppress * flag_rate)
        )
        unseen_staleness = (
            1.0 + self.staleness_gain * expected / max(expected, 1.0)
        )
        unseen = (opt + 1e-6) * unseen_staleness
        return scores, unseen

    # ---- streaming draw ----------------------------------------------

    def _fill_deterministic(self, out: set) -> None:
        """Pathological-corner backstop (the rejection loop exhausted
        its try budget): complete the cohort with the smallest unchosen
        ids — still deterministic, never an infinite loop."""
        for c in range(self.num_clients):
            if len(out) >= self.cohort_size:
                return
            out.add(c)

    def _sample_streaming(self, rng, round_idx: int) -> np.ndarray:
        """O(cohort·log sketch) cohort draw: each slot draws from the
        exploration floor (uniform over all N), the sketch table
        (binary search over the score cumsum), or the unseen pool
        (uniform with seen-ids rejection) — duplicates rejected, so the
        cohort is without replacement like the dense modes. No dense
        [num_clients] structure is ever built. Accepted draws are
        tallied by pool into ``self._last_draws`` (the population
        tracker's exploration/exploitation split) — observation only,
        the rng stream is exactly the pre-tally stream."""
        n, k = self.num_clients, self.cohort_size
        draws = {"explore": 0, "scored": 0, "unseen": 0}
        if self.availability_fn is not None:
            # churn gating: offline candidates rejected (tallied for
            # the population draw-split panel; observation only)
            draws["offline"] = 0
        self._last_draws = draws
        out: set = set()
        sk = self._sketch
        if sk is None:
            cum = np.zeros(0)
            ids = np.zeros(0, np.int64)
            id_set: set = set()
            total_obs = total = 0.0
        else:
            scores, unseen = self._sketch_scores()
            ids = sk["ids"]
            id_set = {int(i) for i in ids}
            cum = np.cumsum(scores)
            total_obs = float(cum[-1]) if len(cum) else 0.0
            total = total_obs + (n - len(ids)) * unseen
            if not np.isfinite(total) or total <= 0.0:
                total = total_obs = 0.0
        budget = _MAX_DRAW_TRIES_PER_SLOT * k
        while len(out) < k and budget > 0:
            budget -= 1
            if total <= 0.0 or rng.random() < self.explore:
                cand = int(rng.integers(n))  # exploration floor: uniform
                pool = "explore"
            else:
                v = rng.random() * total
                if v < total_obs:
                    cand = int(ids[int(np.searchsorted(cum, v, side="right"))])
                    pool = "scored"
                else:
                    cand = int(rng.integers(n))  # unseen pool
                    pool = "unseen"
                    if cand in id_set:
                        continue  # landed on a seen id: not this pool's
            if cand in out:
                continue
            if (self.availability_fn is not None
                    and not bool(self.availability_fn(
                        round_idx, np.asarray([cand], np.int64))[0])):
                # offline this round (run.churn): reject and redraw —
                # the predicate is pure in (round, id), so the rng
                # stream (and hence the schedule) stays replayable
                draws["offline"] += 1
                continue
            out.add(cand)
            draws[pool] += 1
        if len(out) < k:
            draws["backstop"] = k - len(out)
            self._fill_deterministic(out)
        return np.sort(np.fromiter(out, np.int64, len(out)))

    # ------------------------------------------------------------------

    def _note_draws(self, round_idx: int, counts: Dict[str, int]) -> None:
        self._draw_stats[int(round_idx)] = {
            k: v for k, v in counts.items() if v
        }
        if len(self._draw_stats) > 128:
            # an unconsumed tail (population tracking off, or prefetch
            # sampling far ahead) must stay bounded
            self._draw_stats.pop(min(self._draw_stats))

    def take_draw_stats(self, round_idx: int) -> Optional[Dict[str, int]]:
        """Pop the draw-provenance tally for one round (None when that
        round was never sampled, e.g. fedbuff's queue scheduler)."""
        return self._draw_stats.pop(int(round_idx), None)

    def sample(self, round_idx: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, round_idx))
        if self.mode == "poisson":
            # independent Bernoulli(q) per client — the sampling scheme
            # under which the Poisson subsampled-Gaussian RDP bound is
            # EXACT. Realized size is Binomial(N, q); the driver pads to
            # its static cap. A zero-participant round is legitimate
            # (the engine's degenerate-denominator path handles it).
            out = np.flatnonzero(rng.random(self.num_clients) < self.q)
            self._note_draws(round_idx, {"uniform": len(out)})
            return out
        if self.mode == "streaming":
            out = self._sample_streaming(rng, round_idx)
            self._note_draws(round_idx, self._last_draws)
            return out
        if self.mode == "fixed" and self.availability_fn is not None:
            # availability-gated uniform draw (run.churn): uniform
            # without replacement over the ONLINE set. When the
            # diurnal trough leaves fewer online clients than the
            # cohort, every online client participates and the
            # smallest offline ids fill the remaining slots
            # deterministically — they realize as churn dropouts in
            # the driver's failure path, which is exactly what
            # dispatching to an offline device does.
            all_ids = np.arange(self.num_clients) + self.id_base
            online = all_ids[self.availability_fn(round_idx, all_ids)]
            if len(online) >= self.cohort_size:
                out = np.sort(rng.choice(
                    online, size=self.cohort_size, replace=False
                ))
            else:
                offline = np.setdiff1d(all_ids, online)
                fill = offline[: self.cohort_size - len(online)]
                out = np.sort(np.concatenate([online, fill]))
            self._note_draws(round_idx, {"uniform": len(out)})
            return out
        out = np.sort(
            rng.choice(self.num_clients, size=self.cohort_size,
                       replace=False, p=self.probs)
        )
        if self.id_base:
            out = out + self.id_base
        # dense modes draw all slots from one distribution: "scored"
        # when ledger/static weights shaped it (adaptive past the first
        # snapshot, mode="weighted"), the uniform prior otherwise
        self._note_draws(round_idx, {
            ("scored" if self.probs is not None else "uniform"): len(out)
        })
        return out
