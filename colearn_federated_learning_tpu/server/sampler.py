"""Cohort sampler (SURVEY.md §2 C4).

Stateless-by-construction for the classic modes: the cohort for round
``r`` is a pure function of ``(seed, r)`` — resume after checkpoint
restore replays the exact same schedule with no sampler state to
persist (SURVEY.md §5 checkpoint/resume).

``mode="adaptive"`` (Oort-style utility-aware selection, Lai et al.
OSDI'21; ``server.sampling="adaptive"``) relaxes that to *pure in
``(seed, r, ledger_snapshot)``*: the draw probabilities are a
deterministic function of the last client-ledger snapshot observed via
:meth:`observe_snapshot`, and the snapshot itself refreshes only at
fixed round boundaries (``run.obs.client_ledger.log_every`` multiples,
driven by the round driver) and rides the checkpoint — so a resumed
run still replays the straight run's schedule exactly, including
through a snapshot boundary (test-pinned).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# ledger column indices the adaptive score reads (obs/ledger.py
# LEDGER_COLS order: count, flagged, ema_l2, ema_cos, ema_resid,
# ema_loss, ema_z)
_COUNT, _FLAGGED, _EMA_LOSS = 0, 1, 5


class CohortSampler:
    def __init__(self, num_clients: int, cohort_size: int, seed: int,
                 weights: np.ndarray | None = None,
                 mode: str = "fixed",
                 explore: float = 0.1,
                 staleness_gain: float = 1.0,
                 flag_suppress: float = 4.0):
        if cohort_size > num_clients:
            raise ValueError(f"cohort {cohort_size} > clients {num_clients}")
        if mode not in ("fixed", "poisson", "adaptive"):
            raise ValueError(f"unknown sampler mode {mode!r}")
        self.num_clients = num_clients
        self.cohort_size = cohort_size
        self.seed = seed
        self.mode = mode
        self.explore = float(explore)
        self.staleness_gain = float(staleness_gain)
        self.flag_suppress = float(flag_suppress)
        # adaptive: the last observed ledger snapshot (None until the
        # driver feeds one — the all-unseen prior is a uniform draw)
        self.snapshot_round: int = 0
        if weights is not None:
            if mode in ("poisson", "adaptive"):
                raise ValueError(
                    "static weights only apply to mode='fixed' (poisson "
                    "is unweighted q = K/N; adaptive derives its own "
                    "scores from the ledger)"
                )
            w = np.asarray(weights, np.float64)
            # a silent NaN here used to surface rounds later as an
            # opaque rng.choice "probabilities do not sum to 1" error —
            # reject the malformed weights where they enter instead
            if w.shape != (num_clients,):
                raise ValueError(
                    f"sampler weights shape {w.shape} != ({num_clients},)"
                )
            if not np.all(np.isfinite(w)):
                raise ValueError(
                    "sampler weights must be finite (got NaN/Inf entries)"
                )
            if (w < 0).any():
                raise ValueError(
                    f"sampler weights must be non-negative "
                    f"(min {w.min():.3g})"
                )
            if w.sum() <= 0.0:
                raise ValueError(
                    "sampler weights sum to zero — every client would "
                    "have an undefined draw probability"
                )
            self.probs = w / w.sum()
        else:
            self.probs = None

    @property
    def q(self) -> float:
        """Per-client per-round participation probability (poisson)."""
        return self.cohort_size / self.num_clients

    # ---- adaptive scoring (mode="adaptive") --------------------------

    def observe_snapshot(self, ledger: Optional[np.ndarray],
                         round_idx: int) -> None:
        """Refresh the adaptive draw probabilities from a host-side
        ledger snapshot (``[num_clients, LEDGER_WIDTH]``; None resets
        to the uniform prior). Deterministic: the same (snapshot,
        round) always yields the same probabilities, so the schedule
        stays replayable across resume."""
        if self.mode != "adaptive":
            raise ValueError(
                f"observe_snapshot only applies to mode='adaptive' "
                f"(this sampler is {self.mode!r})"
            )
        self.snapshot_round = int(round_idx)
        if ledger is None:
            self.probs = None
            return
        led = np.asarray(ledger, np.float64)
        if led.shape[0] != self.num_clients:
            raise ValueError(
                f"ledger snapshot has {led.shape[0]} rows, sampler "
                f"tracks {self.num_clients} clients"
            )
        self.probs = self._adaptive_probs(led, self.snapshot_round)

    def _adaptive_probs(self, led: np.ndarray,
                        snap_round: int) -> Optional[np.ndarray]:
        """Oort-style scores → draw probabilities. Per client:
        loss-utility EMA (unseen clients take the max seen utility —
        optimistic initialization, so exploration is eager rather than
        starved) × a participation-staleness boost (deficit vs the
        uniform expectation ``round·K/N``) × exponential suppression of
        high-flag-rate clients; then the exploration floor mixes
        ``explore/N`` uniformly so no client's probability ever reaches
        zero."""
        count = led[:, _COUNT]
        seen = count > 0
        if not seen.any():
            return None  # all-unseen prior: uniform draw
        util = np.where(seen, np.maximum(led[:, _EMA_LOSS], 0.0), 0.0)
        max_seen = float(util[seen].max())
        util = np.where(seen, util, max(max_seen, 1e-6))
        flag_rate = np.where(seen, led[:, _FLAGGED] / np.maximum(count, 1.0),
                             0.0)
        expected = snap_round * self.cohort_size / self.num_clients
        deficit = np.maximum(expected - count, 0.0)
        staleness = 1.0 + self.staleness_gain * deficit / max(expected, 1.0)
        score = (
            (util + 1e-6) * staleness * np.exp(-self.flag_suppress * flag_rate)
        )
        total = score.sum()
        if not np.isfinite(total) or total <= 0.0:
            return None
        probs = (
            (1.0 - self.explore) * score / total
            + self.explore / self.num_clients
        )
        return probs / probs.sum()  # exact renormalization for rng.choice

    # ------------------------------------------------------------------

    def sample(self, round_idx: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, round_idx))
        if self.mode == "poisson":
            # independent Bernoulli(q) per client — the sampling scheme
            # under which the Poisson subsampled-Gaussian RDP bound is
            # EXACT. Realized size is Binomial(N, q); the driver pads to
            # its static cap. A zero-participant round is legitimate
            # (the engine's degenerate-denominator path handles it).
            return np.flatnonzero(rng.random(self.num_clients) < self.q)
        return np.sort(
            rng.choice(self.num_clients, size=self.cohort_size,
                       replace=False, p=self.probs)
        )
