"""Cohort sampler (SURVEY.md §2 C4).

Stateless-by-construction: the cohort for round ``r`` is a pure function
of ``(seed, r)`` — resume after checkpoint restore replays the exact
same schedule with no sampler state to persist (SURVEY.md §5
checkpoint/resume).
"""

from __future__ import annotations

import numpy as np


class CohortSampler:
    def __init__(self, num_clients: int, cohort_size: int, seed: int,
                 weights: np.ndarray | None = None,
                 mode: str = "fixed"):
        if cohort_size > num_clients:
            raise ValueError(f"cohort {cohort_size} > clients {num_clients}")
        if mode not in ("fixed", "poisson"):
            raise ValueError(f"unknown sampler mode {mode!r}")
        self.num_clients = num_clients
        self.cohort_size = cohort_size
        self.seed = seed
        self.mode = mode
        if weights is not None:
            if mode == "poisson":
                raise ValueError("poisson sampling is unweighted (q = K/N)")
            w = np.asarray(weights, np.float64)
            self.probs = w / w.sum()
        else:
            self.probs = None

    @property
    def q(self) -> float:
        """Per-client per-round participation probability (poisson)."""
        return self.cohort_size / self.num_clients

    def sample(self, round_idx: int) -> np.ndarray:
        rng = np.random.default_rng((self.seed, round_idx))
        if self.mode == "poisson":
            # independent Bernoulli(q) per client — the sampling scheme
            # under which the Poisson subsampled-Gaussian RDP bound is
            # EXACT. Realized size is Binomial(N, q); the driver pads to
            # its static cap. A zero-participant round is legitimate
            # (the engine's degenerate-denominator path handles it).
            return np.flatnonzero(rng.random(self.num_clients) < self.q)
        return np.sort(
            rng.choice(self.num_clients, size=self.cohort_size,
                       replace=False, p=self.probs)
        )
