"""Aggregation + server optimizer (SURVEY.md §2 C6; call stack §3.4).

The math: ``Δ̄ = Σᵢ nᵢ·Δᵢ / Σᵢ nᵢ`` over the cohort (the reference
realizes the same weighted-sum as an NCCL allreduce, BASELINE.json:5;
the shard_map engine realizes it as ``jax.lax.psum`` — see
parallel/round_engine.py — and this module is the shared host-side /
server-update half).

We aggregate **deltas** (wᵢ − w_global) rather than raw params so a
server-side optimizer (FedAvgM / FedAdam, Reddi et al. 2021) can treat
−Δ̄ as a pseudo-gradient. With the default ``mean`` optimizer and
server_lr=1 this is exactly classic FedAvg.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import optax

from colearn_federated_learning_tpu.config import ServerConfig
from colearn_federated_learning_tpu.utils import trees


def weighted_delta_mean(deltas, weights):
    """Host-side reference weighted mean over a list of delta pytrees."""
    return trees.tree_weighted_mean(deltas, weights)


def robust_reduce(deltas, participation, mode: str, trim_ratio: float = 0.1):
    """Coordinate-wise Byzantine-robust aggregate of stacked client deltas.

    ``deltas``: ``[K, ...]`` tree (the cohort's updates); ``participation``:
    ``[K]`` 0/1 — non-participants (dropout, empty shards) are excluded
    EXACTLY, via an input-independent trick that keeps shapes static: their
    rows are set to +inf before a per-coordinate sort, so they land past
    every participant, and the order statistics index only the first
    ``m = Σ participation`` rows (dynamic scalar, static shapes — XLA
    sorts are oblivious to m). Modes:

    - ``"median"``    — coordinate-wise median over participants (Yin et
      al. 2018); tolerates < m/2 corrupted clients per coordinate.
    - ``"trimmed_mean"`` — drop ``⌊trim_ratio·m⌋`` smallest and largest
      values per coordinate, average the rest (0 ≤ ratio < 0.5).

    Robust statistics are unweighted by design (a weighted median would
    re-open the attack surface weights provide). Math in f32. The result
    feeds the server optimizer exactly like the weighted mean."""
    part = participation.astype(jnp.float32)
    m = part.sum().astype(jnp.int32)
    k = part.shape[0]
    iota = jnp.arange(k)

    def leaf(d):
        pb = part.reshape((k,) + (1,) * (d.ndim - 1))
        s = jnp.sort(
            jnp.where(pb > 0, d.astype(jnp.float32), jnp.inf), axis=0
        )
        if mode == "median":
            lo = jnp.clip((m - 1) // 2, 0, k - 1)
            hi = jnp.clip(m // 2, 0, k - 1)
            med = 0.5 * (jnp.take(s, lo, axis=0) + jnp.take(s, hi, axis=0))
            return jnp.where(m > 0, med, 0.0)
        if mode != "trimmed_mean":
            raise ValueError(f"unknown robust aggregator {mode!r}")
        t = jnp.floor(trim_ratio * m.astype(jnp.float32)).astype(jnp.int32)
        keep = ((iota >= t) & (iota < m - t)).astype(jnp.float32)
        keep = keep.reshape((k,) + (1,) * (d.ndim - 1))
        cnt = jnp.maximum((m - 2 * t).astype(jnp.float32), 1.0)
        # zero dropped rows BEFORE multiplying: 0·inf would be NaN
        return (jnp.where(keep > 0, s, 0.0)).sum(0) / cnt

    return jax.tree.map(leaf, deltas)


def make_server_optimizer(cfg: ServerConfig) -> optax.GradientTransformation:
    if cfg.optimizer == "mean":
        return optax.sgd(cfg.server_lr)
    if cfg.optimizer == "fedavgm":
        return optax.sgd(cfg.server_lr, momentum=cfg.server_momentum)
    if cfg.optimizer == "fedadam":
        return optax.adam(cfg.server_lr, eps=1e-3)
    if cfg.optimizer == "fedyogi":
        # Reddi et al. 2021 (Adaptive Federated Optimization) — yogi's
        # additive second-moment update resists the per-round pseudo-
        # gradient variance that makes fedadam's v_t collapse early.
        return optax.yogi(cfg.server_lr, eps=1e-3)
    raise ValueError(f"unknown server optimizer {cfg.optimizer!r}")


def make_server_update_fn(cfg: ServerConfig):
    """(params, opt_state, mean_delta) → (new_params, new_opt_state).

    Feeds ``−Δ̄`` to optax as the gradient, so every optax transform is a
    valid server optimizer. The state carries a monotone round counter
    (``"round"``) alongside the optax state — the round engine reads it
    to compute round-indexed schedules (client LR decay) *inside* the
    compiled program, so schedules need no extra traced inputs.

    Format note: the ``{"round", "opt"}`` wrapper was introduced in
    round 2 of this build — checkpoints written by earlier builds (raw
    optax state) are not restorable against the current template. No
    migration shim is shipped: there are no deployed checkpoints of the
    old format (run artifacts were never part of the repo).
    """
    opt = make_server_optimizer(cfg)

    def init(params) -> Any:
        return {"round": jnp.zeros((), jnp.int32), "opt": opt.init(params)}

    def update(params, opt_state, mean_delta) -> Tuple[Any, Any]:
        pseudo_grad = jax.tree.map(jnp.negative, mean_delta)
        updates, new_opt = opt.update(pseudo_grad, opt_state["opt"], params)
        new_state = {"round": opt_state["round"] + 1, "opt": new_opt}
        return optax.apply_updates(params, updates), new_state

    return init, update
