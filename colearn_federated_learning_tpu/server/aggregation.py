"""Aggregation + server optimizer (SURVEY.md §2 C6; call stack §3.4).

The math: ``Δ̄ = Σᵢ nᵢ·Δᵢ / Σᵢ nᵢ`` over the cohort (the reference
realizes the same weighted-sum as an NCCL allreduce, BASELINE.json:5;
the shard_map engine realizes it as ``jax.lax.psum`` — see
parallel/round_engine.py — and this module is the shared host-side /
server-update half).

We aggregate **deltas** (wᵢ − w_global) rather than raw params so a
server-side optimizer (FedAvgM / FedAdam, Reddi et al. 2021) can treat
−Δ̄ as a pseudo-gradient. With the default ``mean`` optimizer and
server_lr=1 this is exactly classic FedAvg.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import optax

from colearn_federated_learning_tpu.config import ServerConfig
from colearn_federated_learning_tpu.utils import trees


def weighted_delta_mean(deltas, weights):
    """Host-side reference weighted mean over a list of delta pytrees."""
    return trees.tree_weighted_mean(deltas, weights)


def reputation_weights(ledger, cohort_ids, floor: float, strength: float,
                       z_gain: float, zmax: float):
    """``[K]`` multiplicative trust weights for one round's cohort from
    the device-resident ``[num_clients, LEDGER_WIDTH]`` forensic ledger
    (obs/ledger.py; ``server.reputation``). Per cohort member::

        flag_rate = flagged / max(count, 1)
        excess_z  = max(ema_z / zmax - 1, 0)      # above-threshold only
        score     = flag_rate + z_gain * excess_z
        trust     = floor + (1 - floor) * exp(-strength * score)

    Unseen clients (``count == 0``) — and poisson pad slots, whose
    out-of-range id makes ``take`` fill a zero row — get trust exactly
    1.0: reputation only ever acts on ledger EVIDENCE, so round 1 is a
    plain weighted mean and a fresh client enters at full voice. The
    trust derives from the ledger AS CARRIED INTO the round (the
    round's own stats scatter lands after aggregation), all in f32 with
    one shared implementation for the sharded program, the sequential
    oracle, and the fused scan body — cross-engine parity by
    construction, exactly like ``client_round_stats``. Runs as plain
    jnp under the round jit: zero extra host round-trips."""
    rows = ledger.shape[0]
    ids = jnp.where(
        (cohort_ids >= 0) & (cohort_ids < rows),
        cohort_ids.astype(jnp.int32), jnp.int32(rows),
    )
    row = jnp.take(ledger, ids, axis=0, mode="fill", fill_value=0.0)
    count = row[:, 0]
    flag_rate = row[:, 1] / jnp.maximum(count, 1.0)
    excess_z = jnp.maximum(row[:, 6] / jnp.float32(zmax) - 1.0, 0.0)
    score = flag_rate + jnp.float32(z_gain) * excess_z
    trust = jnp.float32(floor) + jnp.float32(1.0 - floor) * jnp.exp(
        -jnp.float32(strength) * score
    )
    return jnp.where(count > 0, trust, 1.0).astype(jnp.float32)


def scale_deltas_by_trust(deltas, trust):
    """Scale a ``[K, ...]`` stacked delta tree by per-client trust — the
    reputation hook for the ROBUST aggregators, whose order statistics
    are unweighted by design (a weighted median would re-open the
    attack surface weights provide): a suppressed client's upload
    shrinks toward the zero update instead of being hard-ejected, so a
    false flag costs a fraction of one update rather than a cohort
    slot. Shared by both engines."""
    return jax.tree.map(
        lambda d: d * trust.reshape(
            (trust.shape[0],) + (1,) * (d.ndim - 1)
        ).astype(d.dtype),
        deltas,
    )


def robust_reduce(deltas, participation, mode: str, trim_ratio: float = 0.1,
                  byzantine_f: int = 0):
    """Byzantine-robust aggregate of stacked client deltas.

    ``deltas``: ``[K, ...]`` tree (the cohort's updates); ``participation``:
    ``[K]`` 0/1 — non-participants (dropout, empty shards) are excluded
    EXACTLY, via an input-independent trick that keeps shapes static: their
    rows are set to +inf before a per-coordinate sort, so they land past
    every participant, and the order statistics index only the first
    ``m = Σ participation`` rows (dynamic scalar, static shapes — XLA
    sorts are oblivious to m). Modes:

    - ``"median"``    — coordinate-wise median over participants (Yin et
      al. 2018); tolerates < m/2 corrupted clients per coordinate.
    - ``"trimmed_mean"`` — drop ``⌊trim_ratio·m⌋`` smallest and largest
      values per coordinate, average the rest (0 ≤ ratio < 0.5).
    - ``"krum"``      — selection (Blanchard et al. 2017): return the ONE
      participant delta whose summed squared distance to its
      ``m − byzantine_f − 2`` nearest participant neighbours is
      smallest (clamped ≥ 1 neighbour). Whole-update selection — a
      poisoned update is discarded entirely rather than per-coordinate.

    Robust statistics are unweighted by design (a weighted median would
    re-open the attack surface weights provide). Math in f32. The result
    feeds the server optimizer exactly like the weighted mean."""
    if mode == "krum":
        return _krum(deltas, participation, byzantine_f)
    part = participation.astype(jnp.float32)
    m = part.sum().astype(jnp.int32)
    k = part.shape[0]
    iota = jnp.arange(k)

    def leaf(d):
        pb = part.reshape((k,) + (1,) * (d.ndim - 1))
        s = jnp.sort(
            jnp.where(pb > 0, d.astype(jnp.float32), jnp.inf), axis=0
        )
        if mode == "median":
            lo = jnp.clip((m - 1) // 2, 0, k - 1)
            hi = jnp.clip(m // 2, 0, k - 1)
            med = 0.5 * (jnp.take(s, lo, axis=0) + jnp.take(s, hi, axis=0))
            return jnp.where(m > 0, med, 0.0)
        if mode != "trimmed_mean":
            raise ValueError(f"unknown robust aggregator {mode!r}")
        t = jnp.floor(trim_ratio * m.astype(jnp.float32)).astype(jnp.int32)
        keep = ((iota >= t) & (iota < m - t)).astype(jnp.float32)
        keep = keep.reshape((k,) + (1,) * (d.ndim - 1))
        cnt = jnp.maximum((m - 2 * t).astype(jnp.float32), 1.0)
        # zero dropped rows BEFORE multiplying: 0·inf would be NaN
        return (jnp.where(keep > 0, s, 0.0)).sum(0) / cnt

    return jax.tree.map(leaf, deltas)


def krum_select(deltas, participation, byzantine_f: int):
    """The selection half of krum: ``(winner index, participant count)``
    over a [K, ...] delta stack. Split out of :func:`_krum` so the
    fused server-apply path (``server.fused_apply``) can turn the
    winner into a one-hot reduction row for the pallas kernel while
    ``_krum`` itself keeps the identical score/argmin ops (bitwise-
    preserving refactor — the unfused path's float sequence is
    unchanged)."""
    part = participation.astype(jnp.float32)
    k = part.shape[0]
    m = part.sum()
    # pairwise squared distances summed over the whole tree, one [K, K]
    # Gram accumulation per leaf (K is a cohort — tiny)
    d2 = jnp.zeros((k, k), jnp.float32)
    for leaf in jax.tree.leaves(deltas):
        x = leaf.astype(jnp.float32).reshape(k, -1)
        sq = (x * x).sum(-1)
        d2 = d2 + jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (x @ x.T), 0.0)
    inf = jnp.float32(jnp.inf)
    alive = part > 0
    pair_ok = alive[:, None] & alive[None, :]
    d2 = jnp.where(pair_ok, d2, inf)
    d2 = d2.at[jnp.arange(k), jnp.arange(k)].set(inf)  # exclude self
    s = jnp.sort(d2, axis=1)  # each row: finite neighbours first
    n_nb = jnp.maximum(m - byzantine_f - 2, 1.0)  # dynamic neighbour count
    keep = (jnp.arange(k)[None, :] < n_nb).astype(jnp.float32)
    scores = (jnp.where(keep > 0, s, 0.0)).sum(1)
    # m == 1: the lone participant has no neighbours (score inf) — give
    # it score 0 so argmin still selects a participant
    scores = jnp.where(alive & (m > 1), scores, jnp.where(alive, 0.0, inf))
    return jnp.argmin(scores), m


def _krum(deltas, participation, byzantine_f: int):
    """Krum selection over a [K, ...] delta stack (see robust_reduce)."""
    winner, m = krum_select(deltas, participation, byzantine_f)
    # m == 0 (full dropout): every score is inf and argmin would pick an
    # arbitrary NON-participant — return the zero update instead, like
    # the median/trimmed_mean paths do
    return jax.tree.map(
        lambda d: jnp.where(
            m > 0, jnp.take(d.astype(jnp.float32), winner, axis=0), 0.0
        ),
        deltas,
    )


def make_server_optimizer(cfg: ServerConfig) -> optax.GradientTransformation:
    if cfg.optimizer == "mean":
        return optax.sgd(cfg.server_lr)
    if cfg.optimizer == "fedavgm":
        return optax.sgd(cfg.server_lr, momentum=cfg.server_momentum)
    if cfg.optimizer == "fedadam":
        return optax.adam(cfg.server_lr, eps=1e-3)
    if cfg.optimizer == "fedyogi":
        # Reddi et al. 2021 (Adaptive Federated Optimization) — yogi's
        # additive second-moment update resists the per-round pseudo-
        # gradient variance that makes fedadam's v_t collapse early.
        return optax.yogi(cfg.server_lr, eps=1e-3)
    raise ValueError(f"unknown server optimizer {cfg.optimizer!r}")


def make_server_update_fn(cfg: ServerConfig):
    """(params, opt_state, mean_delta) → (new_params, new_opt_state).

    Feeds ``−Δ̄`` to optax as the gradient, so every optax transform is a
    valid server optimizer. The state carries a monotone round counter
    (``"round"``) alongside the optax state — the round engine reads it
    to compute round-indexed schedules (client LR decay) *inside* the
    compiled program, so schedules need no extra traced inputs.

    Format note: the ``{"round", "opt"}`` wrapper was introduced in
    round 2 of this build — checkpoints written by earlier builds (raw
    optax state) are not restorable against the current template. No
    migration shim is shipped: there are no deployed checkpoints of the
    old format (run artifacts were never part of the repo).

    ``cfg.fused_apply`` swaps the optax chain for the pallas fused
    server-apply kernel (ops/pallas_apply.py): the delta apply and the
    optimizer update run as one VMEM-resident pass over the flat param
    vector instead of a chain of full-params XLA ops. The optax STATE
    STRUCTURE is kept bit-for-bit (``(TraceState, EmptyState)`` /
    ``(EmptyState, EmptyState)``), so fused and unfused runs checkpoint-
    interoperate; only ``mean`` / ``fedavgm`` are expressible as the
    kernel's single FMA chain (validate() enforces it; this factory
    guards direct callers). The returned ``update`` additionally carries
    a ``fused_reduce(params, opt_state, wire_stack, weights)`` attribute
    — the stacked-path entry the engines use to fuse trust/weight
    scaling → weighted reduction → apply → optimizer into the same
    kernel (weights pre-folded with the 1/denominator or krum's one-hot
    winner row). Fused ≡ unfused at f32-reassociation tolerance
    (tests/test_fused_apply.py), never bitwise — the fused FMA order
    differs.
    """
    opt = make_server_optimizer(cfg)
    fused = getattr(cfg, "fused_apply", False)
    if fused and cfg.optimizer not in ("mean", "fedavgm"):
        # mirror of config.validate() for direct callers: fedadam/
        # fedyogi carry second-moment state the one-pass kernel does
        # not model
        raise ValueError(
            "server.fused_apply supports optimizer='mean' or 'fedavgm' "
            f"only, got {cfg.optimizer!r}"
        )

    def init(params) -> Any:
        return {"round": jnp.zeros((), jnp.int32), "opt": opt.init(params)}

    if not fused:
        def update(params, opt_state, mean_delta) -> Tuple[Any, Any]:
            pseudo_grad = jax.tree.map(jnp.negative, mean_delta)
            updates, new_opt = opt.update(pseudo_grad, opt_state["opt"], params)
            new_state = {"round": opt_state["round"] + 1, "opt": new_opt}
            return optax.apply_updates(params, updates), new_state

        return init, update

    from colearn_federated_learning_tpu.ops.pallas_apply import (
        fused_delta_apply,
        fused_reduce_apply,
    )

    has_mom = cfg.optimizer == "fedavgm"
    beta = cfg.server_momentum if has_mom else 0.0

    def _momentum(opt_state):
        # optax.sgd state: (TraceState(trace), EmptyState()) with
        # momentum, (EmptyState(), EmptyState()) without
        return opt_state["opt"][0].trace if has_mom else None

    def _repack(opt_state, new_mom) -> Any:
        new_opt = opt_state["opt"]
        if has_mom:
            new_opt = (new_opt[0]._replace(trace=new_mom),) + new_opt[1:]
        return {"round": opt_state["round"] + 1, "opt": new_opt}

    def update(params, opt_state, mean_delta) -> Tuple[Any, Any]:
        new_params, new_mom = fused_delta_apply(
            params, _momentum(opt_state), mean_delta,
            cfg.server_lr, beta,
        )
        return new_params, _repack(opt_state, new_mom)

    def fused_reduce(params, opt_state, wire_stack, weights):
        """(params′, opt_state′, mean_delta) from the wire stack in one
        kernel pass; ``weights`` pre-folded (see ops/pallas_apply)."""
        new_params, new_mom, mean_delta = fused_reduce_apply(
            wire_stack, weights, params, _momentum(opt_state),
            cfg.server_lr, beta,
        )
        return new_params, _repack(opt_state, new_mom), mean_delta

    update.fused_reduce = fused_reduce
    return init, update
