"""Server round driver (SURVEY.md §2 C3, call stack §3.1; layer L4).

Owns the outer round loop the reference drives from its server process:
sample cohort → (broadcast) → local training → aggregate → eval / log /
checkpoint. In the sharded engine the broadcast+train+aggregate middle
is one XLA program (parallel/round_engine.py); this driver's per-round
host work is just index-tensor construction and a scalar metrics fetch.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.client.trainer import (
    make_eval_fn,
    make_local_train_fn,
)
from colearn_federated_learning_tpu.config import DPConfig, ExperimentConfig
from colearn_federated_learning_tpu.data import build_federated_data
from colearn_federated_learning_tpu.data.loader import (
    RoundShape,
    bucket_ladder,
    compute_round_shape,
    eval_batches,
    iter_client_slabs,
    make_round_indices,
    make_round_spec,
    pick_bucket,
    spec_examples,
)
from colearn_federated_learning_tpu.models import build_model
from colearn_federated_learning_tpu.obs import (
    HealthAbortError,
    HealthMonitor,
    Tracer,
    device_memory_stats,
    gossip_round_bytes,
    round_comm_bytes,
    round_host_input_bytes,
    round_shape_stats,
)
from colearn_federated_learning_tpu.obs import digest as digest_mod
from colearn_federated_learning_tpu.obs import executables as exec_mod
from colearn_federated_learning_tpu.obs.executables import (
    ExecutableRegistry,
    HbmBudgetError,
)
from colearn_federated_learning_tpu.obs.roofline import (
    PEAK_HBM_BYTES_PER_SEC,
    analytic_lora_step_flops,
    analytic_step_flops,
    layout_gemm_rows,
    mfu_basis,
    mxu_tile_pad_fraction,
    round_phase_costs,
)
from colearn_federated_learning_tpu.parallel import mesh as mesh_lib
from colearn_federated_learning_tpu.parallel.round_engine import (
    apply_store_shard_ownership,
    make_async_round_fn,
    make_sequential_round_fn,
    make_sharded_round_fn,
)
from colearn_federated_learning_tpu.server.aggregation import make_server_update_fn
from colearn_federated_learning_tpu.server.attacks import (
    UPLOAD_ATTACKS,
    flip_labels,
    select_compromised,
)
from colearn_federated_learning_tpu.server.sampler import CohortSampler
from colearn_federated_learning_tpu.utils.checkpoint import CheckpointStore
from colearn_federated_learning_tpu.utils.metrics import MetricsLogger

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}

# warn-once latch for bf16-on-a-backend-without-native-bf16-matmuls:
# the run is CORRECT there (XLA emulates), just silently slow — e.g. a
# TPU config's bf16 settings smoke-tested on a CPU box
_BF16_BACKEND_WARNED = False


def _warn_bf16_backend(cfg) -> None:
    global _BF16_BACKEND_WARNED
    if _BF16_BACKEND_WARNED:
        return
    eff_local = cfg.run.local_param_dtype or cfg.run.param_dtype
    if "bfloat16" not in (cfg.run.compute_dtype, eff_local):
        return
    backend = jax.default_backend()
    if backend in ("tpu", "gpu"):
        return
    _BF16_BACKEND_WARNED = True
    import logging

    logging.getLogger(__name__).warning(
        "bfloat16 compute requested (run.compute_dtype=%s, effective "
        "local dtype %s) on backend %r, which has no native bf16 "
        "matmul units — results are correct but matmuls run emulated "
        "and SLOWER than float32; this is expected only when "
        "smoke-testing a TPU config off-TPU",
        cfg.run.compute_dtype, eff_local, backend,
    )


class Experiment:
    """Everything needed to run ``fit`` / ``evaluate`` for one config."""

    def __init__(self, cfg: ExperimentConfig, echo: bool = True):
        cfg.validate()
        self.cfg = cfg
        if cfg.run.sanitize:
            jax.config.update("jax_debug_nans", True)
        if cfg.run.compilation_cache_dir:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.expanduser(cfg.run.compilation_cache_dir),
            )
            # cache every round program, not just the slowest compiles
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        compute_dtype = _DTYPES[cfg.run.compute_dtype]
        self.model = build_model(
            cfg.model.name, cfg.model.num_classes,
            compute_dtype=compute_dtype,
            param_dtype=_DTYPES[cfg.run.param_dtype],
            **cfg.model.kwargs,
        )
        # LoRA adapter plane (model.lora, models/lora.py): wrap the
        # transformer so the params pytree every downstream subsystem
        # sees IS the adapter set — the base stays frozen inside the
        # wrapper's apply, the [K,·] wire stack carries adapter deltas,
        # and aggregation/compression/attacks/ledger/reputation all run
        # in adapter space with zero engine involvement. lora-off
        # constructs no wrapper at all (the bitwise-identity contract).
        self._lora = cfg.model.lora.enabled
        self._full_param_stats_cache = None
        self._wire_reduction_cache = None
        if self._lora:
            from colearn_federated_learning_tpu.models.lora import (
                build_lora_model,
            )

            self.model = build_lora_model(
                self.model, cfg.model.name,
                rank=cfg.model.lora.rank, alpha=cfg.model.lora.alpha,
                target=cfg.model.lora.target,
            )
        self.fed = build_federated_data(cfg.data, seed=cfg.run.seed, **cfg.model.kwargs)
        self.task = self.fed.task
        self.shape = compute_round_shape(self.fed, cfg.client, cfg.data)
        # On-device masks (r7): the synchronous cohort paths ship the
        # compact [K, 2] (examples_per_epoch, valid_steps) spec instead
        # of the [K, steps, batch] float32 mask slab — the engines
        # rebuild the identical mask in-program (round_engine
        # `on_device_mask`), roughly halving round-input wire bytes.
        # gossip and fedbuff keep the legacy full-mask inputs (their
        # engines consume it directly).
        self._spec_inputs = cfg.algorithm not in ("gossip", "fedbuff")
        # Device-resident control plane (run.control_plane="device",
        # server/device_plane.py): cohort ids, churn gates, the index
        # slab, and ledger slot ids derive INSIDE the round program —
        # the host ships static plan tables once and a round index per
        # dispatch; realized schedules surface at flush boundaries.
        # validate() restricted the pairing surface (uniform sampling,
        # hbm placement, sharded/sequential engines, dense ledger).
        self._cp_device = cfg.run.control_plane == "device"
        # Ledger-driven adaptive selection (server.sampling="adaptive"):
        # the sampler scores clients Oort-style from periodic host-side
        # ledger snapshots — COLUMN-SLIMMED to the three columns it
        # scores (sampler.SNAPSHOT_COLS: count, flagged, ema_loss). The
        # snapshot refreshes at client_ledger.log_every round boundaries
        # (one blocking fetch each — see run_round) and rides the
        # checkpoint (state["ledger_snapshot"], [num_clients, 3]), so
        # the schedule is a pure function of (seed, round, snapshot) and
        # resume replays it exactly. server.sampling="streaming" is the
        # million-client sibling: O(cohort·log) draws from a fixed-size
        # score SKETCH (state["ledger_sketch_*"]) instead of any dense
        # [num_clients] structure; with the ledger off it degrades to a
        # uniform streaming draw with no snapshot machinery at all.
        self._adaptive = cfg.server.sampling == "adaptive"
        self._streaming = cfg.server.sampling == "streaming"
        lcfg = cfg.run.obs.client_ledger
        self._ledger_on = lcfg.enabled
        self._ledger_cfg = lcfg
        self._snapshot_refresh = self._adaptive or (
            self._streaming and lcfg.enabled and lcfg.log_every >= 1
        )
        self._sampler_snapshot: Optional[np.ndarray] = None
        self._sampler_snapshot_round = 0
        self._sketch_ids = np.full(
            cfg.server.adaptive.sketch_size, -1, np.int32
        )
        self._sketch_stats = np.zeros(
            (cfg.server.adaptive.sketch_size, 3), np.float32
        )
        # Seed-pure availability/churn model (run.churn, server/
        # churn.py): every realized churn event is a pure function of
        # (run.seed, round, client_id), so schedules stay resume-
        # replayable and engine-invariant with zero checkpoint state.
        # The samplers reject offline candidates; dispatched cohort
        # members realize dropout/crash through _apply_failures; the
        # fedbuff scheduler defers offline completions. churn-off
        # constructs no model anywhere (bitwise-identity contract).
        from colearn_federated_learning_tpu.server.churn import (
            build_churn_model,
        )

        self._churn = build_churn_model(cfg)
        # Multi-version async lines (server.async_versions): round r
        # drives line r mod V at line-local version r div V — line 0
        # keeps the legacy state keys (the V=1 bitwise-identity
        # contract), lines l >= 1 ride `*_l{l}` keys. Retirement /
        # re-admission generation accounting lives in state["line_*"].
        self._versions = cfg.server.async_versions
        self._staleness_hist: Dict[int, int] = {}
        self._per_version_absorbed = np.zeros(
            max(1, cfg.server.async_versions), np.int64
        )
        self._version_readmitted = 0
        self._readmit_warned = False
        # Two-tier hierarchy (server.hierarchy): E edge aggregators
        # over deterministic contiguous sub-population blocks. Sync
        # rounds re-run the ONE compiled engine per edge
        # (_run_hier_round) and robust-combine edge deltas at the core;
        # fedbuff groups each popped completion by its edge host-side
        # (crashed edges' members are excluded, never NaN-poisoning
        # the core). hierarchy-off constructs nothing (the bitwise-
        # identity contract).
        self._hier = cfg.server.hierarchy.num_edges > 0
        self._hier_stats: Dict[int, Dict[str, int]] = {}
        self._edge_absorbed = np.zeros(
            max(1, cfg.server.hierarchy.num_edges), np.int64
        )
        self.sampler = CohortSampler(
            self.fed.num_clients, cfg.server.cohort_size, seed=cfg.run.seed,
            weights=(
                self.fed.client_sizes() if cfg.server.sampling == "weighted" else None
            ),
            mode=(
                "poisson" if cfg.server.sampling == "poisson"
                else "adaptive" if self._adaptive
                else "streaming" if self._streaming else "fixed"
            ),
            explore=cfg.server.adaptive.explore,
            staleness_gain=cfg.server.adaptive.staleness_gain,
            flag_suppress=cfg.server.adaptive.flag_suppress,
            sketch_size=cfg.server.adaptive.sketch_size,
            availability_fn=(
                self._churn.available if self._churn is not None else None
            ),
        )
        # Hierarchy edge samplers (sync path): one fixed-mode sampler
        # per edge over its contiguous block [e·N/E, (e+1)·N/E),
        # id_base-offset so draws come back as GLOBAL client ids (the
        # churn availability_fn and the engine index the flat
        # population). Stateless pure-(seed, round) draws — resumes
        # replay them for free. fedbuff pops its queue instead.
        self._edge_samplers = []
        if self._hier and cfg.algorithm not in ("fedbuff", "gossip"):
            _n = self.fed.num_clients
            _e_cnt = cfg.server.hierarchy.num_edges
            for _e in range(_e_cnt):
                _lo = (_e * _n) // _e_cnt
                _hi = ((_e + 1) * _n) // _e_cnt
                self._edge_samplers.append(CohortSampler(
                    _hi - _lo, cfg.server.cohort_size,
                    seed=cfg.run.seed + (_e + 1) * 1_000_003,
                    mode="fixed", id_base=_lo,
                    availability_fn=(
                        self._churn.available
                        if self._churn is not None else None
                    ),
                ))
        # Poisson sampling: the realized Binomial(N, q) cohort is padded
        # to a STATIC cap of K + 5σ (so XLA never retraces); overflow
        # raises — an OBSERVABLE abort whose exact binomial-tail
        # probability is logged as dp_delta_abort and belongs to the DP
        # δ (the (ε, δ + δ_abort) composition for aborting mechanisms).
        self._poisson = cfg.server.sampling == "poisson"
        self._poisson_cap = 0
        if self._poisson:
            import math as _math

            _k, _n = cfg.server.cohort_size, self.fed.num_clients
            _q = _k / _n
            self._poisson_cap = min(
                _n, _k + _math.ceil(5.0 * _math.sqrt(_k * (1.0 - _q))) + 1
            )
        # Heterogeneity-aware round shapes (run.shape_buckets, r7): the
        # federation-max steps_per_epoch is quantized onto a geometric
        # ladder; each round (chunk, under fusion) dispatches on the
        # smallest rung covering the SAMPLED cohort's max capped shard.
        # The bucket for a round is a pure function of (seed, round) —
        # resume and the stream-prefetch worker recompute it for free —
        # and jit caches one executable per realized [K, steps, batch]
        # shape, so the compile budget is bounded by the ladder size
        # (per-bucket attribution: _bucket_compile_span).
        self._sizes_capped = np.minimum(
            self.fed.client_sizes(), self.shape.cap
        ).astype(np.int64)
        sb = cfg.run.shape_buckets
        self._bucket_ladder = (
            bucket_ladder(self.shape.steps_per_epoch, sb.base, sb.count)
            if sb.enabled else None
        )
        self._bucket_cache: Dict[int, int] = {}
        self._bucket_shapes: Dict[int, RoundShape] = {
            self.shape.steps_per_epoch: self.shape
        }
        self._seen_buckets: set = set()
        self.server_opt_init, server_update = make_server_update_fn(cfg.server)
        # SCAFFOLD (cfg.algorithm): per-client control variates live as
        # one stacked [N_pad, ...] tree per leaf. Under the sharded
        # engine the store is DEVICE-RESIDENT, mesh-sharded over the
        # clients axis, and the cohort gather/scatter happens inside the
        # round program (round_engine.py) — zero per-round host sync,
        # multi-host capable. The sequential engine keeps the
        # host-numpy store (it is the debugging oracle).
        self.scaffold = cfg.algorithm == "scaffold"
        # FedDyn shares scaffold's state plumbing: c_global carries h,
        # c_clients carries the per-client gᵢ corrections
        self.feddyn = cfg.algorithm == "feddyn"
        self.stateful = self.scaffold or self.feddyn
        # Error-feedback compression (ServerConfig.error_feedback) rides
        # the SAME device-resident store (c_clients carries the eᵢ
        # residuals) but has no global state — store_state gates the
        # store plumbing, stateful the c_global/dc machinery
        self.ef = cfg.server.error_feedback
        self.store_state = self.stateful or self.ef
        # FedBuff (cfg.algorithm="fedbuff"): the server steps an
        # asynchronous in-flight queue instead of sampling synchronous
        # cohorts — client completions are consumed K at a time, each
        # trained against the stale params version it started from
        # (kept in an on-device history ring), staleness-decayed.
        self.fedbuff = cfg.algorithm == "fedbuff"
        # Decentralized gossip (cfg.algorithm="gossip", parallel/gossip.py):
        # no server — every client keeps its own replica in a [N, ...]
        # mesh-sharded tree; rounds are local-train + ring halo-exchange
        # mixing. state["params"] tracks the consensus mean (what eval/
        # checkpoint-export consume); state["replicas"] is the stack.
        self.gossip = cfg.algorithm == "gossip"
        # partial-participation gossip (r5): K < N ⇒ the sampled cohort
        # trains (in-program gather/scatter over the replica stack),
        # everyone mixes; 0 = classic full participation
        self._gossip_partial = (
            cfg.server.cohort_size
            if self.gossip and cfg.server.cohort_size < cfg.data.num_clients
            else 0
        )
        # secure aggregation (ServerConfig.secure_aggregation): masks
        # ride a STATIC full-cohort ring; the fixed-point range checks
        # run after the aggregation-weight mode is resolved below
        self.secagg = cfg.server.secure_aggregation
        if self.fedbuff:
            # per-client base durations for the async workload model:
            # capped work (= the examples the client actually trains on)
            # quantile-ranked into 1..S — see _client_durations
            work = np.minimum(self.fed.client_sizes(), self.shape.cap)
            ranks = np.argsort(np.argsort(work, kind="stable"))
            s = cfg.server.async_max_staleness
            self._duration_base = (
                1 + (ranks * s) // max(len(work), 1)
            ).astype(np.int32)
        # per-round async scheduler stats (mean/max staleness, clamp +
        # backpressure counts), drained into round records at flush;
        # _traffic_totals accumulates the summable ones for run_summary
        self._async_stats: Dict[int, Dict[str, Any]] = {}
        self._traffic_totals: Dict[str, int] = {}
        self._async_absorbed = 0
        self._staleness_warned = False
        # observability (run.obs, obs/): per-round comm-byte and
        # failure-count stats keyed by round (host-side, popped at
        # flush); the tracer + health monitor are built after the
        # logger below. _param_stats_cache backs both the HBM
        # pre-flight and the comm-byte model.
        self._param_stats_cache = None
        self._comm_stats: Dict[int, Dict[str, int]] = {}
        self._fail_stats: Dict[int, Dict[str, int]] = {}
        # unfused engine twin for non-chunk-aligned resumes under
        # run.fuse_rounds > 1 (set below for the sharded sync path)
        self._make_engine = None
        self._unfused_cache = None
        # Byzantine adversary simulation (AttackConfig, server/attacks.py):
        # the compromised id set is a deterministic pure function of
        # (run.seed, num_clients, fraction) — fixed for the whole run,
        # identical across engines and resumes. Upload attacks ride the
        # engines' [K] byzantine-mask input; label_flip poisons the
        # compromised clients' training labels host-side below, before
        # the corpus is placed (so hbm, stream, and both engines all see
        # the same poisoned shards).
        self.attack_kind = cfg.attack.kind
        self._attack_upload = self.attack_kind in UPLOAD_ATTACKS
        self.compromised = np.zeros(0, np.int64)
        self._attack_stats: Dict[int, int] = {}
        # Per-client forensic ledger (run.obs.client_ledger, obs/
        # ledger.py): each round program emits a [K] per-client stats
        # block (upload L2 / cosine-vs-aggregate / clip-EF residual /
        # loss / robust-z flag) and scatters it into a device-resident
        # [num_clients, LEDGER_WIDTH] store carried across rounds —
        # periodic `client_ledger` JSONL records + the `colearn
        # clients` report read it. validate() already rejected the
        # unsound pairings (secagg, client-DP, gossip/fedbuff,
        # stateful algorithms).
        # (lcfg/_ledger_on/_ledger_cfg were hoisted above the sampler —
        # the snapshot-refresh machinery needs them)
        self._ledger_ref = None
        self._ledger_logged_round = -1
        if self.attack_kind:
            self.compromised = select_compromised(
                self.fed.num_clients, cfg.attack.fraction, cfg.run.seed
            )
            if self.attack_kind == "label_flip":
                if self.fed.task != "classify":
                    raise ValueError(
                        "attack.kind='label_flip' requires a "
                        "classification task"
                    )
                self.fed.train_y = flip_labels(
                    self.fed.train_y, self.fed.client_indices,
                    self.compromised, self.fed.num_classes,
                )
        # Size-proportional sampling pairs with UNIFORM aggregation
        # weights: example-weighting on top of p∝size sampling would count
        # shard size twice (contribution ∝ size²). Uniform sampling keeps
        # classic example-weighted FedAvg. (The pairing is the standard FL
        # importance-sampling heuristic — exactly unbiased only in the
        # with-replacement limit; without-replacement cohorts cap a huge
        # client's inclusion probability at 1, mildly under-weighting it.)
        agg = "uniform" if cfg.server.sampling == "weighted" else "examples"
        if self.feddyn:
            agg = "uniform"  # the paper's plain mean over the cohort
        if cfg.server.dp_client_noise_multiplier > 0.0:
            # client-level DP needs w_i ∈ {0,1} and a fixed public
            # denominator — example weights are private data and would
            # invalidate the sensitivity analysis (ServerConfig docs)
            agg = "uniform"
        self._agg_mode = agg
        # (the secagg fixed-point bound check runs AFTER engine
        # construction so the poisson cap is already lane-rounded —
        # the bound must cover the padded worst case)

        if cfg.run.engine == "sharded":
            batch_shards = max(1, cfg.run.batch_shards)
            if cfg.client.batch_size % batch_shards:
                raise ValueError(
                    f"run.batch_shards={batch_shards} must divide "
                    f"client.batch_size={cfg.client.batch_size}"
                )
            avail = len(jax.devices()) // batch_shards
            if avail < 1:
                raise ValueError(
                    f"run.batch_shards={batch_shards} > visible devices "
                    f"{len(jax.devices())}"
                )
            if cfg.run.num_lanes:
                lanes = cfg.run.num_lanes
                if not self._poisson and cfg.server.cohort_size % lanes != 0:
                    raise ValueError(
                        f"run.num_lanes={lanes} must divide cohort_size="
                        f"{cfg.server.cohort_size} (set num_lanes=0 to auto-pick)"
                    )
            else:
                lanes = mesh_lib.largest_lane_count(cfg.server.cohort_size, avail)
            if self._poisson:
                # static rows must divide the lanes; pad rows are free
                self._poisson_cap = -(-self._poisson_cap // lanes) * lanes
            self.mesh = mesh_lib.build_client_mesh(lanes, batch_shards=batch_shards)
            if self.gossip:
                from colearn_federated_learning_tpu.parallel.gossip import (
                    make_gossip_round_fn,
                )

                self.round_fn = make_gossip_round_fn(
                    self.model, cfg.client, cfg.dp, self.task, self.mesh,
                    num_clients=self.fed.num_clients,
                    gamma=cfg.server.gossip_gamma,
                    mixing_steps=cfg.server.gossip_mixing_steps,
                    topology=cfg.server.gossip_topology,
                    local_dtype=self._local_dtype(),
                    scan_unroll=cfg.run.scan_unroll,
                    cohort_size=cfg.server.cohort_size,
                    attack=self.attack_kind if self._attack_upload else "",
                    attack_scale=cfg.attack.scale,
                    attack_eps=cfg.attack.eps,
                )
            elif self.fedbuff:
                self.round_fn = make_async_round_fn(
                    self.model, cfg.client, cfg.dp, self.task, self.mesh,
                    server_update, buffer_size=cfg.server.cohort_size,
                    window=2 * cfg.server.async_max_staleness + 1,
                    client_vmap_width=cfg.run.client_vmap_width,
                    local_dtype=self._local_dtype(),
                    clip_delta_norm=cfg.server.clip_delta_norm,
                    scan_unroll=cfg.run.scan_unroll,
                    client_ledger=self._ledger_on,
                    ledger_ema=lcfg.ema,
                    ledger_zmax=lcfg.zmax,
                    reputation=cfg.server.reputation.enabled,
                    rep_floor=cfg.server.reputation.floor,
                    rep_strength=cfg.server.reputation.strength,
                    rep_z_gain=cfg.server.reputation.z_gain,
                )
            else:
                def _make_engine(fuse, donate=True):
                    return make_sharded_round_fn(
                        self.model, cfg.client, cfg.dp, self.task, self.mesh,
                        server_update,
                        self._poisson_cap or cfg.server.cohort_size,
                        dp_fixed_denom=cfg.server.cohort_size,
                        client_vmap_width=cfg.run.client_vmap_width,
                        cohort_layout=cfg.run.cohort_layout,
                        local_dtype=self._local_dtype(), agg=agg,
                        scaffold=self.scaffold,
                        num_clients=self.fed.num_clients,
                        aggregator=cfg.server.aggregator,
                        trim_ratio=cfg.server.trim_ratio,
                        compression=cfg.server.compression,
                        topk_ratio=cfg.server.compression_topk_ratio,
                        qsgd_levels=cfg.server.compression_qsgd_levels,
                        topk_exact=cfg.server.compression_topk_exact,
                        clip_delta_norm=cfg.server.clip_delta_norm,
                        feddyn_alpha=(
                            cfg.server.feddyn_alpha if self.feddyn else 0.0
                        ),
                        byzantine_f=cfg.server.krum_byzantine,
                        scan_unroll=cfg.run.scan_unroll,
                        secagg=self.secagg,
                        secagg_quant_step=cfg.server.secagg_quant_step,
                        secagg_mode=cfg.server.secagg_mode,
                        client_dp_noise=cfg.server.dp_client_noise_multiplier,
                        downlink=cfg.server.downlink_compression,
                        downlink_levels=cfg.server.downlink_qsgd_levels,
                        error_feedback=self.ef,
                        fuse_rounds=fuse,
                        attack=(
                            self.attack_kind if self._attack_upload else ""
                        ),
                        attack_scale=cfg.attack.scale,
                        attack_eps=cfg.attack.eps,
                        on_device_mask=self._spec_inputs,
                        client_ledger=self._ledger_on,
                        ledger_ema=lcfg.ema,
                        ledger_zmax=lcfg.zmax,
                        reputation=cfg.server.reputation.enabled,
                        rep_floor=cfg.server.reputation.floor,
                        rep_strength=cfg.server.reputation.strength,
                        rep_z_gain=cfg.server.reputation.z_gain,
                        fused_apply=cfg.server.fused_apply,
                        hierarchy=self._hier,
                        # hierarchy re-dispatches the SAME params/opt
                        # buffers once per edge — donation would delete
                        # them after the first edge's call; the device
                        # control plane moves donation to its outer
                        # wrapper jit (donate=False here)
                        donate=donate and not self._hier,
                    )

                self.round_fn = _make_engine(cfg.run.fuse_rounds)
                # an unfused twin is built lazily (one extra compile)
                # only when a resume lands off a chunk boundary — see
                # _unfused_round_fn / the _fit_body catch-up loop; the
                # device control plane keeps the factory for its
                # donate-free inner engines
                if cfg.run.fuse_rounds > 1 or self._cp_device:
                    self._make_engine = _make_engine
            self._data_sharding = mesh_lib.replicated(self.mesh)
            self._cohort_sharding = mesh_lib.cohort_sharded(self.mesh)
            self._client_sharding = mesh_lib.client_sharded(self.mesh)
            self.n_chips = lanes * batch_shards
            # per-client state store rows: N padded up to a lane multiple
            # (pad rows are never sampled into a cohort, so they stay 0)
            self._state_rows = -(-self.fed.num_clients // lanes) * lanes
        else:
            self.mesh = None
            self.round_fn = make_sequential_round_fn(
                self.model, cfg.client, cfg.dp, self.task, server_update,
                dp_fixed_denom=cfg.server.cohort_size,
                cohort_layout=cfg.run.cohort_layout,
                local_dtype=self._local_dtype(), agg=agg,
                scaffold=self.scaffold, num_clients=self.fed.num_clients,
                aggregator=cfg.server.aggregator,
                trim_ratio=cfg.server.trim_ratio,
                compression=cfg.server.compression,
                topk_ratio=cfg.server.compression_topk_ratio,
                qsgd_levels=cfg.server.compression_qsgd_levels,
                topk_exact=cfg.server.compression_topk_exact,
                clip_delta_norm=cfg.server.clip_delta_norm,
                feddyn_alpha=(
                    cfg.server.feddyn_alpha if self.feddyn else 0.0
                ),
                byzantine_f=cfg.server.krum_byzantine,
                secagg=self.secagg,
                secagg_quant_step=cfg.server.secagg_quant_step,
                secagg_mode=cfg.server.secagg_mode,
                scan_unroll=cfg.run.scan_unroll,
                client_dp_noise=cfg.server.dp_client_noise_multiplier,
                downlink=cfg.server.downlink_compression,
                downlink_levels=cfg.server.downlink_qsgd_levels,
                error_feedback=self.ef,
                attack=self.attack_kind if self._attack_upload else "",
                attack_scale=cfg.attack.scale,
                attack_eps=cfg.attack.eps,
                on_device_mask=self._spec_inputs,
                client_ledger=self._ledger_on,
                ledger_ema=lcfg.ema,
                ledger_zmax=lcfg.zmax,
                reputation=cfg.server.reputation.enabled,
                rep_floor=cfg.server.reputation.floor,
                rep_strength=cfg.server.reputation.strength,
                rep_z_gain=cfg.server.reputation.z_gain,
                fused_apply=cfg.server.fused_apply,
            )
            self._data_sharding = None
            self._cohort_sharding = None
            self._client_sharding = None
            self.n_chips = 1
            self._state_rows = self.fed.num_clients

        if self.secagg:
            # after engine construction: the poisson cap (if any) is now
            # lane-rounded, so the worst-case aggregate bound is final
            self._check_secagg_bounds()

        # Paged ledger (run.obs.client_ledger.hot_capacity, obs/ledger
        # LedgerPager): the device store shrinks to a [hot_capacity,
        # LEDGER_WIDTH] hot set scattered by SLOT; the driver remaps
        # cohort ids → slots host-side (the round program is unchanged)
        # and spills cold rows to an anonymous host mmap. hot_capacity
        # >= num_clients (or 0) keeps the classic dense store. The
        # capacity floor uses the LANE-ROUNDED poisson cap and the full
        # fused-chunk cohort union — the worst case one dispatch can
        # touch — so "cohort fits the hot set" is a construction-time
        # guarantee, not a runtime surprise.
        self._pager = None
        self._ledger_rows = self.fed.num_clients
        hot = lcfg.hot_capacity
        if self._ledger_on and 0 < hot < self.fed.num_clients:
            need = (self._poisson_cap or cfg.server.cohort_size) * max(
                1, cfg.run.fuse_rounds
            )
            if hot < need:
                raise ValueError(
                    f"run.obs.client_ledger.hot_capacity={hot} is smaller "
                    f"than the worst-case dispatch cohort "
                    f"({self._poisson_cap or cfg.server.cohort_size} "
                    f"clients × fuse_rounds={max(1, cfg.run.fuse_rounds)} "
                    f"= {need}) — every dispatched cohort must fit the "
                    f"hot set; raise hot_capacity or shrink the cohort"
                )
            from colearn_federated_learning_tpu.obs.ledger import LedgerPager

            self._pager = LedgerPager(self.fed.num_clients, hot)
            self._ledger_rows = hot

        # Training-corpus placement (SURVEY.md §2 C10 at scale):
        #   hbm    — dataset bytes go to HBM exactly once (replicated over
        #            lanes); rounds gather on device. Default.
        #   stream — corpus stays in host RAM; each round uploads only a
        #            fixed-size slab of the cohort's examples with the
        #            index tensors remapped into it (max slab rows =
        #            cohort × cap + 1). Unlocks corpora larger than HBM;
        #            the per-round working set still must fit.
        # Multi-host runs assemble global arrays from the host-replicated
        # copies instead of device_put-ing across processes.
        put = self._put_data
        self._stream = cfg.data.placement == "stream"
        self._check_memory_budget()
        # Fused-chunk placement (run.fuse_rounds > 1): the stacked
        # [F, K, ...] host slabs go through the same _put path as the
        # per-round tensors, with the fuse dim replicated — under
        # multi-process each host uploads only its addressable shards
        # (host_local_array), so fusion composes with multi-host meshes.
        if self.mesh is not None:
            self._fused_cohort_sharding = mesh_lib.fused_cohort_sharded(
                self.mesh
            )
            self._fused_client_sharding = mesh_lib.fused_client_sharded(
                self.mesh
            )
        else:
            self._fused_cohort_sharding = None
            self._fused_client_sharding = None
        self._prefetch: Dict[int, Any] = {}
        self._host_executor = None
        # Double-buffered rounds (run.double_buffer, ROADMAP item 2
        # lever c): a host worker builds AND places round N+1's inputs
        # while round N's dispatched compute runs — see _maybe_prefetch
        # for the drain rules (fuse chunks, bucket rungs, adaptive
        # snapshot boundaries). fedbuff's scheduler pops its queue
        # in-order and is not buffered.
        self._double_buffer = (
            bool(cfg.run.double_buffer) and not self.fedbuff
            and not self._hier
            # device control plane: there are no host slabs to build
            # ahead — the worker would race the in-program derivation
            # for nothing, so double-buffering is structurally off
            and not self._cp_device
        )
        self._db_stats = {
            "host_prefetched": 0, "placed_prefetched": 0,
            "prefetch_dropped": 0, "slab_prefetched": 0,
        }
        # fused chunk-union slab prefetch (stream × fuse): one future
        # per upcoming chunk, keyed by chunk start round — submitted
        # right before the current chunk's dispatch so the next
        # chunk's store gather runs while this dispatch executes
        self._chunk_prefetch: Dict[int, Any] = {}
        _warn_bf16_backend(cfg)
        if self._stream:
            rows_per_round = (
                (self._poisson_cap or cfg.server.cohort_size)
                * self.shape.cap + 1
            )
            self._slab_rows = min(rows_per_round, len(self.fed.train_x))
            # fused chunks gather ONE union slab over the chunk's
            # cohorts (static shape: fuse rounds' worth of rows) and
            # remap the stacked index tensors into it — the engine
            # still sees a single corpus input per dispatch
            self._fused_slab_rows = min(
                cfg.run.fuse_rounds * (rows_per_round - 1) + 1,
                len(self.fed.train_x),
            )
            self.train_x = None
            self.train_y = None
            # multi-host shard ownership (store-backed pods): each
            # process marks the store shards whose clients land on its
            # contiguous client block, so steady-state gathers fault
            # only local pages; off-block touches fall back to read
            # replicas (counted in gather_stats) — see round_engine
            self._store_ownership = apply_store_shard_ownership(self.fed)
        else:
            self._store_ownership = None
            self.train_x = put(jnp.asarray(self.fed.train_x))
            self.train_y = put(jnp.asarray(self.fed.train_y))
        # Device control plane: build the static plan (cohort table via
        # the UNMODIFIED host sampler — device cohorts are bitwise-equal
        # to host mode by construction — churn thresholds, shard table),
        # ship it to HBM once, and wrap the donate-free engine twins.
        self._device_plan = None
        self._device_sched: Dict[int, Any] = {}
        self._device_draw_stats: Dict[int, Optional[Dict[str, int]]] = {}
        if self._cp_device:
            self._init_device_plane()
        eval_fn = make_eval_fn(self.model, self.task)
        self._eval_fn = exec_mod.instrument("eval.task", jax.jit(eval_fn))

        # Federated (per-client) eval as ONE dispatch: nested lax.scan —
        # outer over clients, inner over each client's padded batch stack
        # — instead of one jitted call per client per batch (up to
        # clients × batches relay round-trips; same fix as _eval_all).
        def _fed_eval_all(params, xs, ys, ms):
            def per_client(_, client_b):
                def body(acc, b):
                    _, c, n = eval_fn(params, *b)
                    return (acc[0] + c, acc[1] + n), None

                sums, _ = jax.lax.scan(
                    body, (jnp.zeros(()), jnp.zeros(())), client_b
                )
                return None, sums

            _, (c, n) = jax.lax.scan(per_client, None, (xs, ys, ms))
            return c, n  # per-client correct/example counts, [n_clients]

        self._fed_eval_all = exec_mod.instrument(
            "eval.fed_all", jax.jit(_fed_eval_all)
        )

        # Full-test-set eval as ONE dispatch: lax.scan over the stacked
        # eval batches instead of one jitted call per batch — at ImageNet
        # scale (50k test / batch 64 ≈ 780 batches) the per-batch loop is
        # host-dispatch-bound on a relayed chip. Parity with the per-batch
        # loop is pinned by tests/test_e2e_mnist.py::test_eval_scan_parity.
        def _eval_all(params, xb, yb, mb):
            def body(acc, b):
                l, c, n = eval_fn(params, *b)
                return (acc[0] + l, acc[1] + c, acc[2] + n), None

            acc, _ = jax.lax.scan(
                body, (jnp.zeros(()), jnp.zeros(()), jnp.zeros(())),
                (xb, yb, mb),
            )
            return acc

        self._eval_all = exec_mod.instrument("eval.all", jax.jit(_eval_all))
        # eval batches are fixed for the run: build + upload exactly once
        xb, yb, mb = eval_batches(
            self.fed.test_x, self.fed.test_y, cfg.client.batch_size
        )
        self._eval_data = (put(jnp.asarray(xb)), put(jnp.asarray(yb)), put(jnp.asarray(mb)))
        # Multi-host: every process runs the identical fit loop (SPMD over
        # the global mesh), but artifacts are SINGLE-WRITER — only process
        # 0 writes/echoes metrics. Checkpointing stays collective (orbax
        # coordinates its own primary-writer protocol internally).
        self._primary = jax.process_index() == 0
        if (self.store_state and jax.process_count() > 1
                and cfg.run.engine != "sharded"):
            # only the sequential oracle still host-scatters per-client
            # state (device_get of non-addressable shards is impossible
            # in a multi-controller run); the sharded engine keeps the
            # store device-resident and is fully multi-host capable
            raise NotImplementedError(
                "scaffold/feddyn/error_feedback under multi-host requires "
                "run.engine=sharded (the sequential oracle's host-"
                "resident state scatter cannot cross processes)"
            )
        self.logger = MetricsLogger(
            (cfg.run.out_dir or None) if self._primary else None,
            cfg.name, echo=echo and self._primary,
            append=cfg.run.resume,
            tensorboard=cfg.run.tensorboard,
        )
        # Round-lifecycle telemetry (run.obs, obs/): the tracer times
        # host phases (and attributes retraces via compile hooks); the
        # health monitor watches the fetched losses at flush
        # boundaries. Under multi-process EVERY process traces into its
        # own lane (pid = process_index): non-primaries export per-host
        # `trace.p<i>.json` fragments and the primary merges them into
        # the final trace.json — the merged timeline replaces the old
        # process-0-only export. The JSONL stays single-writer.
        obs = cfg.run.obs
        self._process_index = jax.process_index()
        self.tracer = Tracer(
            enabled=obs.spans, trace=obs.trace,
            max_events=obs.trace_max_events,
            process_index=self._process_index,
        )
        self.health = (
            HealthMonitor(obs.divergence_factor) if obs.health else None
        )
        # Compiled-program observatory (run.obs.executables): the
        # per-fit AOT registry — installed around fit() so the engines'
        # instrumented jit sites route through it; drained into
        # `executable_compiled`/`retrace`/`hbm_watermark` records at
        # flush boundaries. The same lowering jit would produce —
        # registry-on is bitwise-identical to registry-off
        # (test-pinned).
        self._exec_reg: Optional[ExecutableRegistry] = None
        if obs.executables:
            self._exec_reg = ExecutableRegistry(
                hbm_budget_bytes=obs.hbm_budget_mb * 2**20,
                device_capacity_bytes=exec_mod.device_hbm_capacity(),
                tracer=self.tracer,
            )
        self._counters_on = obs.counters
        # analytic per-phase FLOP/HBM-byte cost records (obs/roofline):
        # pure function of config + realized grid, so both engines (and
        # the fused path) log identical numbers — parity-pinned like
        # the wire counters. Rides the counters infrastructure.
        # Centralized synchronous rounds only: the gossip/fedbuff round
        # programs have different phase structure and would be
        # mis-modeled by the cohort-upload taxonomy.
        self._phase_cost_on = (
            obs.counters and obs.phase_cost
            and not (self.gossip or self.fedbuff)
        )
        self._phase_costs: Dict[int, Dict[str, Dict[str, int]]] = {}
        self._step_flops_cache = None
        # Federation health observatory (run.obs.population, obs/
        # population.py): population/data-plane telemetry — coverage,
        # draw split, staleness, pager/store health, fairness — folded
        # into one `population_health` record per flush window. Purely
        # observational host-side accounting: no device work, no rng
        # consumption, and every count-based column is a pure function
        # of the cohort schedule, so records are engine-parity pinned
        # (the `*_ms` wall-clock fields are the one exception).
        self._population = None
        if obs.population.enabled:
            from colearn_federated_learning_tpu.obs.population import (
                PopulationTracker,
            )

            self._population = PopulationTracker(
                self.fed.num_clients,
                top_k=obs.population.top_k,
                hll_bits=obs.population.hll_bits,
                recency_capacity=obs.population.recency_capacity,
            )

        # Determinism flight recorder (run.obs.digest, obs/digest.py):
        # per-boundary canonical state digests chained prev → self in
        # the JSONL, chain head riding every checkpoint. Read-only over
        # fetched state — digest-on runs are bitwise-identical to
        # digest-off (test-pinned); the O(P) fetch+hash is amortized by
        # `every` and the window fold keeps the schedule/wire
        # components invariant to flush cadence and fuse_rounds.
        self._digest_on = bool(obs.digest.enabled)
        self._digest_every = max(1, int(obs.digest.every))
        self._digest_cohorts: Dict[int, np.ndarray] = {}
        self._digest_window = (
            digest_mod.RoundWindow() if self._digest_on else None
        )
        self._digest_prev = digest_mod.GENESIS
        self._digest_prev_round = 0

        # Host-side round-input construction: the C++ threaded pipeline
        # (native/round_pipeline.cpp) builds + prefetches index tensors off
        # the round loop's critical path; NumPy path otherwise.
        self._native = None
        if self._poisson and cfg.run.host_pipeline == "native":
            raise ValueError(
                "run.host_pipeline=native does not support "
                "server.sampling=poisson (variable cohorts are padded "
                "host-side); use host_pipeline=numpy"
            )
        if (cfg.run.host_pipeline in ("auto", "native")
                and not self._poisson
                # the device control plane derives round inputs
                # in-program — there is no host slab to prefetch
                # (validate() rejects explicit 'native'; 'auto' skips)
                and not self._cp_device
                # bucketed grids vary per round; the C++ pipeline builds
                # ONE fixed shape (validate() rejects the explicit
                # 'native' pairing; 'auto' degrades to NumPy here).
                # snapshot-fed sampling (adaptive, or streaming with a
                # ledger sketch): the pipeline prefetches FUTURE cohorts
                # and treats resubmission as a no-op, so a snapshot
                # refresh between prefetch and dispatch would silently
                # serve a stale cohort's tensors (validate() rejects
                # explicit 'native'; 'auto' degrades). Store-backed
                # federations skip it too: the pipeline materializes the
                # full per-client index lists the store exists to avoid.
                and self._bucket_ladder is None
                and not self._snapshot_refresh
                and not cfg.data.store.dir):
            from colearn_federated_learning_tpu import native

            if native.available():
                self._native = native.NativeRoundPipeline(
                    self.fed.client_indices,
                    self.shape.local_epochs, self.shape.steps_per_epoch,
                    self.shape.batch_size, self.shape.cap,
                    seed=cfg.run.seed,
                    # spec-input engines rebuild the mask on device —
                    # the pipeline skips the float mask slab entirely
                    build_mask=not self._spec_inputs,
                )
            elif cfg.run.host_pipeline == "native":
                raise RuntimeError(
                    f"run.host_pipeline=native but the C++ pipeline cannot "
                    f"be built: {native.build_error()}"
                )

    # ------------------------------------------------------------------

    def _check_secagg_bounds(self) -> None:
        """Worst-case fixed-point range checks for secure aggregation
        (see ServerConfig). The max FedAvg weight comes from the
        RESOLVED aggregation mode (``uniform`` ⇒ 1.0), not the sampling
        mode — e.g. client-DP-forced uniform weights must not inflate
        the bound by the example cap.

        - Per-client: ``max_w·clip/quant_step`` must stay < 2^24 for
          the f32 rounding in ``_secagg_upload`` to remain integer-
          exact. Warn only — realized deltas usually sit orders of
          magnitude below the clip bound.
        - Aggregate: the cohort-summed bound must stay < 2^31 or the
          int32 accumulator can WRAP, silently corrupting the round —
          refuse to run unless the config explicitly opts in via
          ``server.secagg_allow_wrap_risk=true``.
        """
        import logging

        log = logging.getLogger(__name__)
        s = self.cfg.server
        max_w = 1.0 if self._agg_mode == "uniform" else float(self.shape.cap)
        per_client = max_w * s.clip_delta_norm / s.secagg_quant_step
        if per_client >= 2**24:
            log.warning(
                "secure_aggregation per-client fixed-point bound "
                "max_weight*clip/quant_step = %.3g >= 2^24: f32 rounding "
                "in the quantizer can lose integer exactness for clients "
                "that approach the clip bound — consider a larger "
                "secagg_quant_step",
                per_client,
            )
        # poisson: worst case is the static cap (more than K clients can
        # realize); fixed: the cohort size
        bound = (self._poisson_cap or s.cohort_size) * per_client
        if bound >= 2**31:
            if s.secagg_allow_wrap_risk:
                log.warning(
                    "secure_aggregation worst-case aggregate bound "
                    "cohort*max_weight*clip/quant_step = %.3g >= 2^31 "
                    "(secagg_allow_wrap_risk=true): aggregates WILL wrap "
                    "if clients actually reach the clip bound",
                    bound,
                )
            else:
                min_step = (
                    s.cohort_size * max_w * s.clip_delta_norm / (2**31 - 1)
                )
                raise ValueError(
                    f"secure_aggregation worst-case aggregate bound "
                    f"cohort*max_weight*clip/quant_step = {bound:.3g} >= "
                    f"2^31 — an int32 wrap would silently corrupt the "
                    f"aggregate. Raise server.secagg_quant_step to at "
                    f"least {min_step:.3g}, or set "
                    f"server.secagg_allow_wrap_risk=true to accept the "
                    f"risk explicitly"
                )

    def _param_stats(self) -> tuple:
        """(n_coords, bytes) of one params tree at run.param_dtype, via
        eval_shape (no compute, no device memory — shapes only). Cached:
        the HBM pre-flight and the per-round comm-byte model share it."""
        if self._param_stats_cache is None:
            from colearn_federated_learning_tpu.client.trainer import (
                normalize_input,
            )

            dummy = jax.ShapeDtypeStruct(
                (1,) + self.fed.train_x.shape[1:],
                self.fed.train_x.dtype,  # LM corpora are int tokens — an
                # f32 dummy would crash nn.Embed's integer check
            )
            shapes = jax.eval_shape(
                lambda d: self.model.init(
                    jax.random.PRNGKey(0), normalize_input(d), train=False
                )["params"],
                dummy,
            )
            leaves = jax.tree.leaves(shapes)
            coords = sum(int(np.prod(l.shape)) for l in leaves)
            nbytes = sum(
                int(np.prod(l.shape)) * l.dtype.itemsize for l in leaves
            )
            self._param_stats_cache = (coords, nbytes)
        return self._param_stats_cache

    def _param_bytes(self) -> int:
        return self._param_stats()[1]

    def _full_param_stats(self) -> tuple:
        """(n_coords, bytes) of the FULL model — the trained tree's twin
        with LoRA off. Equals :meth:`_param_stats` for non-LoRA runs;
        under the adapter plane it is the frozen base model's size, the
        denominator of ``wire_reduction_vs_full``."""
        if not self._lora:
            return self._param_stats()
        if self._full_param_stats_cache is None:
            from colearn_federated_learning_tpu.client.trainer import (
                normalize_input,
            )

            dummy = jax.ShapeDtypeStruct(
                (1,) + self.fed.train_x.shape[1:], self.fed.train_x.dtype
            )
            shapes = jax.eval_shape(
                lambda d: self.model.base.init(
                    jax.random.PRNGKey(0), normalize_input(d), train=False
                )["params"],
                dummy,
            )
            leaves = jax.tree.leaves(shapes)
            self._full_param_stats_cache = (
                sum(int(np.prod(l.shape)) for l in leaves),
                sum(int(np.prod(l.shape)) * l.dtype.itemsize
                    for l in leaves),
            )
        return self._full_param_stats_cache

    def wire_reduction_vs_full(self) -> float:
        """Analytic per-client upload-byte ratio full-delta ÷ trained
        delta on THIS config's wire format (compression applies to both
        twins, so it cancels) — the logged LoRA communication win,
        exactly 1.0 for non-LoRA runs. Pure function of the config, so
        every engine logs the identical number."""
        if self._wire_reduction_cache is None:
            coords, p_bytes = self._param_stats()
            f_coords, f_bytes = self._full_param_stats()
            up = round_comm_bytes(
                self.cfg.server, 1, 1, coords, p_bytes
            )["upload_bytes"]
            full = round_comm_bytes(
                self.cfg.server, 1, 1, f_coords, f_bytes
            )["upload_bytes"]
            self._wire_reduction_cache = full / max(up, 1)
        return self._wire_reduction_cache

    # ------------------------------------------------------------------
    # analytic phase-cost model (obs/roofline.py)

    def _compute_itemsize(self) -> int:
        """Bytes per element at the EFFECTIVE compute precision — the
        same bf16-if-either-dtype-is-bf16 rule as the MFU basis."""
        basis, _ = mfu_basis(
            self.cfg.run.compute_dtype, self.cfg.run.local_param_dtype,
            self.cfg.run.param_dtype,
        )
        return 2 if basis == "bf16_peak" else 4

    def _xla_step_flops(self) -> Optional[int]:
        """XLA-counted FLOPs of one scan-free train step (fwd+bwd on one
        batch) — the bench's ``model_tflops_per_round`` machinery, but
        lowered from eval_shape structs so no params are materialized.
        None when the backend exposes no cost model."""
        from colearn_federated_learning_tpu.client.trainer import (
            make_loss_fn,
            normalize_input,
        )

        bs = self.cfg.client.batch_size
        try:
            dummy = jax.ShapeDtypeStruct(
                (1,) + self.fed.train_x.shape[1:], self.fed.train_x.dtype
            )
            p_shapes = jax.eval_shape(
                lambda d: self.model.init(
                    jax.random.PRNGKey(0), normalize_input(d), train=False
                )["params"],
                dummy,
            )
            x_s = jax.ShapeDtypeStruct(
                (bs,) + self.fed.train_x.shape[1:], self.fed.train_x.dtype
            )
            y_s = jax.ShapeDtypeStruct(
                (bs,) + self.fed.train_y.shape[1:], self.fed.train_y.dtype
            )
            m_s = jax.ShapeDtypeStruct((bs,), jnp.float32)
            step = jax.value_and_grad(make_loss_fn(self.model, self.task))
            compiled = jax.jit(step).lower(p_shapes, x_s, y_s, m_s).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0]
            if not ca or "flops" not in ca:
                return None
            return int(ca["flops"])
        except Exception:
            return None

    def _train_step_flops(self) -> tuple:
        """(flops, source) of ONE train step on one batch, cached for
        the run. ``run.obs.phase_cost_flops`` picks the source: the
        dense 6·P·B analytic approximation (default, zero compiles) or
        XLA's cost model (exact, one extra compile; falls back to
        analytic when the backend has no cost model)."""
        if self._step_flops_cache is None:
            coords, _ = self._param_stats()
            bs = self.cfg.client.batch_size
            x = self.fed.train_x
            # token corpora: the matmul unit is a token, not an example
            units = bs * (
                int(x.shape[1])
                if x.ndim == 2 and np.issubdtype(x.dtype, np.integer)
                else 1
            )
            flops, source = None, "analytic"
            if self.cfg.run.obs.phase_cost_flops == "xla":
                flops = self._xla_step_flops()
                if flops is not None:
                    source = "xla"
            if flops is None:
                if self._lora:
                    # adapter-aware step cost (obs/roofline.py): the
                    # frozen base still runs the forward + the
                    # activation-gradient backward; only the factor
                    # weight-gradients are trainable — 6·P_adapter·B
                    # would understate the step by ~P_full/P_adapter
                    # and 6·P_full·B would overstate it
                    full_coords, _ = self._full_param_stats()
                    flops = analytic_lora_step_flops(
                        full_coords, coords, units
                    )
                    source = "analytic_lora"
                else:
                    flops = analytic_step_flops(coords, units)
            self._step_flops_cache = (int(flops), source)
        return self._step_flops_cache

    def _record_phase_cost(self, round_idx: int, k: int, steps: int,
                           batch: int, host_input_bytes: int) -> None:
        """Analytic per-phase FLOP/byte costs for one round on its
        REALIZED (bucketed) grid — a pure function of the config and
        the grid, so the sharded, sequential, and fused engines record
        identical numbers (parity-pinned in tests/test_roofline.py).
        Drained into `phase_cost` JSONL records at flush boundaries."""
        cfg = self.cfg
        step_flops, _ = self._train_step_flops()
        coords, _ = self._param_stats()
        self._phase_costs[round_idx] = round_phase_costs(
            k=k, steps=steps, batch=batch, n_coords=coords,
            compute_bytes=self._compute_itemsize(), step_flops=step_flops,
            aggregator=cfg.server.aggregator,
            attack=bool(self._attack_upload),
            ledger=bool(self._ledger_on),
            reputation=bool(cfg.server.reputation.enabled),
            fused_apply=bool(cfg.server.fused_apply),
            host_input_bytes=int(host_input_bytes),
        )

    def _check_memory_budget(self) -> None:
        """Construction-time HBM pre-flight (VERDICT r4 missing-#4):
        estimate the PERSISTENT per-device footprint and fail fast with
        an actionable breakdown when it exceeds the budget. At the
        north-star scales the N·|params| stacks dominate: gossip
        N=1000 × ResNet-18 is ~44 GB f32 on one lane — impossible on a
        16 GB chip, and without this check the failure is an opaque
        RESOURCE_EXHAUSTED minutes into compilation. Transients
        (activations, collective buffers) are NOT modeled; the check is
        a lower bound on usage, so exceeding it is definitely fatal."""
        budget_gb = self.cfg.run.hbm_gb
        if budget_gb < 0:
            return
        if budget_gb == 0:
            # local_devices: under multi-process, jax.devices()[0] can
            # belong to ANOTHER process and memory_stats then raises
            dev = jax.local_devices()[0]
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if stats and stats.get("bytes_limit"):
                budget_gb = stats["bytes_limit"] / 2**30
            elif dev.platform == "cpu":
                return  # host RAM; no meaningful fixed budget
            else:
                budget_gb = 16.0  # TPU v5e default; override via run.hbm_gb
        gib = float(2**30)
        p_bytes = self._param_bytes()
        lanes = self.mesh.shape[mesh_lib.CLIENT_AXIS] if self.mesh else 1
        parts: Dict[str, float] = {}
        if not self._stream:
            parts["corpus (replicated)"] = (
                self.fed.train_x.nbytes + self.fed.train_y.nbytes
            ) / gib
        opt_factor = {"mean": 0, "fedavgm": 1, "fedadam": 2, "fedyogi": 2}[
            self.cfg.server.optimizer
        ]
        parts["params + server opt"] = p_bytes * (1 + opt_factor) / gib
        state_itemsize = (
            2 if self.cfg.server.client_state_dtype == "bfloat16" else 4
        )
        if self.store_state:
            rows = self._state_rows / lanes
            n_trees = 1 + (1 if self.stateful else 0)  # store (+ c_global)
            parts["per-client state store / lane"] = (
                rows * p_bytes * state_itemsize / 4 * n_trees / gib
            )
        if self.gossip:
            parts["gossip replica stack / lane"] = (
                (self.fed.num_clients / lanes) * p_bytes / gib
            )
        if self.fedbuff:
            window = 2 * self.cfg.server.async_max_staleness + 1
            parts["fedbuff history ring"] = window * p_bytes / gib
        total = sum(parts.values())
        if total > 0.9 * budget_gb:
            breakdown = "; ".join(f"{k}: {v:.2f} GiB" for k, v in parts.items())
            raise ValueError(
                f"persistent HBM footprint ≈ {total:.2f} GiB exceeds 90% "
                f"of the {budget_gb:.1f} GiB device budget ({breakdown}). "
                f"Remedies: data.placement=stream (drops the replicated "
                f"corpus), server.client_state_dtype=bfloat16 (halves the "
                f"state store), more mesh lanes (stacks shard over "
                f"lanes), fewer clients, or a smaller model. Set "
                f"run.hbm_gb to adjust the budget or -1 to disable this "
                f"check."
            )

    def preflight(self) -> Dict[str, Any]:
        """OOM preflight (``colearn preflight``): walk ONE round of the
        real dispatch path with a preflight-mode executable registry —
        every instrumented jit site lowers and compiles (XLA memory
        analysis = the predicted peak) but returns abstract
        ``ShapeDtypeStruct`` outputs instead of executing, so output
        and temp buffers are never allocated. Host-side inputs (params,
        cohort slabs) ARE staged — they must fit anyway for the run to
        start; the unknown the preflight answers is the program's
        working set. Returns the registry's report (predicted peak
        bytes + per-program dominant buffers); raises
        :class:`HbmBudgetError` when ``run.obs.hbm_budget_mb`` is set
        and exceeded.

        Requires a fully-jitted round program: the sequential oracle's
        eager python loop cannot run on abstract values."""
        if self.cfg.run.engine != "sharded":
            raise ValueError(
                "preflight requires run.engine=sharded: the sequential "
                "oracle's eager per-client loop cannot run on abstract "
                "outputs"
            )
        obs = self.cfg.run.obs
        reg = ExecutableRegistry(
            preflight=True,
            hbm_budget_bytes=obs.hbm_budget_mb * 2**20,
            device_capacity_bytes=exec_mod.device_hbm_capacity(),
            tracer=self.tracer,
        )
        prev = exec_mod.current()
        exec_mod.install(reg)
        try:
            state = self._place_state(self.init_state())
            try:
                self.run_round(state, 0)
            except HbmBudgetError:
                raise
            except Exception:
                # post-dispatch host unwinding on abstract outputs
                # (metric slicing, store scatter) is expected to fail —
                # the programs were already captured at that point. An
                # empty registry means the dispatch itself never
                # lowered: that IS the preflight failure.
                if not reg.preflight_report()["programs"]:
                    raise
        finally:
            if prev is not None:
                exec_mod.install(prev)
            else:
                exec_mod.uninstall()
        return reg.preflight_report()

    def _local_dtype(self):
        d = self.cfg.run.local_param_dtype
        return _DTYPES[d] if d else None

    def _put(self, arr, sharding):
        if sharding is None:
            return jax.device_put(arr)
        if jax.process_count() > 1:
            from colearn_federated_learning_tpu.parallel.distributed import (
                host_local_array,
            )

            return host_local_array(arr, sharding)
        return jax.device_put(arr, sharding)

    def _put_data(self, arr):
        return self._put(arr, self._data_sharding)

    def init_state(self, seed: Optional[int] = None) -> Dict[str, Any]:
        seed = self.cfg.run.seed if seed is None else seed
        rng = jax.random.PRNGKey(seed)
        init_rng, run_rng = jax.random.split(rng)
        from colearn_federated_learning_tpu.client.trainer import normalize_input

        dummy = normalize_input(jnp.asarray(self.fed.train_x[:1]))
        variables = self.model.init(init_rng, dummy, train=False)
        params = variables["params"]
        state = {
            "params": params,
            "server_opt_state": self.server_opt_init(params),
            "round": 0,
            "rng_key": run_rng,
        }
        if self.store_state:
            # scaffold: c (replicated) + all-clients cᵢ; feddyn: h + gᵢ
            # — same shapes; error feedback: per-client eᵢ residuals
            # only (no global). The template is host numpy (cheap: zeros
            # are lazily allocated); _place_state moves it to the device
            # store (sharded engine) or keeps it writable numpy
            # (sequential oracle). Rows are lane-padded under the
            # sharded engine; pad rows are never addressed.
            if self.stateful:
                state["c_global"] = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32), params
                )
            state["c_clients"] = jax.tree.map(
                lambda p: np.zeros((self._state_rows,) + p.shape, np.float32),
                params,
            )
        if self._ledger_on:
            # per-client forensic ledger rows (count, flagged, EMAs);
            # dense: row index == client id; paged: row index == HOT
            # SLOT (the driver remaps ids — see LedgerPager), with the
            # cold spill + slot bookkeeping riding alongside. No lane
            # padding either way (the store is replicated — a few KB).
            # Pads/non-residents scatter out of bounds and drop.
            from colearn_federated_learning_tpu.obs.ledger import (
                LEDGER_WIDTH,
            )

            state["ledger"] = np.zeros(
                (self._ledger_rows, LEDGER_WIDTH), np.float32
            )
            if self._pager is not None:
                state["ledger_cold"] = np.zeros(
                    (self.fed.num_clients, LEDGER_WIDTH), np.float32
                )
                state["ledger_slots"] = np.full(
                    self._ledger_rows, -1, np.int64
                )
                state["ledger_slot_used"] = np.full(
                    self._ledger_rows, -1, np.int64
                )
        if self._adaptive:
            # the adaptive sampler's ACTIVE ledger snapshot (host-side,
            # refreshed at log_every round boundaries) rides the
            # checkpoint so a resumed run scores rounds between
            # snapshot boundaries exactly like the straight run did.
            # Column-slimmed (PR 9): only the three scored columns
            # (sampler.SNAPSHOT_COLS) are fetched and persisted.
            from colearn_federated_learning_tpu.server.sampler import (
                SNAPSHOT_COLS,
            )

            state["ledger_snapshot"] = np.zeros(
                (self.fed.num_clients, len(SNAPSHOT_COLS)), np.float32
            )
            state["ledger_snapshot_round"] = 0
        if self._streaming and self._snapshot_refresh:
            # the streaming sampler's fixed-size score sketch: columnar
            # (ids, scored stats) arrays bounded by sketch_size — the
            # O(1)-in-num_clients replacement for the dense snapshot
            state["ledger_sketch_ids"] = np.full(
                len(self._sketch_ids), -1, np.int32
            )
            state["ledger_sketch_stats"] = np.zeros(
                self._sketch_stats.shape, np.float32
            )
            state["ledger_snapshot_round"] = 0
        if self.gossip:
            # every client starts at the same point (the standard
            # consensus init); the stack is host numpy until
            # _place_state shards it over the mesh
            state["replicas"] = jax.tree.map(
                lambda p: np.broadcast_to(
                    np.asarray(p)[None], (self.fed.num_clients,) + p.shape
                ).copy(),
                params,
            )
        if self.fedbuff:
            s_max = self.cfg.server.async_max_staleness
            window = 2 * s_max + 1
            k = self.cfg.server.cohort_size
            m = k * s_max  # in-flight concurrency
            state["history"] = jax.tree.map(
                lambda p: jnp.broadcast_to(p[None], (window,) + p.shape), params
            )
            qrng = np.random.default_rng((seed, 8191))
            state["queue_clients"] = qrng.choice(
                self.fed.num_clients, size=m,
                replace=m > self.fed.num_clients,
            ).astype(np.int32)
            state["queue_versions"] = np.zeros(m, np.int32)
            state["queue_finish"] = self._client_durations(
                state["queue_clients"], qrng
            )
            state["queue_seq"] = np.arange(m, dtype=np.int32)
            state["queue_next_seq"] = m
            if self._versions > 1:
                # multi-version lines: line 0 keeps the legacy keys
                # above; each extra line is an independent FedBuff
                # instance (own params/opt/history ring/queue) seeded
                # from its own qrng stream. line_* carries the
                # retirement generation bookkeeping per line.
                V = self._versions
                state["queue_gen"] = np.zeros(m, np.int32)
                for li in range(1, V):
                    qrng_l = np.random.default_rng((seed, 8191, li))
                    state[f"params_l{li}"] = params
                    state[f"server_opt_state_l{li}"] = (
                        self.server_opt_init(params)
                    )
                    state[f"history_l{li}"] = jax.tree.map(
                        lambda p: jnp.broadcast_to(
                            p[None], (window,) + p.shape
                        ), params,
                    )
                    state[f"queue_clients_l{li}"] = qrng_l.choice(
                        self.fed.num_clients, size=m,
                        replace=m > self.fed.num_clients,
                    ).astype(np.int32)
                    state[f"queue_versions_l{li}"] = np.zeros(m, np.int32)
                    state[f"queue_finish_l{li}"] = self._client_durations(
                        state[f"queue_clients_l{li}"], qrng_l
                    )
                    state[f"queue_seq_l{li}"] = np.arange(m, dtype=np.int32)
                    state[f"queue_next_seq_l{li}"] = m
                    state[f"queue_gen_l{li}"] = np.zeros(m, np.int32)
                state["line_gen"] = np.zeros(V, np.int32)
                state["line_birth"] = np.zeros(V, np.int32)
                state["line_absorbed"] = np.zeros(V, np.int64)
        if self._hier:
            # per-edge reputation trust for the core tier (EMA over
            # edge liveness; consumed when core_aggregator="reputation",
            # always maintained as a health signal). Checkpointed.
            state["edge_trust"] = np.ones(
                self.cfg.server.hierarchy.num_edges, np.float32
            )
        # digest-chain head (run.obs.digest): uint32 [hash_lo, hash_hi,
        # round], all-zero = genesis. ALWAYS in the template — orbax
        # restore requires template/checkpoint key agreement, and a
        # digest-off run must be able to restore a digest-on run's
        # checkpoint (and vice versa). Popped from live state at fit
        # start (_fit_body) and re-injected at every save site.
        state["digest_head"] = np.zeros(3, np.uint32)
        return state

    def _client_durations(self, clients: np.ndarray, rng) -> np.ndarray:
        """Simulated train durations (server steps, 1..S) for the given
        clients: SIZE-CORRELATED (VERDICT r2 weak-#4) — a client's local
        work is its capped example count, so the per-client base duration
        is its work rank quantile-mapped into 1..S, plus ±1 stochastic
        jitter. Big-data clients therefore finish later and accumulate
        more staleness, which couples the staleness distribution to the
        data heterogeneity — the regime async FL is designed for.
        Durations stay ≤ S, so the pop-K-earliest 2S staleness bound
        (and the 2S+1 ring sizing) is unchanged."""
        s_max = self.cfg.server.async_max_staleness
        base = self._duration_base[clients]
        jitter = rng.integers(-1, 2, size=len(clients))
        return np.clip(base + jitter, 1, s_max).astype(np.int32)

    def _place_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Replicate params/opt state over the mesh (fresh init or restore)."""
        if self._data_sharding is not None:
            state["params"] = self._put_data(state["params"])
            state["server_opt_state"] = self._put_data(state["server_opt_state"])
            if self.stateful:
                state["c_global"] = self._put_data(state["c_global"])
        if self.store_state:
            if self._data_sharding is not None:
                # device-resident store: client-sharded over the mesh at
                # the configured storage dtype; HBM budget is
                # state_rows·|params| at that dtype ÷ lanes per chip.
                # Cast on HOST (ml_dtypes numpy bf16) and hand numpy to
                # device_put so only each chip's shard is uploaded — a
                # jnp cast would transiently materialize the FULL store
                # on one device, an L× spike over the per-chip budget.
                if self.cfg.server.client_state_dtype == "bfloat16":
                    import ml_dtypes

                    np_dt = ml_dtypes.bfloat16
                else:
                    np_dt = np.float32

                def _place_store(a):
                    if isinstance(a, jax.Array) and a.dtype == np_dt:
                        # warm-start state from a previous fit() on this
                        # Experiment: already device-resident + sharded
                        # (fetching it would break under multi-host)
                        return a
                    return self._put(
                        np.asarray(a).astype(np_dt, copy=False),
                        self._client_sharding,
                    )

                state["c_clients"] = jax.tree.map(
                    _place_store, state["c_clients"]
                )
            else:
                # sequential oracle: restored checkpoints arrive as jax
                # arrays; the host scatter path needs writable numpy
                # (fresh init already is)
                state["c_clients"] = jax.tree.map(
                    lambda a: a
                    if isinstance(a, np.ndarray) and a.flags.writeable
                    else np.array(a, dtype=np.float32, copy=True),
                    state["c_clients"],
                )
        if self._ledger_on:
            # ledger (dense, or the paged HOT set): replicated device
            # array (tiny); a warm-start or restored ledger arrives as
            # jax/numpy — both place fine
            state["ledger"] = self._put(
                jnp.asarray(np.asarray(state["ledger"], np.float32)),
                self._data_sharding,
            )
            if self._pager is not None:
                # cold spill + slot bookkeeping stay HOST-side: load
                # them into the pager's mmap/maps and re-point the
                # state at the live structures (so later checkpoints
                # capture the current paging state without copies)
                self._pager.load_state(
                    state["ledger_slots"], state["ledger_slot_used"],
                    state["ledger_cold"],
                )
                state["ledger_cold"] = self._pager.cold
                state["ledger_slots"] = self._pager.slot_clients
                state["ledger_slot_used"] = self._pager.slot_used
        if self._adaptive:
            # the sampler snapshot stays HOST-side (the sampler is host
            # code); a restored checkpoint hands back jax arrays
            state["ledger_snapshot"] = np.asarray(
                state["ledger_snapshot"], np.float32
            )
            state["ledger_snapshot_round"] = int(
                np.asarray(state["ledger_snapshot_round"])
            )
        if self._streaming and self._snapshot_refresh:
            state["ledger_sketch_ids"] = np.asarray(
                state["ledger_sketch_ids"], np.int32
            )
            state["ledger_sketch_stats"] = np.asarray(
                state["ledger_sketch_stats"], np.float32
            )
            state["ledger_snapshot_round"] = int(
                np.asarray(state["ledger_snapshot_round"])
            )
        if self.gossip:
            # warm-start replicas from a previous fit() on this
            # Experiment are already device-resident + client-sharded;
            # fresh init / orbax restore arrive as host numpy and only
            # each chip's shard is uploaded (same rationale as the
            # scaffold store placement above)
            state["replicas"] = jax.tree.map(
                lambda a: a if isinstance(a, jax.Array)
                else self._put(np.asarray(a), self._client_sharding),
                state["replicas"],
            )
        if self.fedbuff:
            V = self._versions
            qkeys = ["queue_clients", "queue_versions", "queue_finish",
                     "queue_seq"] + (["queue_gen"] if V > 1 else [])
            for li in range(V):
                sfx = "" if li == 0 else f"_l{li}"
                if self._data_sharding is not None:
                    state["history" + sfx] = self._put_data(
                        state["history" + sfx]
                    )
                    if sfx:
                        # extra lines' trees place like line 0's (which
                        # went through the generic params placement at
                        # the top of this method)
                        state["params" + sfx] = self._put_data(
                            state["params" + sfx]
                        )
                        state["server_opt_state" + sfx] = self._put_data(
                            state["server_opt_state" + sfx]
                        )
                for key in qkeys:
                    a = state[key + sfx]
                    if not (isinstance(a, np.ndarray) and a.flags.writeable):
                        state[key + sfx] = np.array(
                            a, dtype=np.int32, copy=True
                        )
                state["queue_next_seq" + sfx] = int(
                    state["queue_next_seq" + sfx]
                )
            if V > 1:
                for key, dt in (("line_gen", np.int32),
                                ("line_birth", np.int32),
                                ("line_absorbed", np.int64)):
                    a = state[key]
                    if not (isinstance(a, np.ndarray) and a.flags.writeable
                            and a.dtype == dt):
                        state[key] = np.array(a, dtype=dt, copy=True)
        if self._hier:
            a = state["edge_trust"]
            if not (isinstance(a, np.ndarray) and a.flags.writeable
                    and a.dtype == np.float32):
                state["edge_trust"] = np.array(
                    a, dtype=np.float32, copy=True
                )
        return state

    # ---- heterogeneity-aware round shapes (run.shape_buckets) --------

    def _round_bucket_spe(self, round_idx: int) -> int:
        """The ladder rung (steps_per_epoch) for one round: smallest
        rung whose grid holds the SAMPLED cohort's max capped shard.
        Pure in (seed, round) — the sampler is stateless, so the
        prefetch worker, a resume, and the fused chunk-max computation
        all agree without coordination."""
        spe = self._bucket_cache.get(round_idx)
        if spe is None:
            cohort = np.asarray(self.sampler.sample(round_idx))
            max_need = (
                int(self._sizes_capped[cohort].max()) if len(cohort) else 1
            )
            need = max(1, -(-max_need // self.shape.batch_size))
            spe = pick_bucket(need, self._bucket_ladder)
            self._bucket_cache[round_idx] = spe
        return spe

    def _bucket_shape(self, spe: int) -> RoundShape:
        import dataclasses as _dc

        shp = self._bucket_shapes.get(spe)
        if shp is None:
            shp = _dc.replace(self.shape, steps_per_epoch=spe)
            self._bucket_shapes[spe] = shp
        return shp

    def _round_shape(self, round_idx: int) -> RoundShape:
        """The round's grid shape: a ladder rung under shape buckets,
        the federation-max legacy shape otherwise."""
        if self._bucket_ladder is None:
            return self.shape
        return self._bucket_shape(self._round_bucket_spe(round_idx))

    def _bucket_compile_span(self, round_idx: int, steps: int):
        """Context manager wrapping the FIRST dispatch on a new ladder
        rung: brackets the tracer's backend_compile counters and logs a
        `shape_bucket` event attributing the rung's retrace cost — the
        per-bucket compile accounting the ≤-ladder-size budget is
        asserted against (tests/test_shape_buckets.py)."""
        from contextlib import contextmanager

        @contextmanager
        def span():
            if self._exec_reg is not None:
                # every dispatch site enters this span — the registry's
                # records carry the round they were compiled on
                self._exec_reg.round = round_idx + 1
            if self._bucket_ladder is None or steps in self._seen_buckets:
                yield
                return
            self._seen_buckets.add(steps)
            c0, s0 = self.tracer.compile_stats()
            yield
            c1, s1 = self.tracer.compile_stats()
            self.logger.log({
                "event": "shape_bucket",
                "round": round_idx + 1,
                "bucket_steps": int(steps),
                "ladder_steps": [
                    r * self.cfg.client.local_epochs
                    for r in self._bucket_ladder
                ],
                "compiles": int(c1 - c0),
                "compile_ms": round((s1 - s0) * 1000.0, 3),
            })

        return span()

    def _host_inputs(self, round_idx: int, shape: Optional[RoundShape] = None,
                     build_slab: bool = True):
        """All host-side work for one round: sampling, index construction,
        dropout weights, and (stream mode) the slab gather. Pure in
        (seed, round) — safe to run ahead on a worker thread.
        ``shape`` overrides the round's grid (the fused chunk-max path);
        default is the round's own bucket rung (or the legacy full
        shape). Under ``_spec_inputs`` the third return slot carries the
        [K, 2] mask SPEC instead of the full float32 mask.
        ``build_slab=False`` skips the per-round stream slab — the fused
        chunk path gathers ONE union slab over the whole chunk instead."""
        # named control-plane sub-spans (children of round.host_inputs
        # in the waterfall — roofline excludes them from host_exposed
        # totals so nothing double-counts): exactly the work the device
        # control plane removes, attributable line by line
        with self.tracer.span("round.host_inputs.sampler"):
            if self.gossip and self._gossip_partial == 0:
                # full participation: row i of the round tensors IS
                # client i (the ring order is the client-id order,
                # every round)
                cohort = np.arange(self.fed.num_clients, dtype=np.int64)
            else:
                # centralized cohorts, or partial-participation
                # gossip's per-round active subset (uniform without
                # replacement)
                cohort = self.sampler.sample(round_idx)
        if shape is None:
            shape = self._round_shape(round_idx)
        host_rng = np.random.default_rng((self.cfg.run.seed, 7919, round_idx))
        with self.tracer.span("round.host_inputs.slab_build"):
            if self._native is not None:
                self._native.submit(round_idx, cohort)  # no-op if prefetched
                # overlap: the NEXT dispatch's tensors build on C++
                # worker threads while the device executes this one.
                # Under run.fuse_rounds > 1 a dispatch consumes a whole
                # chunk, so the look-ahead window is `fuse` rounds of
                # index slabs per submit (duplicate submits are no-ops
                # in the pipeline).
                ahead = max(1, self.cfg.run.fuse_rounds)
                for j in range(1, ahead + 1):
                    nxt = round_idx + j
                    if nxt < self.cfg.server.num_rounds:
                        self._native.submit(nxt, self.sampler.sample(nxt))
                idx, mask, n_ex = self._native.fetch(round_idx, len(cohort))
                if self._spec_inputs:
                    # the pipeline skipped the mask slab
                    # (build_mask=False); the spec is analytic — native
                    # packs each epoch's min(|shard|, cap) real indices
                    # contiguously
                    take = self._sizes_capped[np.asarray(cohort)]
                    mask = np.stack(
                        [take, np.full(len(cohort), shape.steps, np.int64)],
                        1,
                    ).astype(np.int32)
            elif self._spec_inputs:
                idx, mask, n_ex = make_round_spec(
                    self.fed, cohort, shape, host_rng
                )
            else:
                idx, mask, n_ex = make_round_indices(
                    self.fed, cohort, shape, host_rng
                )
        with self.tracer.span("round.host_inputs.churn"):
            mask, n_ex = self._apply_failures(
                mask, n_ex, len(cohort), host_rng, round_idx=round_idx,
                shape=shape, cohort=cohort,
            )
        if self._poisson:
            cap, b = self._poisson_cap, len(cohort)
            if b > cap:
                raise RuntimeError(
                    f"poisson cohort {b} exceeded the static cap {cap} "
                    f"(a ~5-sigma event; its exact probability is logged "
                    f"as dp_delta_abort and is part of the DP delta). "
                    f"Aborting rather than silently truncating — rerun "
                    f"with a different seed or a larger cohort_size."
                )
            pad = cap - b
            if pad:
                # pad id == num_clients: OUT OF RANGE by construction, so
                # state-store scatters drop it and no real client's row
                # can be touched by a pad slot; pad rows carry zero mask
                # and zero weight (exact no-ops, the dropout machinery)
                cohort = np.concatenate(
                    [cohort, np.full(pad, self.fed.num_clients, cohort.dtype)]
                )
                idx = np.concatenate(
                    [idx, np.zeros((pad,) + idx.shape[1:], idx.dtype)]
                )
                mask = np.concatenate(
                    [mask, np.zeros((pad,) + mask.shape[1:], mask.dtype)]
                )
                n_ex = np.concatenate([n_ex, np.zeros(pad, n_ex.dtype)])
        slab = (
            self._stream_slab(idx) if self._stream and build_slab else None
        )
        return cohort, idx, mask, n_ex, slab

    def _apply_failures(self, mask, n_ex, k, host_rng, round_idx=None,
                        shape=None, cohort=None):
        """Straggler truncation + dropout zeroing — shared by the sync
        cohort path and the async (fedbuff) scheduler. Realized counts
        are recorded per round for the telemetry counters (this runs on
        the prefetch worker thread too; dict stores are atomic).
        ``mask`` is either the full [K, steps, batch] float mask or the
        [K, 2] spec (``_spec_inputs``) — straggler truncation writes the
        spec's valid-steps column and recomputes the weights through the
        closed form ``spec_examples`` (exactly ``mask.sum((1, 2))`` of
        the expanded mask), so both representations realize identical
        failures from identical host draws.

        With ``run.churn`` on, ``cohort`` (the round's client ids)
        additionally realizes the seed-pure churn draws through the
        SAME machinery: a crash-mid-round truncates the client's mask
        at its hash-drawn work fraction (the straggler path — partial
        work still aggregates), and offline/hazard-dropped members
        zero their weight (the dropout path). Every churn draw is a
        pure function of (seed, round, id) — no host_rng consumption —
        so churn-on failures are identical across engines, resumes,
        and the prefetch worker, and churn-off leaves host_rng's
        stream untouched (the bitwise-identity contract). An all-
        dropped round is legitimate (a diurnal trough): the engines'
        degenerate-denominator path handles it, exactly like an empty
        poisson round."""
        if k == 0:
            return mask, n_ex  # empty poisson round: nothing to fail
        shape = shape or self.shape
        spec_mode = mask.ndim == 2  # [K, 2] spec vs [K, steps, batch]
        n_strag = n_drop = 0
        if self.cfg.server.straggler_rate > 0:
            # simulated stragglers (SURVEY.md §5, FedProx's motivating
            # scenario): a fraction of the cohort completes only
            # straggler_work of its local steps — their mask tail is
            # truncated, so the engine's padded-step machinery makes the
            # unfinished steps exact no-ops and the FedAvg weight (and
            # SCAFFOLD's Kᵢ) shrinks to the work actually done
            strag = host_rng.random(k) < self.cfg.server.straggler_rate
            if strag.any():
                done = max(1, int(round(
                    self.cfg.server.straggler_work * shape.steps
                )))
                mask = mask.copy()
                if spec_mode:
                    mask[strag, 1] = np.minimum(mask[strag, 1], done)
                    n_ex = spec_examples(mask, shape)
                else:
                    mask[strag, done:, :] = 0.0
                    n_ex = mask.sum((1, 2))
                n_strag = int(strag.sum())
        if self.cfg.server.dropout_rate > 0:
            # simulated client dropout (SURVEY.md §5): zero the FedAvg weight
            participate = (
                host_rng.random(k) >= self.cfg.server.dropout_rate
            )
            if not participate.any():
                participate[host_rng.integers(k)] = True
            n_ex = n_ex * participate.astype(np.float32)
            if self.gossip:
                # gossip has no aggregation weight for n_ex to zero —
                # the local phase is gated by the step mask, so a
                # dropped client must have its mask zeroed too (it then
                # trains zero valid steps and only RELAYS its replica,
                # the decentralized dropout semantics)
                mask = mask.copy()
                mask[~participate] = 0.0
            n_drop = int(k - participate.sum())
        n_unavail = n_hazard = n_crash = 0
        if (self._churn is not None and cohort is not None
                and round_idx is not None):
            ids = np.asarray(cohort, np.int64)
            real = ids < self.fed.num_clients  # poisson pads never churn
            crashed, frac = self._churn.crashed(round_idx, ids)
            crashed &= real
            if crashed.any():
                # crash-mid-round: truncate at the hash-drawn fraction
                # of the FULL step grid (≥ 1 step — a crash during
                # step 1 still uploads that step's work)
                done = np.maximum(
                    1, np.floor(frac * shape.steps).astype(np.int64)
                )
                mask = mask.copy()
                if spec_mode:
                    mask[crashed, 1] = np.minimum(
                        mask[crashed, 1], done[crashed]
                    )
                    n_ex = spec_examples(mask, shape)
                else:
                    cut = (
                        np.arange(shape.steps)[None, :] < done[crashed, None]
                    )
                    mask[crashed] = mask[crashed] * cut[:, :, None].astype(
                        mask.dtype
                    )
                    n_ex = mask.sum((1, 2))
                n_crash = int(crashed.sum())
            offline = ~self._churn.available(round_idx, ids) & real
            hazard = self._churn.dropped(round_idx, ids) & real
            churn_drop = offline | hazard
            if churn_drop.any():
                n_ex = n_ex * (~churn_drop).astype(np.float32)
                n_unavail = int(offline.sum())
                n_hazard = int((hazard & ~offline).sum())
        if (round_idx is not None and self._counters_on
                and (n_strag or n_drop or n_unavail or n_hazard or n_crash)):
            stats = {}
            if n_strag or n_drop:
                stats["straggler_clients"] = n_strag
                stats["dropped_clients"] = n_drop
            if n_unavail:
                stats["churn_unavailable"] = n_unavail
            if n_hazard:
                stats["churn_dropped"] = n_hazard
            if n_crash:
                stats["churn_crashed"] = n_crash
            self._fail_stats[round_idx] = stats
        return mask, n_ex

    def _prefetch_spe(self, round_idx: int) -> Optional[int]:
        """The ladder rung the steady-state dispatch will request for
        this round (None without buckets): the chunk-max rung under
        fusion, the round's own rung otherwise. Pure in (seed, round),
        so the prefetch worker and the consumer agree — an unaligned-
        resume catch-up round (dispatched fuse=1 on its OWN rung) is
        the one deliberate mismatch, and the consumer drains it."""
        if self._bucket_ladder is None:
            return None
        fuse = self.cfg.run.fuse_rounds
        if fuse > 1:
            start = round_idx - round_idx % fuse
            end = min(start + fuse, self.cfg.server.num_rounds)
            return max(self._round_bucket_spe(j) for j in range(start, end))
        return self._round_bucket_spe(round_idx)

    def _place_round_inputs(self, idx, mask, n_ex, slab):
        """Device placement of one round's host tensors — shared by the
        critical path and the double-buffer prefetch worker (device_put
        is async, so a worker-thread placement overlaps the dispatched
        compute of the PREVIOUS round)."""
        if slab is not None:
            idx, slab_x, slab_y = slab
            train_x = self._put_data(jnp.asarray(slab_x))
            train_y = self._put_data(jnp.asarray(slab_y))
        else:
            train_x, train_y = self.train_x, self.train_y
        if self._cohort_sharding is not None:
            idx = self._put(idx, self._cohort_sharding)
            # the [K, 2] spec has no batch dim — cohort-sharded only
            mask = self._put(
                mask,
                self._client_sharding if self._spec_inputs
                else self._cohort_sharding,
            )
            n_ex = self._put(n_ex, self._client_sharding)
        return idx, mask, n_ex, train_x, train_y

    def _build_prefetch_entry(self, round_idx: int, spe: Optional[int],
                              place: bool) -> Dict[str, Any]:
        """Worker-thread body: build (and, double-buffered, place) one
        round's inputs. The entry records the rung it was built for so
        the consumer can detect (and drain) a grid mismatch."""
        shape = self._bucket_shape(spe) if spe is not None else None
        cohort, idx, mask, n_ex, slab = self._host_inputs(
            round_idx, shape=shape,
            # fused chunks consume host tensors only (the union slab is
            # gathered at chunk-stack time); per-round slabs would be
            # wasted work the consumer drops
            build_slab=self.cfg.run.fuse_rounds == 1,
        )
        placed = (
            self._place_round_inputs(idx, mask, n_ex, slab) if place
            else None
        )
        return {"spe": spe, "host": (cohort, idx, mask, n_ex, slab),
                "placed": placed}

    def _build_chunk_slab_entry(self, start: int, fuse: int,
                                spe: Optional[int]) -> Optional[Dict[str, Any]]:
        """Worker-thread body for the fused chunk-union slab (stream ×
        fuse overlap): stack the chunk's index grids — reusing the
        per-round prefetch entries, which the one-worker executor's
        FIFO order guarantees already completed; _host_inputs is pure
        in (seed, round), so rebuilding any missing one is bitwise
        harmless — dedup into the union row set, and run the store
        gather (the expensive mmap I/O) off the critical path. The
        consumer verifies the row set against its own stack and drains
        on any mismatch, so a wrong-shape build can never smuggle
        wrong bytes into a dispatch."""
        shape = self._bucket_shape(spe) if spe is not None else None
        idxs = []
        for t in range(start, start + fuse):
            entry = None
            fut = self._prefetch.get(t)
            if fut is not None:
                entry = fut.result()
            if entry is not None and entry["spe"] == spe:
                idxs.append(entry["host"][1])
            else:
                _c, idx, _m, _n, _s = self._host_inputs(
                    t, shape=shape, build_slab=False
                )
                idxs.append(idx)
        uniq = np.unique(np.stack(idxs))
        rows = self._fused_slab_rows
        if len(uniq) > rows:
            # overflow is the consumer's error to raise (same message,
            # its own stack); an over-full prefetched slab is just drained
            return None
        slab_x = np.empty((rows,) + self.fed.train_x.shape[1:],
                          self.fed.train_x.dtype)
        slab_y = np.empty((rows,) + self.fed.train_y.shape[1:],
                          self.fed.train_y.dtype)
        slab_x[: len(uniq)] = self.fed.train_x[uniq]
        slab_y[: len(uniq)] = self.fed.train_y[uniq]
        return {"spe": spe, "fuse": fuse, "uniq": uniq,
                "slab_x": slab_x, "slab_y": slab_y}

    def _submit_chunk_slab_prefetch(self, round_idx: int, fuse: int) -> None:
        """Queue the NEXT chunk's union-slab store gather on the host
        worker — called right before this chunk's dispatch, so the
        gather I/O runs while the device executes and the next
        ``round.stream_slab`` span collapses to a verify+remap. The
        next chunk's per-round host builds are already queued ahead of
        it (FIFO), so the slab builder reuses their index grids. The
        ledger-snapshot refresh boundary rule from _maybe_prefetch
        applies chunk-wholesale: a chunk past the boundary is a
        function of a snapshot that does not exist yet."""
        if (not self._stream or not self._double_buffer
                or self._native is not None):
            return
        start = round_idx + fuse
        if (start >= self.cfg.server.num_rounds
                or start in self._chunk_prefetch):
            return
        if self._snapshot_refresh:
            le = self._ledger_cfg.log_every
            if le and (start + fuse - 1) // le != round_idx // le:
                return
        ex = self._ensure_executor()
        if ex is None:
            return
        self._chunk_prefetch[start] = ex.submit(
            self._build_chunk_slab_entry, start, fuse,
            self._prefetch_spe(start),
        )

    def _ensure_executor(self):
        if self._host_executor is None and (
            self._double_buffer or self._stream
        ):
            from concurrent.futures import ThreadPoolExecutor

            # ONE worker: all builds serialize, so the native pipeline
            # and the samplers never see two concurrent builders
            self._host_executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="colearn-prefetch"
            )
        return self._host_executor

    def _maybe_prefetch(self, round_idx: int) -> None:
        """Submit the upcoming rounds' input builds to the host worker
        (run.double_buffer): the next round's build AND placement run
        while round_idx's dispatched compute executes — the second
        in-flight placed-slab buffer. Under fuse_rounds the whole next
        chunk's host slabs build ahead (placement stays with the chunk
        stacker, and the chunk-union STORE GATHER runs ahead through
        _build_chunk_slab_entry); double-buffered stream mode builds
        AND places the next round's slab ahead too — the second
        in-flight slab is the overlap buffer, the deliberate +1-slab
        cost of hiding the store gather under dispatch (legacy
        non-double-buffered stream keeps the build-only single
        look-ahead). The adaptive sampler never prefetches across a
        ledger-snapshot refresh boundary — the cohort there is a
        function of a snapshot that does not exist yet."""
        ex = self._ensure_executor()
        if ex is None:
            return
        fuse = self.cfg.run.fuse_rounds
        if not self._double_buffer:
            depth = 1  # legacy stream-mode behavior
        elif fuse > 1:
            depth = fuse
        else:
            depth = 2
        for t in range(round_idx + 1, round_idx + 1 + depth):
            if t >= self.cfg.server.num_rounds or t in self._prefetch:
                continue
            if self._snapshot_refresh:
                # never prefetch across a snapshot/sketch refresh
                # boundary — the cohort there is a function of a
                # snapshot that does not exist yet (adaptive AND
                # sketch-fed streaming sampling)
                le = self._ledger_cfg.log_every
                if le and t // le != round_idx // le:
                    continue
            place = self._double_buffer and fuse == 1
            self._prefetch[t] = ex.submit(
                self._build_prefetch_entry, t, self._prefetch_spe(t), place
            )

    def _round_inputs(self, round_idx: int, place: bool = True,
                      shape: Optional[RoundShape] = None):
        """``place=False`` returns the idx/mask/n_ex tensors as HOST
        arrays (the fused-chunk path stacks `fuse` rounds of them and
        places the [F, ...] slabs once through the fused shardings —
        stacking already-placed global arrays would be an eager op on
        non-addressable shards under multi-process). ``shape`` is the
        fused chunk-max grid override; prefetch entries are keyed by
        round with the bucket baked in (the bucket is a pure function
        of the round, so worker and consumer agree)."""
        if shape is not None:
            want_spe = shape.steps_per_epoch
        elif self._bucket_ladder is not None:
            want_spe = self._round_bucket_spe(round_idx)
        else:
            want_spe = None
        fut = self._prefetch.pop(round_idx, None)
        entry = None
        # the span measures the CRITICAL-PATH host-input cost: ~0 when
        # the prefetch worker ran ahead, the full build otherwise
        with self.tracer.span("round.host_inputs"):
            if fut is not None:
                entry = fut.result()
                if entry["spe"] != want_spe or (
                    place and self._stream and entry["host"][4] is None
                ):
                    # overlap drain: the prefetched grid was built for a
                    # different ladder rung (unaligned-resume catch-up
                    # dispatches on the round's own rung, not the
                    # steady-state chunk max), or — stream × fuse — it
                    # was built slab-less for a fused consumer but an
                    # unfused catch-up round needs the per-round slab.
                    # Rebuild on the right shape.
                    self._db_stats["prefetch_dropped"] += 1
                    entry = None
                else:
                    self._db_stats["host_prefetched"] += 1
            if entry is not None:
                cohort, idx, mask, n_ex, slab = entry["host"]
            else:
                cohort, idx, mask, n_ex, slab = self._host_inputs(
                    round_idx, shape=shape, build_slab=place,
                )
        if self._population is not None and slab is not None:
            # stream-slab dedup shape, observed at CONSUMPTION (not in
            # _stream_slab, which may also run for prefetch entries the
            # consumer drops): the remapped index tensor's max + 1 IS
            # the unique-row count the gather copied
            sl_idx = slab[0]
            self._population.observe_slab(
                int(sl_idx.size),
                int(sl_idx.max()) + 1 if sl_idx.size else 0,
            )
        self._maybe_prefetch(round_idx)
        if self._digest_on:
            # schedule-component capture (consumed at flush): the
            # realized cohort ids, poisson pads included — the pad
            # pattern is part of the deterministic schedule
            self._digest_cohorts[round_idx] = np.asarray(
                cohort, np.int64
            ).copy()
        n_host = np.asarray(n_ex)  # pairwise secagg reads dropout host-side
        if self._counters_on:
            stats = self._round_comm(cohort, n_host)
            # padded-shape accounting (r7): grid provenance, analytic
            # host→device index-input bytes (the mask slab's removal is
            # visible here), and the padded-step / wasted-FLOP gauges
            rows, steps_g, batch_g = (
                int(idx.shape[0]), int(idx.shape[1]), int(idx.shape[2])
            )
            stats["host_input_bytes"] = round_host_input_bytes(
                rows, steps_g, batch_g, self._spec_inputs
            )
            if self._spec_inputs:
                stats.update(round_shape_stats(
                    mask, steps_g, batch_g, self.shape.local_epochs
                ))
                if self._bucket_ladder is not None:
                    stats["shape_bucket_steps"] = steps_g
            self._comm_stats[round_idx] = stats
            if self._phase_cost_on:
                self._record_phase_cost(
                    round_idx, rows, steps_g, batch_g,
                    stats["host_input_bytes"],
                )
        if not place:
            # fuse>1 requires hbm placement (validate), so slab is None
            return cohort, idx, mask, n_ex, self.train_x, self.train_y, n_host
        with self.tracer.span("round.placement"):
            if entry is not None and entry["placed"] is not None:
                # double-buffered: the worker already placed this
                # round's tensors while the previous dispatch ran —
                # the placement span records only this hand-off
                idx, mask, n_ex, train_x, train_y = entry["placed"]
                self._db_stats["placed_prefetched"] += 1
            else:
                idx, mask, n_ex, train_x, train_y = self._place_round_inputs(
                    idx, mask, n_ex, slab
                )
        return cohort, idx, mask, n_ex, train_x, train_y, n_host

    def _round_comm(self, cohort, n_host) -> Dict[str, int]:
        """Analytic wire bytes for one round (obs/counters.py): the
        realized participant count (dropouts excluded) uploads, the
        real — non-poisson-pad — cohort downloads."""
        coords, p_bytes = self._param_stats()
        _, f_bytes = self._full_param_stats()
        if self.gossip:
            stats = gossip_round_bytes(
                self.fed.num_clients, self.cfg.server.gossip_mixing_steps,
                self.cfg.server.gossip_topology, p_bytes,
            )
            full_up = gossip_round_bytes(
                self.fed.num_clients, self.cfg.server.gossip_mixing_steps,
                self.cfg.server.gossip_topology, f_bytes,
            )["upload_bytes"]
        else:
            n_up = int((n_host > 0).sum())
            n_down = int(
                (np.asarray(cohort) < self.fed.num_clients).sum()
            )
            stats = round_comm_bytes(
                self.cfg.server, n_participants=n_up, n_downloads=n_down,
                n_coords=coords, param_bytes=p_bytes,
            )
            f_coords, _ = self._full_param_stats()
            full_up = round_comm_bytes(
                self.cfg.server, n_participants=n_up, n_downloads=n_down,
                n_coords=f_coords, param_bytes=f_bytes,
            )["upload_bytes"]
        # LoRA wire accounting (ROADMAP item 3's headline number): what
        # the FULL-delta twin would have uploaded this round, and the
        # per-client reduction ratio — 1.0 exactly for non-LoRA runs
        stats["upload_bytes_full"] = full_up
        stats["wire_reduction_vs_full"] = round(
            self.wire_reduction_vs_full(), 2
        )
        return stats

    def _stream_slab(self, idx: np.ndarray):
        """Gather this round's unique example rows into a fixed-shape slab
        (static shape ⇒ one XLA trace for the whole run) and remap the
        index tensor into it. Tail rows past ``len(uniq)`` are left
        uninitialized — every remapped index points below ``len(uniq)``,
        so they are never gathered."""
        with self.tracer.span("round.stream_slab"):
            uniq, inv = np.unique(idx, return_inverse=True)
            if len(uniq) > self._slab_rows:
                raise RuntimeError(
                    f"stream slab overflow: round gathered {len(uniq)} "
                    f"unique example rows but the static slab holds "
                    f"{self._slab_rows} — the construction-time sizing "
                    f"(cohort x cap + 1) should have prevented this"
                )
            slab_x = np.empty((self._slab_rows,) + self.fed.train_x.shape[1:],
                              self.fed.train_x.dtype)
            slab_y = np.empty((self._slab_rows,) + self.fed.train_y.shape[1:],
                              self.fed.train_y.dtype)
            slab_x[: len(uniq)] = self.fed.train_x[uniq]
            slab_y[: len(uniq)] = self.fed.train_y[uniq]
            new_idx = inv.reshape(idx.shape).astype(np.int32)
            return new_idx, slab_x, slab_y

    def _run_async_round(self, state: Dict[str, Any], round_idx: int) -> Dict[str, Any]:
        """One FedBuff server step: pop the K earliest-finishing in-flight
        clients, train each against its stale start version (history
        ring gather inside the program), aggregate with staleness-decayed
        weights, start K replacement clients at the new version.

        The pop-K-earliest discipline with durations ≤ S and concurrency
        K·S bounds realized staleness by 2S (a finished client waits at
        most concurrency/K = S further steps), which sizes the 2S+1-slot
        ring. Without churn the bound is an invariant (violations
        raise); under ``run.churn`` offline clients DEFER completions
        and the bound becomes a BUDGET — the admission gate clamps an
        over-bound update's start version to the oldest retained ring
        slot, decays its weight at the TRUE staleness (strictly
        stronger), counts it (``staleness_clamped``), and warns once.
        ``run.strict_staleness=true`` restores the raise.

        Million-client plane (the churn PR): ``data.placement=stream``
        gathers only the popped buffer's example rows into the
        fixed-shape slab (mmap store composes — the gather IS the
        store read path), ``server.sampling=streaming`` draws arrivals
        through the O(cohort·log) sketch sampler (availability-gated,
        Oort-scored once per-insert ledger stats feed the sketch), and
        ``run.obs.client_ledger`` rides the round program per insert.
        ``server.async_backlog_cap`` sheds completed backlog beyond
        the cap per ``async_overload_policy`` (drop-oldest vs
        reject-newest; shed clients re-enter as fresh arrivals at the
        current version, their in-flight work discarded and counted)."""
        cfg = self.cfg
        s_max = cfg.server.async_max_staleness
        window = 2 * s_max + 1
        k = cfg.server.cohort_size
        # multi-version lines (server.async_versions): round r drives
        # line r mod V at LINE-LOCAL version r div V — each line is an
        # independent FedBuff instance (own params/history/queue) whose
        # queue arithmetic runs in line-local steps. V=1 degenerates to
        # line 0 at version == round_idx, bitwise the single-version
        # plane (sfx == "" selects the legacy state keys).
        V = self._versions
        line = round_idx % V
        version = round_idx // V
        sfx = "" if line == 0 else f"_l{line}"
        q_clients = state["queue_clients" + sfx]
        q_versions = state["queue_versions" + sfx]
        q_finish = state["queue_finish" + sfx]
        q_seq = state["queue_seq" + sfx]
        host_rng = np.random.default_rng((cfg.run.seed, 6073, round_idx))
        # version retirement (server.async_retire_*): at the line's
        # turn, a generation that aged past async_retire_rounds or
        # absorbed async_retire_updates RETIRES — the line's params
        # continue as the successor generation, and in-flight work
        # against the retired generation re-admits below at the oldest
        # live version with decayed weight (strict_versions rejects).
        gen = 0
        q_gen = None
        if V > 1:
            q_gen = state["queue_gen" + sfx]
            gen = int(state["line_gen"][line])
            age = version - int(state["line_birth"][line])
            rr = cfg.server.async_retire_rounds
            ru = cfg.server.async_retire_updates
            if ((rr > 0 and age >= rr) or
                    (ru > 0 and int(state["line_absorbed"][line]) >= ru)):
                gen += 1
                state["line_gen"][line] = gen
                state["line_birth"][line] = version
                state["line_absorbed"][line] = 0
        if (self._snapshot_refresh and round_idx > 0
                and round_idx % self._ledger_cfg.log_every == 0):
            # streaming-sketch refresh from the per-insert ledger, at
            # the same log_every boundaries as the sync loop — arrival
            # draws for rounds [r, r + log_every) are a pure function
            # of (seed, round, sketch@r)
            self._refresh_adaptive_snapshot(round_idx)

        n_bp_drop = n_bp_rej = 0
        with self.tracer.span("round.async_schedule"):
            if self._churn is not None:
                # availability-aware pop: an offline client's
                # completion cannot be absorbed — it WAITS (sorted
                # behind every online entry), so its staleness
                # accumulates while the device is dark, exactly the
                # production regime the admission gate below absorbs.
                # Stateless by construction (the availability bit is
                # the pure churn hash — nothing mutates, so resume
                # replays the same pops). When fewer than K online
                # completions exist, offline entries fill the static-
                # shape pop and realize as churn dropouts (weight 0)
                # in _apply_failures, their slots re-queued fresh.
                offline = (
                    ~self._churn.available(round_idx, q_clients)
                ).astype(np.int32)
                order = np.lexsort((q_seq, q_finish, offline))
            else:
                order = np.lexsort((q_seq, q_finish))
            pick = order[:k]
            cap = cfg.server.async_backlog_cap
            if cap > 0:
                # overload backpressure: completed entries beyond the
                # K this step absorbs form the backlog; anything past
                # the cap is shed per policy — the client re-enters as
                # a fresh arrival at the current version, its
                # in-flight work discarded (counted)
                done = np.flatnonzero(q_finish <= version)
                waiting = np.setdiff1d(done, pick, assume_unique=False)
                excess = len(waiting) - cap
                if excess > 0:
                    if cfg.server.async_overload_policy == "drop_oldest":
                        # shed the stalest waiters (oldest start
                        # version first; ties by arrival order)
                        shed_order = np.lexsort((
                            q_seq[waiting], q_versions[waiting],
                        ))
                        shed = waiting[shed_order[:excess]]
                        n_bp_drop = excess
                    else:  # reject_newest: FIFO admission
                        shed_order = np.lexsort((
                            -q_seq[waiting], -q_versions[waiting],
                        ))
                        shed = waiting[shed_order[:excess]]
                        n_bp_rej = excess
                    q_versions[shed] = version + 1
                    q_finish[shed] = (
                        version + 1 + self._client_durations(
                            q_clients[shed], host_rng
                        )
                    ).astype(np.int32)
                    nxt_shed = state["queue_next_seq" + sfx]
                    q_seq[shed] = np.arange(
                        nxt_shed, nxt_shed + excess, dtype=np.int32
                    )
                    state["queue_next_seq" + sfx] = nxt_shed + excess
                    if q_gen is not None:
                        # shed clients re-enter as fresh arrivals of
                        # the CURRENT generation
                        q_gen[shed] = gen
            cohort = q_clients[pick].copy()
            staleness = version - q_versions[pick]
            late = np.zeros(k, dtype=bool)
            if q_gen is not None:
                late = q_gen[pick] < gen
            n_readmit = int(late.sum())
        if not (staleness >= 0).all():
            # a negative staleness is a scheduler bug, never a churn
            # outcome — must survive python -O
            raise RuntimeError(
                f"fedbuff staleness bound violated: {staleness} outside "
                f"[0, {2 * s_max}] — history ring sizing is wrong"
            )
        over = staleness > 2 * s_max
        n_clamped = int(over.sum())
        if n_clamped and cfg.run.strict_staleness:
            # the pre-churn contract, preserved behind the escape
            # hatch: the ring bound is an invariant
            raise RuntimeError(
                f"fedbuff staleness bound violated: {staleness} outside "
                f"[0, {2 * s_max}] — history ring sizing is wrong"
            )
        # graceful admission: an update whose start version aged out of
        # the ring trains against the OLDEST RETAINED version (slot
        # arithmetic on the clamped version — the true start was
        # overwritten), while its weight decays at the TRUE staleness
        eff_versions = np.maximum(
            q_versions[pick], version - 2 * s_max
        )
        slots = (eff_versions % window).astype(np.int32)
        if n_readmit:
            # late completions against a retired generation: hard
            # reject under run.strict_versions, otherwise re-admit at
            # the oldest live version (the slot clamp above already
            # covers an aged-out start) with decayed weight below
            if cfg.run.strict_versions:
                raise RuntimeError(
                    f"fedbuff line {line}: {n_readmit} completion(s) "
                    f"arrived against a retired generation "
                    f"(queue gen < line gen {gen}) and "
                    f"run.strict_versions=true rejects re-admission"
                )
            if not self._readmit_warned:
                self._readmit_warned = True
                self.logger.log({
                    "event": "warning",
                    "warning": "version_readmitted",
                    "round": int(round_idx),
                    "detail": (
                        f"fedbuff line {line}: completion(s) against a "
                        f"retired generation re-admitted at the oldest "
                        f"live version with weight decayed by "
                        f"async_readmit_decay="
                        f"{cfg.server.async_readmit_decay} per retired "
                        f"generation; counted as version_readmitted "
                        f"(warn-once; set run.strict_versions=true to "
                        f"make this an error)"
                    ),
                })
        if n_clamped and not self._staleness_warned:
            self._staleness_warned = True
            self.logger.log({
                "event": "warning",
                "warning": "staleness_clamped",
                "round": int(round_idx),
                "detail": (
                    f"fedbuff update(s) exceeded the 2S={2 * s_max} "
                    f"staleness bound (max realized "
                    f"{int(staleness.max())}): start version clamped "
                    f"to the oldest retained ring slot, weight decayed "
                    f"at the true staleness; counted as "
                    f"staleness_clamped (warn-once; set "
                    f"run.strict_staleness=true to make this an error)"
                ),
            })
        stale_f = staleness.astype(np.float64)
        self._async_stats[round_idx] = {
            "mean": float(staleness.mean()),
            "max": int(staleness.max()),
            "p50": float(np.percentile(stale_f, 50)),
            "p90": float(np.percentile(stale_f, 90)),
            "clamped": n_clamped,
            "bp_dropped": n_bp_drop,
            "bp_rejected": n_bp_rej,
        }
        if V > 1:
            self._async_stats[round_idx]["version"] = line
            self._async_stats[round_idx]["readmitted"] = n_readmit
        # pooled run-level staleness distribution (run_summary / bench
        # extras): a bounded value→count histogram, never per-update
        for v_, c_ in zip(*np.unique(staleness, return_counts=True)):
            self._staleness_hist[int(v_)] = (
                self._staleness_hist.get(int(v_), 0) + int(c_)
            )
        self._version_readmitted += n_readmit

        with self.tracer.span("round.host_inputs"):
            idx, mask, n_ex = make_round_indices(
                self.fed, cohort, self.shape, host_rng
            )
            mask, n_ex = self._apply_failures(mask, n_ex, k, host_rng,
                                              round_idx=round_idx,
                                              shape=self.shape,
                                              cohort=cohort)
        if self._digest_on:
            # schedule-component capture: the popped completion set IS
            # the async scheduler's realized schedule for this step
            self._digest_cohorts[round_idx] = np.asarray(
                cohort, np.int64
            ).copy()
        if self._counters_on:
            self._comm_stats[round_idx] = self._round_comm(cohort, n_ex)
        base_w = (
            n_ex if self._agg_mode == "examples"
            else (n_ex > 0).astype(np.float32)
        )
        agg_w = (
            base_w * (1.0 + staleness.astype(np.float32))
            ** -cfg.server.async_staleness_exponent
        )
        if n_readmit:
            # re-admission decay: once per retired generation gap, on
            # top of the true-staleness decay above
            agg_w = agg_w * np.where(
                late,
                np.float32(cfg.server.async_readmit_decay)
                ** (gen - q_gen[pick]).astype(np.float32),
                np.float32(1.0),
            ).astype(np.float32)
        absorbed_mask = n_ex > 0
        n_edges_crashed = n_edge_excluded = 0
        if self._hier:
            # async two-tier grouping: each popped completion belongs
            # to the edge covering its contiguous id block. A crashed
            # edge's completions are EXCLUDED (weight 0, counted) — a
            # dead tier degrades the step, never NaN-poisons the core.
            # core_aggregator="reputation" folds the edge-liveness
            # trust EMA into its members' admission weights.
            from colearn_federated_learning_tpu.server.churn import (
                edge_crashed,
            )

            E = cfg.server.hierarchy.num_edges
            edge_ids = (
                np.asarray(cohort, np.int64) * E // self.fed.num_clients
            )
            e_crashed = edge_crashed(
                cfg.run.seed, round_idx, E,
                cfg.server.hierarchy.edge_dropout_rate,
            )
            n_edges_crashed = int(e_crashed.sum())
            excl = e_crashed[edge_ids]
            n_edge_excluded = int((excl & absorbed_mask).sum())
            agg_w = agg_w * (~excl).astype(np.float32)
            absorbed_mask = absorbed_mask & ~excl
            trust = state["edge_trust"]
            if cfg.server.hierarchy.core_aggregator == "reputation":
                agg_w = agg_w * trust[edge_ids].astype(np.float32)
            d = cfg.server.hierarchy.core_trust_decay
            trust *= np.float32(1.0 - d)
            trust += np.float32(d) * (~e_crashed).astype(np.float32)
            np.add.at(self._edge_absorbed, edge_ids[absorbed_mask], 1)
            if n_edges_crashed:
                self._async_stats[round_idx]["edge_crashed"] = (
                    n_edges_crashed
                )
                self._async_stats[round_idx]["edge_excluded"] = (
                    n_edge_excluded
                )
        n_absorbed = int(absorbed_mask.sum())
        self._async_absorbed += n_absorbed
        self._per_version_absorbed[line] += n_absorbed
        if V > 1:
            state["line_absorbed"][line] += n_absorbed
        if self._population is not None:
            self._population.observe_async(
                round_idx, staleness, absorbed=n_absorbed,
                clamped=n_clamped, bp_dropped=n_bp_drop,
                bp_rejected=n_bp_rej, readmitted=n_readmit,
                edge_crashed=n_edges_crashed,
                version=line if V > 1 else None,
            )

        if self._stream:
            # store-backed / larger-than-HBM corpora: gather only this
            # step's example rows into the fixed-shape slab (the mmap
            # store's gather path) and remap the index tensor into it
            idx, slab_x, slab_y = self._stream_slab(idx)
            if self._population is not None:
                self._population.observe_slab(
                    int(idx.size), int(len(np.unique(idx)))
                )
            train_x = self._put_data(jnp.asarray(slab_x))
            train_y = self._put_data(jnp.asarray(slab_y))
        else:
            train_x, train_y = self.train_x, self.train_y

        put_c = lambda a: self._put(jnp.asarray(a), self._client_sharding)  # noqa: E731
        rng = jax.random.fold_in(state["rng_key"], round_idx)
        common = (
            state["history" + sfx], state["server_opt_state" + sfx],
            train_x, train_y,
            put_c(idx), put_c(mask), put_c(agg_w.astype(np.float32)),
            put_c(n_ex), put_c(slots),
        )
        ring = (
            jnp.int32(version % window), jnp.int32((version + 1) % window),
        )
        ledger = None
        with self.tracer.span("round.dispatch"):
            if self._ledger_on:
                # per-insert forensic stats + (optionally) the
                # staleness-aware reputation-weighted merge: cohort ids
                # and the carried ledger ride the program; the updated
                # ledger comes back before the metrics
                cohort_dev = self._put(
                    jnp.asarray(np.asarray(cohort, np.int32)),
                    self._data_sharding,
                )
                history, params, opt_state, ledger, metrics = self.round_fn(
                    *common, cohort_dev, state["ledger"], *ring, rng,
                )
            else:
                history, params, opt_state, metrics = self.round_fn(
                    *common, *ring, rng,
                )

        # replace the popped clients: fresh arrivals starting at the
        # NEW version, finishing 1..S steps from the next step. The
        # draw is uniform (churn-gated to online clients), or the
        # streaming sketch sampler's O(cohort·log) draw — availability-
        # gated and Oort-scored once ledger evidence feeds the sketch.
        if self._streaming:
            # the streaming sampler's draw is availability-gated and
            # (with ledger evidence) Oort-scored; its deterministic
            # backstop guarantees exactly K ids
            arrivals = self.sampler.sample(round_idx).astype(np.int32)
            arrival_draws = self.sampler.take_draw_stats(round_idx)
        else:
            if self._churn is not None:
                all_ids = np.arange(self.fed.num_clients)
                online = all_ids[self._churn.available(round_idx, all_ids)]
                pool = online if len(online) else all_ids
                arrivals = host_rng.choice(
                    pool, size=k, replace=k > len(pool),
                ).astype(np.int32)
            else:
                # churn-off keeps the exact pre-churn draw (int form —
                # the bitwise-identity contract covers the rng stream)
                arrivals = host_rng.choice(
                    self.fed.num_clients, size=k,
                    replace=k > self.fed.num_clients,
                ).astype(np.int32)
            arrival_draws = None
        if self._population is not None:
            # coverage/fairness track the REALIZED server step (pads
            # and zero-weight failures excluded); the draw split — when
            # present — describes this step's ARRIVALS (fedbuff pops
            # its queue; the sampler only feeds it)
            self._population.observe_cohort(
                round_idx, cohort, n_ex, arrival_draws,
            )
        q_clients[pick] = arrivals
        q_versions[pick] = version + 1
        q_finish[pick] = (
            version + 1
            + self._client_durations(q_clients[pick], host_rng)
        ).astype(np.int32)
        nxt = state["queue_next_seq" + sfx]
        q_seq[pick] = np.arange(nxt, nxt + k, dtype=np.int32)
        if q_gen is not None:
            q_gen[pick] = gen

        # pass-through: every other line's state (and any host-side
        # sampler/ledger keys) rides unchanged; only this line's tree,
        # ring, and queue-counter keys are replaced. V=1 produces
        # exactly the legacy key set (the bitwise-identity contract).
        new_state = dict(state)
        new_state.pop("_metrics", None)
        new_state.update({
            "history" + sfx: history,
            "params" + sfx: params,
            "server_opt_state" + sfx: opt_state,
            "round": round_idx + 1,
            "queue_next_seq" + sfx: nxt + k,
            "_metrics": metrics,
        })
        if self._ledger_on:
            new_state["ledger"] = ledger
        return new_state

    def _pairwise_seeds(self, round_idx: int, n_host: np.ndarray):
        """One round of the Bonawitz key protocol, host-side
        (privacy/secagg_keys.py): fresh per-round DH secrets + Shamir
        shares, pairwise seed matrix for the cohort, and — when clients
        dropped (weight 0 at collection) — the server's REAL recovery
        path: reconstruct each dropped secret from exactly t survivor
        shares and recompute its seed row from the publics alone.
        Raises ThresholdError below t survivors (the protocol's defined
        abort; nothing can be aggregated that round)."""
        from colearn_federated_learning_tpu.privacy import secagg_keys as sk

        k = self.cfg.server.cohort_size
        t = self.cfg.server.secagg_threshold or (k // 2 + 1)
        rng = np.random.default_rng((self.cfg.run.seed, round_idx, 0x5ECA))
        keys = sk.setup_cohort(rng, k, t)
        seeds = sk.build_seed_matrix(keys)
        dropped = np.flatnonzero(n_host == 0)
        if dropped.size:
            survivors = np.flatnonzero(n_host > 0)
            rows = sk.recover_dropped_rows(keys, dropped.tolist(),
                                           survivors.tolist())
            for d, row in rows.items():
                # DH symmetry guarantees the recovered row equals the
                # client's own; check it explicitly (cheap, and it IS
                # the protocol correctness property — an explicit raise,
                # not an assert, so the gate survives `python -O`)
                if not np.array_equal(row, seeds[d]):
                    raise RuntimeError(
                        f"pairwise secagg: Shamir-recovered seeds for "
                        f"dropped client {d} diverge from DH agreement "
                        f"— seed recovery is corrupt; aborting the round"
                    )
                seeds[d] = row
        arr = jnp.asarray(seeds)
        if self._data_sharding is not None:
            arr = self._put(arr, self._data_sharding)
        return arr

    def _unfused_round_fn(self):
        """The fuse_rounds=1 engine twin, built lazily (one extra
        compile) the first time a non-chunk-aligned resume needs
        unfused catch-up rounds."""
        if self._unfused_cache is None:
            if self._make_engine is None:
                raise RuntimeError(
                    "no unfused engine twin for this configuration"
                )
            self._unfused_cache = self._make_engine(1)
        return self._unfused_cache

    # ---- device-resident control plane (run.control_plane="device") --

    def _init_device_plane(self) -> None:
        """Build the device control plane (server/device_plane.py): the
        cohort table runs the UNCHANGED host sampler over every round
        (so device cohorts are bitwise-equal to host mode by
        construction), churn thresholds precompute the diurnal curve as
        integer gates, and the shard table makes the index slab a pure
        in-program gather. Draw-provenance tallies are captured here
        per round (the sampler bounds its unconsumed backlog) and
        consumed by the flush drain's population feed."""
        from colearn_federated_learning_tpu.server.device_plane import (
            build_device_plan,
            make_schedule_fn,
            plan_arrays,
        )

        cfg = self.cfg

        def _sample(r):
            out = self.sampler.sample(r)
            self._device_draw_stats[r] = self.sampler.take_draw_stats(r)
            return out

        self._device_plan = build_device_plan(
            self.fed, self.shape, _sample, self._churn,
            cfg.run.seed, cfg.server.num_rounds,
        )
        arrs = plan_arrays(self._device_plan)
        if self._data_sharding is not None:
            self._device_arrays = {
                k: self._put(jnp.asarray(v), self._data_sharding)
                for k, v in arrs.items()
            }
        else:
            self._device_arrays = {
                k: jnp.asarray(v) for k, v in arrs.items()
            }
        self._schedule_fn = make_schedule_fn(self._device_plan)
        self._device_unfused_cache = None
        if self.mesh is not None:
            self._device_round_fn = self._build_device_round_fn(
                cfg.run.fuse_rounds
            )
        else:
            # sequential oracle: the jitted schedule derivation runs on
            # device and its fetched outputs feed the python-loop
            # engine — the oracle pins schedule/params parity, not
            # wall-clock
            self._device_schedule_jit = jax.jit(self._schedule_fn)

    def _build_device_round_fn(self, fuse: int):
        from colearn_federated_learning_tpu.parallel.round_engine import (
            make_device_round_fn,
        )

        return make_device_round_fn(
            self._make_engine(fuse, donate=False), self._schedule_fn,
            fuse, client_ledger=self._ledger_on,
            data_sharding=self._data_sharding,
            cohort_sharding=self._cohort_sharding,
            client_sharding=self._client_sharding,
            fused_cohort_sharding=self._fused_cohort_sharding,
            fused_client_sharding=self._fused_client_sharding,
        )

    def _device_unfused_round_fn(self):
        """The fuse=1 device-wrapper twin, built lazily for unaligned-
        resume catch-up rounds (mirrors _unfused_round_fn)."""
        if self._device_unfused_cache is None:
            self._device_unfused_cache = self._build_device_round_fn(1)
        return self._device_unfused_cache

    def _note_device_sched(self, round_idx: int, fuse: int,
                           sched: Dict[str, Any]) -> None:
        """Keep device handles of the realized schedule (WITHOUT the
        index slab — cohort/spec/weights/churn scalars only) for the
        flush-boundary drain. Under fuse the [F]-stacked outputs are
        held as per-sub-round device slices, like pending metrics."""
        sched = {k: v for k, v in sched.items() if k != "idx"}
        if fuse > 1:
            for j in range(fuse):
                self._device_sched[round_idx + j] = jax.tree.map(
                    lambda a, j=j: a[j], sched
                )
        else:
            self._device_sched[round_idx] = sched

    def _run_device_round(self, state: Dict[str, Any], round_idx: int,
                          fuse: int) -> Dict[str, Any]:
        """One device-control-plane dispatch: the round program derives
        its own cohort, churn gates, and index slab from (seed, round)
        — the host passes a round index. Under fuse>1 the scan body
        derives each sub-round's schedule itself, so host I/O collapses
        to flush boundaries."""
        if self.mesh is None:
            return self._run_device_round_seq(state, round_idx)
        if fuse == self.cfg.run.fuse_rounds:
            round_fn = self._device_round_fn
        else:
            round_fn = self._device_unfused_round_fn()
        args = (state["params"], state["server_opt_state"],
                self.train_x, self.train_y, self._device_arrays,
                jnp.int32(round_idx), state["rng_key"])
        with self.tracer.span("round.dispatch"):
            if self._ledger_on:
                params, opt_state, ledger, metrics, sched = round_fn(
                    *args, state["ledger"]
                )
            else:
                params, opt_state, metrics, sched = round_fn(*args)
        self._note_device_sched(round_idx, fuse, sched)
        new_state = {
            "params": params,
            "server_opt_state": opt_state,
            "round": round_idx + fuse,
            "rng_key": state["rng_key"],
            "_metrics": metrics,
        }
        if self._ledger_on:
            new_state["ledger"] = ledger
        return new_state

    def _run_device_round_seq(self, state: Dict[str, Any],
                              round_idx: int) -> Dict[str, Any]:
        """Sequential-engine device mode: the schedule still derives
        on device (the jitted schedule program — host_inputs is one
        fetch, no sampler/churn/slab python), then feeds the unchanged
        per-client oracle loop."""
        with self.tracer.span("round.host_inputs"):
            sched = jax.device_get(self._device_schedule_jit(
                self._device_arrays, jnp.int32(round_idx)
            ))
        self._note_device_sched(round_idx, 1, sched)
        rng = jax.random.fold_in(state["rng_key"], round_idx)
        kw = {}
        if self._ledger_on:
            kw = dict(
                ledger=state["ledger"],
                ledger_ids=jnp.asarray(
                    np.asarray(sched["cohort"], np.int32)
                ),
            )
        with self.tracer.span("round.dispatch"):
            out = self.round_fn(
                state["params"], state["server_opt_state"],
                self.train_x, self.train_y, sched["idx"], sched["spec"],
                sched["n_ex"], rng, **kw,
            )
        if self._ledger_on:
            params, opt_state, ledger, metrics = out
        else:
            params, opt_state, metrics = out
        new_state = {
            "params": params,
            "server_opt_state": opt_state,
            "round": round_idx + 1,
            "rng_key": state["rng_key"],
            "_metrics": metrics,
        }
        if self._ledger_on:
            new_state["ledger"] = ledger
        return new_state

    def _drain_device_sched(self) -> None:
        """Flush-boundary drain of the device-derived schedules: ONE
        device fetch of every pending round's realized (cohort, spec,
        weights, churn stats), then the same per-round bookkeeping the
        host control plane does inline — digest cohorts, wire counters
        (host_input_bytes=0: no index slab crossed the wire), padded-
        shape gauges, churn fail counters, phase costs, and the
        population observatory's cohort/draw feed. Runs FIRST in
        flush(), so the record loop's pops find everything in place."""
        if not self._device_sched:
            return
        pend = sorted(self._device_sched)
        with self.tracer.span("round.sched_fetch"):
            fetched = jax.device_get(
                [self._device_sched[r] for r in pend]
            )
        self._device_sched.clear()
        for ridx, s in zip(pend, fetched):
            cohort = np.asarray(s["cohort"], np.int64)
            spec = np.asarray(s["spec"])
            n_ex = np.asarray(s["n_ex"])
            if self._digest_on:
                self._digest_cohorts[ridx] = cohort.copy()
            if self._counters_on:
                stats = self._round_comm(cohort, n_ex)
                stats["host_input_bytes"] = 0
                stats.update(round_shape_stats(
                    spec, self.shape.steps, self.shape.batch_size,
                    self.shape.local_epochs,
                ))
                self._comm_stats[ridx] = stats
                if self._phase_cost_on:
                    self._record_phase_cost(
                        ridx, len(cohort), self.shape.steps,
                        self.shape.batch_size, 0,
                    )
                fail = {
                    key: int(s[src]) for key, src in (
                        ("churn_unavailable", "unavailable"),
                        ("churn_dropped", "dropped"),
                        ("churn_crashed", "crashed"),
                    ) if int(s[src])
                }
                if fail:
                    self._fail_stats[ridx] = fail
            if self._population is not None:
                self._population.observe_cohort(
                    ridx, cohort, n_ex,
                    self._device_draw_stats.pop(ridx, None),
                )

    def _run_hier_round(self, state: Dict[str, Any],
                        round_idx: int) -> Dict[str, Any]:
        """One two-tier synchronous round (``server.hierarchy``): E
        edge aggregators each run the EXISTING compiled round program
        over a cohort sampled from their contiguous sub-population
        block (device → edge tier, with ``server.aggregator`` as the
        edge-tier defense, e.g. krum), then the core combines the E
        edge DELTAS per ``hierarchy.core_aggregator`` — example-
        weighted mean, reputation-weighted mean over the edge-liveness
        trust EMA, or a robust reduce (median/trimmed_mean/krum with
        the core knobs). Edge-dropout fault injection
        (``edge_dropout_rate``, seed-pure per (round, edge)) skips the
        crashed edge's dispatch entirely: its delta is EXCLUDED from
        the core combine and counted — a dead tier degrades the round,
        it never NaN-poisons the aggregate (an all-crashed round is an
        exact no-op). The engine is reused recursively: ONE compile
        serves all E invocations, and validate() already restricted
        the pairing surface to what that reuse keeps sound."""
        from colearn_federated_learning_tpu.parallel.round_engine import (
            RoundMetrics,
        )
        from colearn_federated_learning_tpu.server.aggregation import (
            robust_reduce,
        )
        from colearn_federated_learning_tpu.server.churn import edge_crashed

        cfg = self.cfg
        hier = cfg.server.hierarchy
        E = hier.num_edges
        crashed = edge_crashed(
            cfg.run.seed, round_idx, E, hier.edge_dropout_rate
        )
        n_crashed = int(crashed.sum())
        params0 = state["params"]
        base_rng = jax.random.fold_in(state["rng_key"], round_idx)
        zero_delta = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params0
        )
        deltas = []
        participation = np.zeros(E, np.float32)
        edge_examples = np.zeros(E, np.float64)
        edge_metrics = []
        opt_state_new = None
        fail_acc: Dict[str, int] = {}
        byz_total = 0
        all_cohorts, all_nex = [], []
        for e in range(E):
            cohort = np.asarray(self._edge_samplers[e].sample(round_idx))
            with self.tracer.span("round.host_inputs"):
                host_rng = np.random.default_rng(
                    (cfg.run.seed, 7919, round_idx, e)
                )
                if self._spec_inputs:
                    idx, mask, n_ex = make_round_spec(
                        self.fed, cohort, self.shape, host_rng
                    )
                else:
                    idx, mask, n_ex = make_round_indices(
                        self.fed, cohort, self.shape, host_rng
                    )
                mask, n_ex = self._apply_failures(
                    mask, n_ex, len(cohort), host_rng,
                    round_idx=round_idx, shape=self.shape, cohort=cohort,
                )
                # _apply_failures stores per-ROUND counts; merge the
                # per-edge dicts so the round record sums all tiers
                for key_, v_ in self._fail_stats.pop(round_idx, {}).items():
                    fail_acc[key_] = fail_acc.get(key_, 0) + int(v_)
            all_cohorts.append(cohort)
            all_nex.append(np.asarray(n_ex))
            if crashed[e]:
                # edge crashed mid-round: no dispatch, delta excluded
                deltas.append(zero_delta)
                continue
            akw = {}
            if self.attack_kind:
                byz_h = np.isin(cohort, self.compromised)
                byz_total += int(byz_h.sum())
                if self._attack_upload:
                    byz = jnp.asarray(byz_h.astype(np.float32))
                    if self._client_sharding is not None:
                        byz = self._put(byz, self._client_sharding)
                    akw["byz"] = byz
            idx_p, mask_p, n_ex_p, train_x, train_y = (
                self._place_round_inputs(idx, mask, n_ex, None)
            )
            rng_e = jax.random.fold_in(base_rng, e)
            with self.tracer.span("round.dispatch"):
                params_e, opt_e, metrics_e = self.round_fn(
                    params0, state["server_opt_state"], train_x, train_y,
                    idx_p, mask_p, n_ex_p, rng_e, **akw,
                )
            deltas.append(jax.tree.map(
                lambda a, b: (a - b).astype(jnp.float32),
                params_e, params0,
            ))
            participation[e] = 1.0
            edge_examples[e] = float(np.asarray(n_ex).sum())
            edge_metrics.append(metrics_e)
            if opt_state_new is None:
                # optimizer="mean" (validate-enforced): every edge's
                # returned opt state is identical — take the first
                opt_state_new = opt_e
        if fail_acc:
            self._fail_stats[round_idx] = fail_acc
        if self.attack_kind:
            self._attack_stats[round_idx] = byz_total
        n_alive = int(participation.sum())
        self._edge_absorbed += participation.astype(np.int64)
        if n_alive == 0:
            # every edge crashed: the round is an exact no-op (params
            # and opt state carry; the zero-example metrics record it)
            new_params = params0
            opt_state_new = state["server_opt_state"]
            metrics = RoundMetrics(jnp.float32(0.0), jnp.float32(0.0))
        else:
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *deltas)
            if hier.core_aggregator in ("median", "trimmed_mean", "krum"):
                mean_delta = robust_reduce(
                    stacked, jnp.asarray(participation),
                    hier.core_aggregator,
                    trim_ratio=hier.core_trim_ratio,
                    byzantine_f=hier.core_krum_byzantine,
                )
            else:
                w = edge_examples * participation.astype(np.float64)
                if hier.core_aggregator == "reputation":
                    w = w * state["edge_trust"].astype(np.float64)
                ws = w.sum()
                w = (w / (ws if ws > 0 else 1.0)).astype(np.float32)
                wj = jnp.asarray(w)
                mean_delta = jax.tree.map(
                    lambda s: jnp.tensordot(wj, s, axes=(0, 0)), stacked
                )
            new_params = jax.tree.map(
                lambda p, d: (p + d.astype(p.dtype)).astype(p.dtype),
                params0, mean_delta,
            )
            losses = jnp.stack([m.train_loss for m in edge_metrics])
            exs = jnp.stack(
                [jnp.asarray(m.examples, jnp.float32) for m in edge_metrics]
            )
            tot = exs.sum()
            metrics = RoundMetrics(
                (losses * exs).sum() / jnp.maximum(tot, 1.0), tot
            )
        # edge-liveness trust EMA (consumed by core "reputation",
        # always maintained as the tier-health signal)
        trust = state["edge_trust"]
        d = hier.core_trust_decay
        trust *= np.float32(1.0 - d)
        trust += np.float32(d) * (~crashed).astype(np.float32)
        union_cohort = np.concatenate(all_cohorts)
        union_nex = np.concatenate(all_nex)
        if self._digest_on:
            # schedule-component capture: the per-edge cohorts' union,
            # in edge order — the two-tier round's realized schedule
            self._digest_cohorts[round_idx] = np.asarray(
                union_cohort, np.int64
            ).copy()
        if self._counters_on:
            stats = self._round_comm(union_cohort, union_nex)
            # per-tier wire accounting: the edge→core tier moves one
            # full delta per LIVE edge on top of the device→edge tier
            # the cohort numbers above describe
            _, p_bytes = self._param_stats()
            stats["hier_core_upload_bytes"] = n_alive * p_bytes
            self._comm_stats[round_idx] = stats
        if n_crashed:
            self._hier_stats[round_idx] = {"edge_crashed": n_crashed}
        if self._population is not None:
            self._population.observe_cohort(
                round_idx, union_cohort, union_nex, None,
            )
        return {
            "params": new_params,
            "server_opt_state": opt_state_new,
            "round": round_idx + 1,
            "rng_key": state["rng_key"],
            "edge_trust": trust,
            "_metrics": metrics,
        }

    def run_round(self, state: Dict[str, Any], round_idx: int,
                  fuse_override: Optional[int] = None) -> Dict[str, Any]:
        """``fuse_override=1`` forces a single unfused round through the
        lazily-built fuse=1 engine twin — the catch-up path for resumes
        that land off a chunk boundary (see _fit_body)."""
        if self.fedbuff:
            return self._run_async_round(state, round_idx)
        if self._hier:
            return self._run_hier_round(state, round_idx)
        if self._cp_device:
            # device control plane: the program derives its own
            # schedule — none of the host input machinery below runs
            return self._run_device_round(
                state, round_idx,
                self.cfg.run.fuse_rounds if fuse_override is None
                else fuse_override,
            )
        if (self._snapshot_refresh and round_idx > 0
                and round_idx % self._ledger_cfg.log_every == 0):
            # snapshot/sketch refresh BEFORE this round samples: the
            # cohort for rounds [r, r + log_every) is a pure function of
            # (seed, round, ledger@r) — round 0 keeps the all-unseen
            # uniform prior (the zero snapshot/sketch init_state seeds)
            self._refresh_adaptive_snapshot(round_idx)
        fuse = (
            self.cfg.run.fuse_rounds if fuse_override is None
            else fuse_override
        )
        if fuse > 1:
            return self._run_fused_chunk(state, round_idx, fuse)
        round_fn = self.round_fn
        if self.cfg.run.fuse_rounds > 1:
            round_fn = self._unfused_round_fn()
        (cohort, idx, mask, n_ex, train_x, train_y,
         n_host) = self._round_inputs(round_idx)
        if self._population is not None:
            self._population.observe_cohort(
                round_idx, cohort, n_host,
                self.sampler.take_draw_stats(round_idx),
            )
        rng = jax.random.fold_in(state["rng_key"], round_idx)
        # Byzantine mask for this round's cohort: which sampled slots
        # the adversary owns. An ARRAY input alongside n_ex (no
        # retrace); poisson pad slots (id == num_clients) can never be
        # compromised. byzantine_count is recorded for every attack
        # kind (label_flip included — its slots attack through data).
        akw = {}
        if self.attack_kind:
            byz_h = np.isin(np.asarray(cohort), self.compromised)
            self._attack_stats[round_idx] = int(byz_h.sum())
            if self._attack_upload:
                byz = jnp.asarray(byz_h.astype(np.float32))
                if self._client_sharding is not None:
                    byz = self._put(byz, self._client_sharding)
                akw["byz"] = byz
        if self.gossip:
            extra = ()
            if self._gossip_partial:
                extra = (self._put(
                    jnp.asarray(np.asarray(cohort, np.int32)),
                    self._data_sharding,
                ),)
            with self.tracer.span("round.dispatch"):
                replicas, mean_params, metrics = round_fn(
                    state["replicas"], train_x, train_y, idx, mask, n_ex,
                    rng, *extra, **akw,
                )
            return {
                "params": mean_params,
                "server_opt_state": state["server_opt_state"],
                "round": round_idx + 1,
                "rng_key": state["rng_key"],
                "replicas": replicas,
                "_metrics": metrics,
            }
        if self.store_state:
            # scaffold/feddyn carry c_global on top of the per-client
            # store; error feedback is store-only. One branch covers
            # both — the round fn's extra leading state arg (c_global)
            # and return slot exist exactly when self.stateful.
            common = (state["params"], state["server_opt_state"],
                      train_x, train_y, idx, mask, n_ex, rng)
            glob = (state["c_global"],) if self.stateful else ()
            ledger = None
            if self._data_sharding is not None:
                # device-resident store: the cohort gather/scatter runs
                # INSIDE the round program (donated, so the store is
                # updated in place) — no host sync, multi-host capable
                cohort_dev = self._put(
                    jnp.asarray(np.asarray(cohort, np.int32)),
                    self._data_sharding,
                )
                ltail = (state["ledger"],) if self._ledger_on else ()
                with self._bucket_compile_span(round_idx, int(idx.shape[1])), \
                        self.tracer.span("round.dispatch"):
                    out = round_fn(
                        *common, *glob, state["c_clients"], cohort_dev,
                        *ltail,
                    )
                if self._ledger_on:
                    *head, c_clients, ledger, metrics = out
                else:
                    *head, c_clients, metrics = out
            else:
                # sequential oracle: host-resident numpy store with an
                # explicit per-round gather/scatter. Poisson pad slots
                # carry id == num_clients (OOB by construction): gather
                # reads row 0 in their place (harmless — pad rows are
                # fully masked) and the scatter SKIPS them, mirroring
                # the sharded engine's take-fill/scatter-drop semantics.
                rows = np.asarray(cohort)
                real = rows < self.fed.num_clients
                safe = np.where(real, rows, 0)
                c_cohort = jax.tree.map(
                    lambda a: jnp.asarray(a[safe]), state["c_clients"]
                )
                lkw = {}
                if self._ledger_on:
                    lkw = dict(
                        ledger=state["ledger"],
                        ledger_ids=jnp.asarray(
                            np.asarray(cohort, np.int32)
                        ),
                    )
                with self._bucket_compile_span(round_idx, int(idx.shape[1])), \
                        self.tracer.span("round.dispatch"):
                    out = round_fn(
                        *common, *(glob or (None,)), c_cohort, **lkw,
                    )
                if self._ledger_on:
                    *head, new_c_cohort, ledger, metrics = out
                else:
                    *head, new_c_cohort, metrics = out
                fetched = jax.device_get(new_c_cohort)
                jax.tree.map(
                    lambda store, f: store.__setitem__(
                        rows[real], f[real]
                    ),
                    state["c_clients"], fetched,
                )
                c_clients = state["c_clients"]
            new_state = {
                "params": head[0],
                "server_opt_state": head[1],
                "round": round_idx + 1,
                "rng_key": state["rng_key"],
                "c_clients": c_clients,
                "_metrics": metrics,
            }
            if self._ledger_on:
                new_state["ledger"] = ledger
            if self.stateful:
                new_state["c_global"] = head[2]
            return new_state
        kw = dict(akw)
        if self.secagg and self.cfg.server.secagg_mode == "pairwise":
            with self.tracer.span("round.secagg_keys"):
                kw["pair_seeds"] = self._pairwise_seeds(round_idx, n_host)
        if self._ledger_on:
            with self.tracer.span("round.host_inputs.slot_assign"):
                cohort_ids = jnp.asarray(
                    self._ledger_slot_ids(cohort, round_idx, state)
                )
            if self._data_sharding is not None:
                # sharded: positional trailing (byz, ledger, cohort) so
                # the ledger input stays donatable
                with self._bucket_compile_span(round_idx, int(idx.shape[1])), \
                        self.tracer.span("round.dispatch"):
                    params, opt_state, ledger, metrics = round_fn(
                        state["params"], state["server_opt_state"],
                        train_x, train_y, idx, mask, n_ex, rng,
                        kw.get("byz"), state["ledger"],
                        self._put(cohort_ids, self._data_sharding),
                    )
            else:
                with self._bucket_compile_span(round_idx, int(idx.shape[1])), \
                        self.tracer.span("round.dispatch"):
                    params, opt_state, ledger, metrics = round_fn(
                        state["params"], state["server_opt_state"],
                        train_x, train_y, idx, mask, n_ex, rng,
                        ledger=state["ledger"], ledger_ids=cohort_ids,
                        **kw,
                    )
            return {
                "params": params,
                "server_opt_state": opt_state,
                "round": round_idx + 1,
                "rng_key": state["rng_key"],
                "ledger": ledger,
                "_metrics": metrics,
            }
        with self._bucket_compile_span(round_idx, int(idx.shape[1])), \
                self.tracer.span("round.dispatch"):
            params, opt_state, metrics = round_fn(
                state["params"], state["server_opt_state"],
                train_x, train_y, idx, mask, n_ex, rng, **kw,
            )
        return {
            "params": params,
            "server_opt_state": opt_state,
            "round": round_idx + 1,
            "rng_key": state["rng_key"],
            "_metrics": metrics,
        }

    def _run_fused_chunk(self, state: Dict[str, Any], round_idx: int,
                         fuse: int) -> Dict[str, Any]:
        """Dispatch one fused chunk: `fuse` rounds as ONE XLA program.

        The chunk's host inputs are built per sub-round (exactly the
        unfused loop's tensors, prefetch included), stacked host-side
        into [F, ...] slabs, and placed ONCE through the fused
        shardings — the multi-process-capable path (each host uploads
        only its addressable shards). Per-round rngs are the unfused
        loop's exact derivations, so fused ≡ unfused bitwise. Upload
        attacks ride a stacked [F, K] byzantine-mask input; error
        feedback's store enters as the donated scan carry and comes
        back updated in place."""
        # shape buckets compose with fusion at CHUNK granularity: the
        # stacked [F, K, steps, batch] slab must be rectangular, so the
        # chunk dispatches on the max of its sub-rounds' ladder rungs
        # (monotone ladder pick ⇒ identical to picking for the chunk-max
        # requirement). Padded steps are no-ops, so a sub-round riding a
        # larger-than-its-own rung is still bitwise the same round.
        chunk_shape = None
        if self._bucket_ladder is not None:
            chunk_shape = self._bucket_shape(max(
                self._round_bucket_spe(round_idx + j) for j in range(fuse)
            ))
        idxs, masks, n_exs, rngs, cohorts, byz_rows = [], [], [], [], [], []
        train_x = train_y = None
        for j in range(fuse):
            (c_j, i_j, m_j, n_j, train_x, train_y,
             _) = self._round_inputs(round_idx + j, place=False,
                                     shape=chunk_shape)
            if self._population is not None:
                self._population.observe_cohort(
                    round_idx + j, c_j, n_j,
                    self.sampler.take_draw_stats(round_idx + j),
                )
            idxs.append(i_j)
            masks.append(m_j)
            n_exs.append(n_j)
            cohorts.append(np.asarray(c_j, np.int32))
            rngs.append(jax.random.fold_in(state["rng_key"], round_idx + j))
            if self.attack_kind:
                # byzantine_count per fused sub-round, for every attack
                # kind (label_flip attacks through data and composes
                # with fusion with no engine involvement)
                byz_h = np.isin(np.asarray(c_j), self.compromised)
                self._attack_stats[round_idx + j] = int(byz_h.sum())
                if self._attack_upload:
                    byz_rows.append(byz_h.astype(np.float32))
        with self.tracer.span("round.placement"):
            idx_stack = np.stack(idxs)
            if self._stream:
                # stream × fuse: ONE union slab over the chunk's cohorts
                # (static [rows, ...] shape — one trace for the run),
                # stacked indices remapped into it. The engine still
                # sees a single corpus input; only the chunk's unique
                # example records are gathered/uploaded.
                with self.tracer.span("round.stream_slab"):
                    uniq, inv = np.unique(idx_stack, return_inverse=True)
                    rows = self._fused_slab_rows
                    if len(uniq) > rows:
                        raise RuntimeError(
                            f"fused union-slab overflow: chunk gathered "
                            f"{len(uniq)} unique example rows but the "
                            f"static slab holds {rows} — the "
                            f"construction-time sizing (fuse x cohort x "
                            f"cap + 1) should have prevented this"
                        )
                    if self._population is not None:
                        # union-slab dedup under fuse: the whole chunk's
                        # grid slots vs the one slab actually gathered
                        self._population.observe_slab(
                            int(idx_stack.size), int(len(uniq))
                        )
                    # overlapped chunk gather: the PREVIOUS chunk queued
                    # this chunk's union-slab build before its dispatch,
                    # so the mmap I/O ran under device compute. Adopt it
                    # only if the row set matches bitwise what we just
                    # stacked (a cheap np.array_equal vs the expensive
                    # gather) — any mismatch (rung drift, resume seam)
                    # drains to the synchronous build below.
                    for stale in [k for k in self._chunk_prefetch
                                  if k < round_idx]:
                        self._chunk_prefetch.pop(stale).cancel()
                    pre = self._chunk_prefetch.pop(round_idx, None)
                    entry = pre.result() if pre is not None else None
                    if (entry is not None
                            and entry["spe"] == self._prefetch_spe(round_idx)
                            and entry["fuse"] == fuse
                            and np.array_equal(entry["uniq"], uniq)):
                        slab_x = entry["slab_x"]
                        slab_y = entry["slab_y"]
                        self._db_stats["slab_prefetched"] += 1
                    else:
                        if pre is not None:
                            self._db_stats["prefetch_dropped"] += 1
                        slab_x = np.empty(
                            (rows,) + self.fed.train_x.shape[1:],
                            self.fed.train_x.dtype,
                        )
                        slab_y = np.empty(
                            (rows,) + self.fed.train_y.shape[1:],
                            self.fed.train_y.dtype,
                        )
                        slab_x[: len(uniq)] = self.fed.train_x[uniq]
                        slab_y[: len(uniq)] = self.fed.train_y[uniq]
                    idx_stack = inv.reshape(idx_stack.shape).astype(np.int32)
                train_x = self._put_data(jnp.asarray(slab_x))
                train_y = self._put_data(jnp.asarray(slab_y))
            idx_f = self._put(idx_stack, self._fused_cohort_sharding)
            # mask SPECS [F, K, 2] have no batch dim: fuse replicated,
            # cohort over lanes — the per-client fused sharding
            mask_f = self._put(
                np.stack(masks),
                self._fused_client_sharding if self._spec_inputs
                else self._fused_cohort_sharding,
            )
            n_ex_f = self._put(np.stack(n_exs), self._fused_client_sharding)
            # rng keys are tiny device scalars derived identically on
            # every process; stack on host (normalizing typed PRNG keys
            # — a restored checkpoint's rng_key comes back typed — to
            # their raw uint32 data, which fold_in/split accept with
            # identical bits), replicate like other per-round inputs
            def _key_data(k):
                if jax.dtypes.issubdtype(k.dtype, jax.dtypes.prng_key):
                    k = jax.random.key_data(k)
                return np.asarray(k)

            rngs_f = self._put(
                np.stack([_key_data(r) for r in rngs]), self._data_sharding
            )
            tail = ()
            if byz_rows:
                tail = (self._put(
                    np.stack(byz_rows), self._fused_client_sharding
                ),)
            if self.ef or self._ledger_on:
                with self.tracer.span("round.host_inputs.slot_assign"):
                    if self._pager is not None:
                        # paged ledger: assign hot slots for the
                        # CHUNK'S cohort union up front (one assignment
                        # protects every sub-round's residents from
                        # mid-chunk eviction), seed paged-in slots,
                        # then ship slot ids; the engine's
                        # gather/scatter is unchanged
                        union = np.unique(np.concatenate(cohorts))
                        self._ledger_slot_ids(union, round_idx, state)
                        cohort_rows = np.stack(
                            [self._pager.lookup(c) for c in cohorts]
                        )
                    else:
                        cohort_rows = np.stack(cohorts)
                cohorts_f = self._put(cohort_rows, self._data_sharding)
        common = (state["params"], state["server_opt_state"], train_x,
                  train_y, idx_f, mask_f, n_ex_f, rngs_f)
        # queue the NEXT chunk's union-slab store gather before this
        # chunk's dispatch — the I/O overlaps device compute (tentpole
        # of the store data plane: slab_build collapses under dispatch)
        self._submit_chunk_slab_prefetch(round_idx, fuse)
        ledger = None
        with self._bucket_compile_span(round_idx, int(idx_f.shape[2])), \
                self.tracer.span("round.dispatch", fuse=fuse):
            if self.ef:
                if self._ledger_on:
                    (params, opt_state, c_clients, ledger,
                     metrics) = self.round_fn(
                        *common, state["c_clients"], cohorts_f,
                        state["ledger"],
                    )
                else:
                    params, opt_state, c_clients, metrics = self.round_fn(
                        *common, state["c_clients"], cohorts_f,
                    )
            elif self._ledger_on:
                # the ledger rides the fused scan carry; per-sub-round
                # cohort ids are a stacked [fuse, K] scan input
                params, opt_state, ledger, metrics = self.round_fn(
                    *common, tail[0] if tail else None, state["ledger"],
                    cohorts_f,
                )
            else:
                params, opt_state, metrics = self.round_fn(*common, *tail)
        new_state = {
            "params": params,
            "server_opt_state": opt_state,
            "round": round_idx + fuse,
            "rng_key": state["rng_key"],
            "_metrics": metrics,
        }
        if self._ledger_on:
            new_state["ledger"] = ledger
        if self.ef:
            new_state["c_clients"] = c_clients
        return new_state

    # ------------------------------------------------------------------

    def _run_dir(self) -> str:
        """Base directory for this run's artifacts; out_dir="" → cwd."""
        return os.path.join(self.cfg.run.out_dir or ".", self.cfg.name)

    def _stop_prefetch(self) -> None:
        """Shut down the host prefetch worker (no-op when none ran).

        Outstanding futures are CANCELLED before their keys are
        dropped: with a second in-flight placed buffer, clearing the
        dict alone would orphan a still-running future whose
        device_put lands AFTER an abort/KeyboardInterrupt — masking
        the ledger's final flush and racing the shutdown. A future
        already executing cannot be cancelled; ``shutdown(wait=True)``
        then blocks until it drains, so nothing runs past this call."""
        ex, self._host_executor = self._host_executor, None
        for fut in self._prefetch.values():
            fut.cancel()
        self._prefetch.clear()
        for fut in self._chunk_prefetch.values():
            fut.cancel()
        self._chunk_prefetch.clear()
        if ex is not None:
            ex.shutdown(wait=True, cancel_futures=True)

    def _ckpt_store(self) -> Optional[CheckpointStore]:
        if not self.cfg.run.out_dir:
            return None
        return CheckpointStore(os.path.join(self._run_dir(), "ckpt"))

    # EF residuals and scaffold/feddyn control variates share the
    # checkpoint key "c_clients" (same [N_pad, ...] shapes); a resume
    # across those settings would silently reinterpret one as the
    # other (ADVICE r4 #3). A sidecar records the store's SEMANTICS —
    # not the raw algorithm string: stateless pairs (fedavg ↔ fedprox)
    # have no c_clients rows and may resume each other freely, while
    # structurally-different states (gossip replicas, fedbuff queue)
    # already fail orbax's template restore on their own.
    def _state_kind(self) -> Dict[str, Any]:
        if self.scaffold:
            kind = "scaffold"
        elif self.feddyn:
            kind = "feddyn"
        elif self.ef:
            kind = "ef"
        else:
            kind = "none"
        return {"client_state": kind}

    def _state_kind_path(self) -> str:
        return os.path.join(self._run_dir(), "ckpt", "STATE_KIND.json")

    def _write_state_kind(self) -> None:
        if not self._primary or not self.cfg.run.out_dir:
            return
        path = self._state_kind_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # atomic: a crash mid-write must not leave a truncated sidecar
        # that would later read as corrupt
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._state_kind(), f)
        os.replace(tmp, path)

    def _check_state_kind(self) -> None:
        """Reject a run whose existing checkpoint store was written under
        different state semantics. Absent sidecar (pre-r5 run dirs) is
        accepted for backward compatibility; a corrupt sidecar is an
        error (silently skipping the check would defeat it)."""
        try:
            with open(self._state_kind_path()) as f:
                saved = json.load(f)
        except FileNotFoundError:
            return
        except json.JSONDecodeError as e:
            raise ValueError(
                f"corrupt state-kind sidecar {self._state_kind_path()}: {e}; "
                f"delete it (accepting the pre-r5 no-provenance behavior) "
                f"or use a fresh run.out_dir"
            ) from e
        want = self._state_kind()
        if saved != want:
            raise ValueError(
                f"checkpoint store at {self._state_kind_path()} was written "
                f"with state semantics {saved}, but this run is configured "
                f"as {want}; 'c_clients' rows would be silently "
                f"reinterpreted — use a fresh run.out_dir or match the "
                f"original algorithm/error_feedback settings"
            )

    def _ledger_slot_ids(self, cohort, round_idx: int,
                         state: Dict[str, Any]) -> np.ndarray:
        """Ledger row ids for a cohort: the client ids verbatim on the
        dense store; hot-set SLOT ids under paging (obs/ledger.py
        LedgerPager). Paging cold members in seeds their slots from the
        cold mmap via one tiny async device scatter — ``state["ledger"]``
        is rebound to the seeded array, so the subsequent round dispatch
        reads client rows identical to the dense run's (the paging-is-
        invisible contract). Pads (id == num_clients) and anything not
        resident map out of bounds and drop, exactly like dense pads."""
        ids = np.asarray(cohort, np.int64)
        if self._pager is None:
            return ids.astype(np.int32)
        slots, new_slots, seed_rows = self._pager.assign(
            ids, round_idx,
            fetch_hot=lambda: np.asarray(jax.device_get(state["ledger"])),
        )
        if len(new_slots):
            upd = self._put(jnp.asarray(seed_rows), self._data_sharding)
            at = self._put(jnp.asarray(new_slots), self._data_sharding)
            state["ledger"] = state["ledger"].at[at].set(upd)
            self._ledger_ref = state["ledger"]
        return slots

    def _log_ledger(self, round_idx: int) -> Optional[np.ndarray]:
        """Emit one columnar `client_ledger` JSONL record from the
        device-resident ledger (rows with at least one participation).
        Called at periodic flush boundaries, at the adaptive sampler's
        snapshot refreshes (which consume the returned array — the
        JSONL flush IS the sampler's feed), and — via fit()'s finally —
        on EVERY exit path, so aborted runs (HealthAbortError,
        KeyboardInterrupt, crashes) still land their partial ledger,
        mirroring the trace-on-abort guarantee. Returns the fetched
        ``[num_clients, LEDGER_WIDTH]`` array (None when no ledger)."""
        if self._ledger_ref is None:
            return None
        from colearn_federated_learning_tpu.obs.ledger import LEDGER_COLS

        ids, rows = self._fetch_ledger_rows()
        rec: Dict[str, Any] = {
            "event": "client_ledger",
            "round": int(round_idx),
            "num_clients": int(self.fed.num_clients),
            "ema": self._ledger_cfg.ema,
            "zmax": self._ledger_cfg.zmax,
            "ids": [int(i) for i in ids],
            "count": [int(v) for v in rows[:, 0]],
            "flagged": [int(v) for v in rows[:, 1]],
        }
        for j, col in enumerate(LEDGER_COLS[2:], start=2):
            rec[col] = [round(float(v), 6) for v in rows[:, j]]
        self.logger.log(rec)
        self._ledger_logged_round = int(round_idx)
        return ids, rows

    def _fetch_ledger_rows(self):
        """ONE blocking device fetch of the ledger, reduced to the
        columnar active view ``(client ids, [A, LEDGER_WIDTH] rows)`` —
        ids ascending, one row per client with ≥1 participation. Dense:
        a flatnonzero over the fetched store. Paged: the hot set is
        written back into the cold mmap and the merged view scanned —
        client ids throughout, never slots, so records/reports/snapshots
        are layout-independent (paged ≡ dense, test-pinned)."""
        hot = np.asarray(jax.device_get(self._ledger_ref))
        if self._pager is not None:
            return self._pager.active_rows(hot)
        active = np.flatnonzero(hot[:, 0] > 0)
        return active, hot[active]

    def _refresh_adaptive_snapshot(self, round_idx: int) -> None:
        """Refresh the sampler's ledger view at a ``log_every`` round
        boundary: ONE blocking device fetch of the ledger (the same
        fetch emits the periodic ``client_ledger`` JSONL record — the
        flush is the sampler's feed). The refresh rounds are pure round
        arithmetic (multiples of log_every — chunk boundaries under
        fuse_rounds, enforced by validate()), so a resumed run
        refreshes at exactly the rounds the straight run did; between
        refreshes the checkpointed snapshot/sketch covers it.

        Only the three scored columns flow to the sampler
        (sampler.SNAPSHOT_COLS — count, flagged, ema_loss):
        ``adaptive`` scatters them into its dense [num_clients, 3]
        snapshot; ``streaming`` keeps the fixed-size columnar sketch
        (top participation, ties by id) and never builds anything
        O(num_clients)."""
        if self._ledger_ref is None:
            return
        if self._ledger_logged_round == round_idx:
            # a flush boundary already logged (and fetched) this exact
            # round — fetch without emitting a duplicate JSONL record
            ids, rows = self._fetch_ledger_rows()
        else:
            ids, rows = self._log_ledger(round_idx)
        # LEDGER_COLS → SNAPSHOT_COLS: count, flagged, ema_loss
        cols = rows[:, [0, 1, 5]].astype(np.float32)
        self._sampler_snapshot_round = int(round_idx)
        if self._adaptive:
            dense = np.zeros((self.fed.num_clients, 3), np.float32)
            dense[ids] = cols
            self._sampler_snapshot = dense
            self.sampler.observe_snapshot(dense, round_idx)
            return
        m = len(self._sketch_ids)
        total_flagged = float(cols[:, 1].sum())
        if len(ids) > m:
            keep = np.sort(np.lexsort((ids, -cols[:, 0]))[:m])
            ids, cols = ids[keep], cols[keep]
        if self._population is not None:
            # sketch-vs-universe flag coverage: how much of the
            # ledger's flagged (attacker-evidence) mass the retained
            # sketch rows carry — the number that says whether the
            # streaming sampler can SEE the attacker population
            self._population.observe_sketch_refresh(
                total_flagged, float(cols[:, 1].sum())
            )
        self._sketch_ids = np.full(m, -1, np.int32)
        self._sketch_ids[: len(ids)] = ids
        self._sketch_stats = np.zeros((m, 3), np.float32)
        self._sketch_stats[: len(ids)] = cols
        self.sampler.observe_snapshot(
            {
                "ids": ids,
                "count": cols[:, 0],
                "flagged": cols[:, 1],
                "ema_loss": cols[:, 2],
            } if len(ids) else None,
            round_idx,
        )

    def _log_population(self, last_round: int) -> None:
        """Fold the population tracker's window into one
        ``population_health`` JSONL record (no-op when tracking is off
        or the window saw no rounds — tail flushes stay silent)."""
        if self._population is None:
            return
        store_arrays = [
            a for a in (self.fed.train_x, self.fed.train_y)
            if hasattr(a, "gather_stats")
        ]
        sketch_ids = refresh_age = None
        if self._streaming and self._snapshot_refresh:
            sketch_ids = self._sketch_ids
            refresh_age = max(
                0, int(last_round) - int(self._sampler_snapshot_round)
            )
        rec = self._population.window_record(
            last_round, pager=self._pager, store_arrays=store_arrays,
            sketch_ids=sketch_ids, refresh_age=refresh_age,
        )
        if rec is not None:
            self.logger.log(rec)

    def _seed_sampler_from_state(self, state: Dict[str, Any]) -> None:
        """Feed the sampler the checkpoint's ACTIVE snapshot (adaptive)
        or score sketch (streaming) so a resumed run scores mid-window
        rounds exactly like the straight run did (zeros / empty sketch
        on a fresh run → the uniform all-unseen prior)."""
        self._sampler_snapshot_round = int(state["ledger_snapshot_round"])
        if self._adaptive:
            self._sampler_snapshot = state["ledger_snapshot"]
            self.sampler.observe_snapshot(
                self._sampler_snapshot, self._sampler_snapshot_round
            )
            return
        self._sketch_ids = np.asarray(state["ledger_sketch_ids"], np.int32)
        self._sketch_stats = np.asarray(
            state["ledger_sketch_stats"], np.float32
        )
        live = self._sketch_ids >= 0
        self.sampler.observe_snapshot(
            {
                "ids": self._sketch_ids[live],
                "count": self._sketch_stats[live, 0],
                "flagged": self._sketch_stats[live, 1],
                "ema_loss": self._sketch_stats[live, 2],
            } if live.any() else None,
            self._sampler_snapshot_round,
        )

    def _carry_host_ledger_state(self, state: Dict[str, Any]) -> None:
        """run_round returns a fresh state dict holding only the round
        program's outputs — re-attach the host-side sampler snapshot /
        sketch and the pager's cold-spill bookkeeping so they ride
        every checkpoint."""
        if self._snapshot_refresh:
            state["ledger_snapshot_round"] = self._sampler_snapshot_round
            if self._adaptive:
                state["ledger_snapshot"] = self._sampler_snapshot
            else:
                state["ledger_sketch_ids"] = self._sketch_ids
                state["ledger_sketch_stats"] = self._sketch_stats
        if self._pager is not None:
            state["ledger_cold"] = self._pager.cold
            state["ledger_slots"] = self._pager.slot_clients
            state["ledger_slot_used"] = self._pager.slot_used

    def _staleness_percentiles(self) -> tuple:
        """(p50, p90, max) over the pooled per-update staleness
        histogram accumulated across every async round this fit —
        exact weighted percentiles (the histogram is value → count, so
        no sample is ever dropped), (0.0, 0.0, 0) before any absorb."""
        if not self._staleness_hist:
            return (0.0, 0.0, 0)
        vals = np.array(sorted(self._staleness_hist), np.int64)
        cnts = np.array(
            [self._staleness_hist[int(v)] for v in vals], np.int64
        )
        cum = np.cumsum(cnts)
        total = int(cum[-1])
        p50 = float(vals[np.searchsorted(cum, 0.5 * total)])
        p90 = float(vals[np.searchsorted(cum, 0.9 * total)])
        return (p50, p90, int(vals[-1]))

    def fit(self, state: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        caller_state = state is not None
        # per-fit accumulators for the end-of-fit `run_summary` record
        # (cumulative wire bytes, rounds, wall time, compile count) and
        # the ledger's final flush
        self._fit_t0 = time.perf_counter()
        self._rounds_done = 0
        self._run_totals = {
            k: 0 for k in ("upload_bytes", "upload_bytes_raw",
                           "download_bytes", "download_bytes_raw",
                           "upload_bytes_full")
            + (("hier_core_upload_bytes",) if self._hier else ())
        }
        self._total_compiles = 0
        self._total_compile_ms = 0.0
        self._ledger_logged_round = -1
        self._traffic_totals = {}
        self._async_absorbed = 0
        self._staleness_warned = False
        self._staleness_hist = {}
        self._per_version_absorbed[:] = 0
        self._version_readmitted = 0
        self._readmit_warned = False
        self._edge_absorbed[:] = 0
        self._db_stats = {k: 0 for k in self._db_stats}
        # Checkpoint provenance baseline: only checkpoints written BY THIS
        # fit() call may be restored on retry — restoring a stale
        # checkpoint left in the same out_dir by an earlier run would
        # silently return the old run's params as "recovered".
        baseline_step = None
        if self.cfg.run.max_retries > 0:
            store = self._ckpt_store()
            if store is not None:
                baseline_step = store.latest_step()
                store.close()
        retries = 0
        if self._exec_reg is not None:
            # fit-scoped: sequential fits on other Experiment instances
            # must not route through this registry's cache
            exec_mod.install(self._exec_reg)
        try:
            while True:
                try:
                    return self._fit(state)
                except KeyboardInterrupt:
                    raise
                except HealthAbortError:
                    # the monitor's configured abort is a VERDICT, not a
                    # transient failure — a NaN/diverged run restored
                    # from its own checkpoint re-diverges; retrying
                    # would spend the retry budget hiding the signal
                    raise
                except digest_mod.DigestResumeError:
                    # strict digest verification failed: the retry path
                    # skips verification (its own log tail is expected
                    # to disagree), so retrying would silently bypass
                    # the --strict-digest contract
                    raise
                except HbmBudgetError:
                    # the over-budget verdict is a property of the
                    # compiled program, not a transient failure —
                    # recompiling predicts the same peak
                    raise
                except Exception as e:  # noqa: BLE001 — failure recovery (§5)
                    if retries >= self.cfg.run.max_retries:
                        raise
                    restored = None
                    store = self._ckpt_store()
                    if store is not None:
                        latest = store.latest_step()
                        if latest is not None and (
                            baseline_step is None or latest > baseline_step
                        ):
                            restored, _ = store.restore(
                                template=self.init_state()
                            )
                        store.close()
                    if restored is None and caller_state:
                        # the caller's warm-start state may have been
                        # donated to the failed attempt's round dispatch;
                        # with no checkpoint of our own there is nothing
                        # safe to resume from
                        raise
                    retries += 1
                    self.logger.log({
                        "event": "retry",
                        "attempt": retries,
                        "round": None if restored is None else int(restored["round"]),
                        "error": repr(e)[:200],
                    })
                    # drop any in-flight prefetch state from the failed
                    # attempt; state=None restarts fresh (or re-resumes,
                    # if this run was itself a --resume run)
                    self._stop_prefetch()
                    state = restored
        finally:
            self._stop_prefetch()
            if self._exec_reg is not None:
                exec_mod.uninstall()
                # abort paths can leave queued registry records behind
                # the last flush boundary — the JSONL gets them anyway
                try:
                    for _rec in self._exec_reg.drain_records():
                        self.logger.log(_rec)
                except Exception as e:
                    print(f"executable record flush failed: {e}",
                          flush=True)
            if self._ledger_on and self._ledger_ref is not None:
                # final (or abort-path partial) ledger flush — same
                # every-exit-path guarantee as the trace export below
                try:
                    if self._ledger_logged_round != self._rounds_done:
                        self._log_ledger(self._rounds_done)
                except Exception as e:
                    print(f"client_ledger flush failed: {e}", flush=True)
            try:
                # end-of-fit run_summary: totals that otherwise require
                # re-aggregating the whole JSONL (aborts included)
                self.logger.log({
                    "event": "run_summary",
                    "rounds": int(self._rounds_done),
                    "wall_time_sec": round(
                        time.perf_counter() - self._fit_t0, 3
                    ),
                    "compiles": int(self._total_compiles),
                    "compile_ms": round(self._total_compile_ms, 3),
                    # double-buffer accounting: rounds whose host build
                    # / device placement were served from the prefetch
                    # buffers (i.e. hidden under the previous round's
                    # dispatch), and drains where purity forced a
                    # rebuild
                    **{k: int(v) for k, v in self._db_stats.items()},
                    **{k: int(v) for k, v in self._run_totals.items()},
                    # adapter-plane wire accounting: the full-delta ÷
                    # adapter-delta upload ratio (1.0 when lora is off)
                    "wire_reduction_vs_full": round(
                        self.wire_reduction_vs_full(), 2
                    ),
                    # ledger paging accounting: evictions are the cold
                    # spills, page_syncs the blocking hot-set fetches
                    # they forced (0 when the working set fit)
                    **({
                        "ledger_evictions": int(self._pager.evictions),
                        "ledger_page_syncs": int(self._pager.page_syncs),
                    } if self._pager is not None else {}),
                    # production-traffic totals (run.churn / fedbuff):
                    # staleness clamps, backpressure sheds, realized
                    # churn counts — present only on runs that saw them
                    **{k: int(v) for k, v in sorted(
                        self._traffic_totals.items()
                    )},
                    # the async throughput headline: updates absorbed
                    # (weight > 0 at admission) per wall-clock second,
                    # at the configured staleness bound — the number
                    # the async_throughput bench entry reads
                    **({
                        "async_updates_absorbed": int(self._async_absorbed),
                        "async_updates_per_sec": round(
                            self._async_absorbed
                            / max(time.perf_counter() - self._fit_t0, 1e-9),
                            3,
                        ),
                        "async_staleness_bound": int(
                            2 * self.cfg.server.async_max_staleness
                        ),
                        # pooled staleness distribution over every
                        # absorbed update this fit (satellite of the
                        # hier_async bench: the bound above is the
                        # ceiling, these are the realized quantiles)
                        "async_staleness_p50": self._staleness_percentiles()[0],
                        "async_staleness_p90": self._staleness_percentiles()[1],
                        "async_staleness_max": self._staleness_percentiles()[2],
                    } if self.fedbuff else {}),
                    # multi-version plane (server.async_versions > 1):
                    # per-version absorbed counts + late re-admissions
                    **({
                        "async_per_version": {
                            str(v): int(n) for v, n in enumerate(
                                self._per_version_absorbed[:self._versions]
                            )
                        },
                    } if self.fedbuff and self._versions > 1 else {}),
                    # hierarchy plane (server.hierarchy): per-edge
                    # absorbed updates and the final edge-trust vector
                    **({
                        "hier_edges": int(
                            self.cfg.server.hierarchy.num_edges
                        ),
                        "hier_edge_absorbed": {
                            str(e): int(n)
                            for e, n in enumerate(self._edge_absorbed)
                        },
                    } if self._hier else {}),
                    # population totals (run.obs.population): lifetime
                    # coverage / participation / pager hit rate / store
                    # bytes — `colearn summarize` renders these
                    **(self._population.summary_totals(
                        self._pager,
                        (self.fed.train_x, self.fed.train_y),
                    ) if self._population is not None else {}),
                    # compiled-program observatory: the run's predicted
                    # HBM high-water mark and which program set it
                    **({
                        "hbm_peak_bytes": int(self._exec_reg.peak_bytes),
                        "hbm_peak_program": self._exec_reg.peak_program,
                        "executables_compiled": int(
                            self._exec_reg.total_compiles
                        ),
                    } if self._exec_reg is not None
                        and self._exec_reg.peak_program else {}),
                })
            except Exception as e:
                print(f"run_summary log failed: {e}", flush=True)
            if self.tracer.trace and self.cfg.run.out_dir:
                # end-of-fit Chrome-trace dump (aborted/failed runs
                # included — the trace is the post-mortem artifact).
                # Multi-process: non-primaries write per-host
                # `trace.p<i>.json` fragments; the primary merges every
                # fragment present into the final trace.json so the
                # timeline carries one lane group per host (fragments
                # from hosts that finish later stay loadable on their
                # own — the merge is best-effort by design).
                try:
                    if self._primary:
                        import glob as _glob

                        frags = sorted(_glob.glob(
                            os.path.join(self._run_dir(), "trace.p*.json")
                        ))
                        path = self.tracer.export(
                            os.path.join(self._run_dir(), "trace.json"),
                            fragments=frags,
                        )
                        if path:
                            self.logger.log({
                                "event": "trace", "path": path,
                                "merged_fragments": len(frags),
                            })
                    else:
                        self.tracer.export(os.path.join(
                            self._run_dir(),
                            f"trace.p{self._process_index}.json",
                        ))
                except Exception as e:
                    print(f"trace export failed: {e}", flush=True)
            # flush + join the TensorBoard writer thread (no-op without TB)
            self.logger.close()

    def _fit(self, state: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        cfg = self.cfg
        store = self._ckpt_store()
        try:
            return self._fit_body(state, store)
        finally:
            # close on BOTH paths — a crashed attempt under run.max_retries
            # must not leak an open orbax manager per retry
            if store is not None:
                store.close()

    def _fit_body(self, state, store):
        cfg = self.cfg
        if store and store.latest_step() is not None:
            # checked for NON-resume runs too: a fresh run over a
            # mismatched store would overwrite the sidecar while orbax
            # retains the old run's higher-numbered checkpoints — a later
            # resume would then load them under the new (wrong) semantics
            self._check_state_kind()
        resumed = False
        if state is None:
            if cfg.run.resume and store and store.latest_step() is not None:
                template = self.init_state()
                state, step = store.restore(template=template)
                resumed = True
                self.logger.log({
                    "event": "resumed", "round": int(state["round"]),
                    # the two host pipelines use different (both
                    # deterministic) permutation RNGs; exact schedule
                    # replay requires resuming on the same kind
                    "host_pipeline": "native" if self._native else "numpy",
                })
            else:
                state = self.init_state()
        # The digest-chain head is host bookkeeping, not live round
        # state (run_round returns fresh dicts that would drop it):
        # pop it before placement and re-anchor the recorder. A retried
        # attempt (run.max_retries) re-enters here with the restored
        # head and NO verification — its own log tail past the restore
        # point is expected, and the re-run boundaries overwrite it
        # (last-wins in obs/digest.py's stream view).
        head = state.pop("digest_head", None)
        self._digest_prev, self._digest_prev_round = (
            digest_mod.head_unpack(head) if head is not None
            else (digest_mod.GENESIS, 0)
        )
        self._digest_cohorts.clear()
        if self._digest_on:
            self._digest_window = digest_mod.RoundWindow()
        if self._digest_on and resumed and cfg.run.obs.digest.verify_resume:
            self._verify_digest_resume(int(state["round"]))
        state = self._place_state(state)
        if self._ledger_on:
            self._ledger_ref = state.get("ledger")
        if self._snapshot_refresh:
            # seed the sampler with the checkpoint's ACTIVE snapshot /
            # sketch (zeros/empty on a fresh run → the uniform all-
            # unseen prior); refreshes at later log_every boundaries
            # override it at exactly the rounds the straight run did
            self._seed_sampler_from_state(state)
        start_round = int(state["round"])
        self._rounds_done = max(self._rounds_done, start_round)
        if start_round == 0:
            # precision/fusion provenance: every throughput or MFU
            # number read off this log is meaningless without the
            # dtype policy it ran under (`colearn summarize` surfaces
            # this record as its precision line)
            self.logger.log({
                "event": "precision",
                "param_dtype": cfg.run.param_dtype,
                "compute_dtype": cfg.run.compute_dtype,
                "local_param_dtype": (
                    cfg.run.local_param_dtype or cfg.run.param_dtype
                ),
                "fused_apply": bool(cfg.server.fused_apply),
                "double_buffer": bool(self._double_buffer),
                "control_plane": cfg.run.control_plane,
            })
        if start_round == 0 and self._phase_cost_on:
            # the static half of the cost model (obs/roofline.py): the
            # per-round `phase_cost` records carry only the per-grid
            # numbers; `colearn mfu` joins the two. peak_flops follows
            # the run's mfu_basis so a bf16 run is never decomposed
            # against the f32 roof (or vice versa).
            step_flops, flop_source = self._train_step_flops()
            coords, p_bytes = self._param_stats()
            basis, peak = mfu_basis(
                cfg.run.compute_dtype, cfg.run.local_param_dtype,
                cfg.run.param_dtype,
            )
            # cohort-layout GEMM geometry (obs/roofline.py): the rows
            # each shared-weight train GEMM feeds the MXU under this
            # run's layout, and the row-tile padding they waste — the
            # attribution `colearn mfu` prints next to the waterfall
            # (the megabatch layout's whole point is driving this pad
            # fraction to ~0 without touching any wire shape)
            lanes = (
                int(self.mesh.shape[mesh_lib.CLIENT_AXIS])
                if self.mesh is not None else 1
            )
            k_round = int(self._poisson_cap or cfg.server.cohort_size)
            k_local = max(1, k_round // max(1, lanes))
            # megabatch × LoRA runs the decomposed apply (frozen base
            # as a closure constant), so the un-batched-weight GEMMs
            # cover EVERY local step, not just the shared-weight step 0
            lora_all_steps = bool(
                cfg.model.lora.enabled
                and cfg.run.cohort_layout == "megabatch"
            )
            rows = layout_gemm_rows(
                cfg.run.cohort_layout, k_local, cfg.client.batch_size,
                lora_all_steps=lora_all_steps,
            )
            self.logger.log({
                "event": "phase_cost_model",
                "step_flops": int(step_flops),
                "flop_source": flop_source,
                "n_coords": int(coords),
                # the FULL model's coordinate count (== n_coords unless
                # model.lora is on) — the adapter-aware step-FLOP model
                # is a function of both, so the record carries both
                "n_coords_full": int(self._full_param_stats()[0]),
                "param_bytes": int(p_bytes),
                "compute_bytes": int(self._compute_itemsize()),
                "mfu_basis": basis,
                "peak_flops": float(peak),
                "peak_hbm_bytes_per_sec": float(PEAK_HBM_BYTES_PER_SEC),
                "n_chips": int(self.n_chips),
                "process_index": int(self._process_index),
                "cohort_layout": cfg.run.cohort_layout,
                "clients_per_lane": int(k_local),
                "gemm_rows": int(rows),
                "lora_all_steps": lora_all_steps,
                "mxu_tile_pad_fraction": round(
                    mxu_tile_pad_fraction(rows), 4
                ),
            })
        if start_round == 0 and self._poisson:
            self.logger.log({
                "event": "poisson_sampling",
                "q": round(self.sampler.q, 6),
                "cap": int(self._poisson_cap),
                # exact total abort probability over the run — the
                # δ_abort term of the (ε, δ + δ_abort) guarantee for the
                # aborting mechanism (see dp_client_epsilon)
                "dp_delta_abort": float(self.dp_delta_abort()),
            })
        if start_round == 0 and self._churn is not None:
            # churn provenance: the full hazard model, so any staleness
            # / dropout / convergence number in this log can be
            # attributed to the traffic shape it ran under
            cch = cfg.run.churn
            self.logger.log({
                "event": "churn",
                "diurnal_period": int(cch.diurnal_period),
                "diurnal_amplitude": float(cch.diurnal_amplitude),
                "base_availability": float(cch.base_availability),
                "min_availability": float(cch.min_availability),
                "dropout_hazard": float(cch.dropout_hazard),
                "crash_rate": float(cch.crash_rate),
                # trace replay (run.churn.trace): the availability
                # schedule came from a recorded on/off bitmap, not the
                # analytic diurnal model — record its shape so a
                # resume/replay can be checked against the same file
                **({
                    "trace": str(cch.trace),
                    "trace_rounds": int(self._churn.trace_rounds),
                    "trace_rows": int(self._churn.trace_rows),
                } if cch.trace else {}),
            })
        if start_round == 0 and self._hier:
            # hierarchy provenance: the two-tier topology and the core
            # defense every per-tier number in this log ran under
            hch = cfg.server.hierarchy
            self.logger.log({
                "event": "hierarchy",
                "num_edges": int(hch.num_edges),
                "core_aggregator": str(hch.core_aggregator),
                "edge_aggregator": str(cfg.server.aggregator),
                "edge_dropout_rate": float(hch.edge_dropout_rate),
                "core_trust_decay": float(hch.core_trust_decay),
            })
        if start_round == 0 and self.fedbuff and self._versions > 1:
            # multi-version provenance: concurrent model lines and the
            # retirement policy their generations age under
            self.logger.log({
                "event": "async_versions",
                "versions": int(self._versions),
                "retire_rounds": int(cfg.server.async_retire_rounds),
                "retire_updates": int(cfg.server.async_retire_updates),
                "readmit_decay": float(cfg.server.async_readmit_decay),
                "strict_versions": bool(cfg.run.strict_versions),
            })
        if start_round == 0 and self._bucket_ladder is not None:
            # shape-bucket provenance: the ladder every round's grid is
            # drawn from (rungs in steps_per_epoch), plus the bound the
            # compile budget is asserted against
            self.logger.log({
                "event": "shape_buckets",
                "ladder": [int(r) for r in self._bucket_ladder],
                "full_steps_per_epoch": int(self.shape.steps_per_epoch),
                "max_compiles_per_engine": len(self._bucket_ladder),
            })
        if start_round == 0 and self.attack_kind:
            # attack provenance: everything needed to attribute a run's
            # metrics to its adversary (kind, knobs, the compromised set)
            self.logger.log({
                "event": "attack",
                "kind": self.attack_kind,
                "fraction": cfg.attack.fraction,
                "scale": cfg.attack.scale,
                "eps": cfg.attack.eps,
                "n_compromised": int(len(self.compromised)),
                # the FULL set (one event per run): the `colearn
                # clients` report scores the anomaly flag against it
                "compromised": [int(c) for c in self.compromised],
            })
        if start_round == 0 and cfg.dp.enabled and cfg.dp.clipping == "two_pass":
            # ADVICE r5 #1: two_pass clipping is exact only up to
            # floating-point reassociation between the pass-1 norms and
            # the pass-2 released gradients; the accountant does not
            # model that slack, so make the assumption visible in the
            # run log next to the epsilons it qualifies
            self.logger.log({
                "event": "warning",
                "warning": "dp_two_pass_clipping",
                "detail": (
                    "dp.clipping='two_pass' with DP accounting enabled: "
                    "the reported dp_epsilon assumes exact per-example "
                    "clipping; two_pass clipping is exact only up to "
                    "floating-point reassociation between the norm pass "
                    "and the release pass"
                ),
            })
        if start_round == 0 and self.fed.meta.get("repair_used"):
            # the Dirichlet extreme-α repair changed the realized label
            # skew — record it in the run log so experiments at extreme α
            # know their partition was patched (data/partition.py)
            self.logger.log({
                "event": "partition_repair",
                "moved": int(self.fed.meta.get("repair_moved", 0)),
            })
        t_start = time.perf_counter()

        # Rounds are DISPATCHED asynchronously; per-round metric scalars
        # stay on device in `pending` and are drained in one device_get at
        # flush boundaries. Host↔device round-trips (the expensive part on
        # a tunneled chip) happen once per flush, not once per round.
        # Throughput is measured per flush window (dispatch timestamps are
        # meaningless under async execution); the first window includes
        # compile time.
        flush_every = max(1, cfg.run.metrics_flush_every)
        if cfg.run.sanitize:
            flush_every = 1  # sanitize wants per-round finiteness checks
        if self._stream:
            # every dispatched-but-unexecuted round holds a full slab in
            # HBM; cap the async backlog so stream mode's bounded-memory
            # promise survives (≤2 dispatched + 1 prefetching)
            flush_every = min(flush_every, 2)
        pending = []  # (round_idx, RoundMetrics-on-device)
        flush_t0 = time.perf_counter()

        obs_cfg = cfg.run.obs

        def flush_obs(last_round):
            """Drain the tracer (+ device-memory gauges) into the JSONL
            — one `spans` record per flush window, not per span."""
            phases = self.tracer.drain()
            if phases:
                comp = phases.get("compile")
                if comp:
                    # run_summary accounting: lifetime compile totals
                    self._total_compiles += comp["count"]
                    self._total_compile_ms += comp["total_ms"]
                self.logger.log({
                    "event": "spans", "round": last_round, "phases": phases,
                    "process_index": int(self._process_index),
                })
            if obs_cfg.device_memory:
                mem = device_memory_stats()
                if mem:
                    self.logger.log(
                        {"event": "device_memory", "round": last_round, **mem}
                    )
            if self._exec_reg is not None:
                # registry-built records (executable_compiled / retrace
                # / warning) + this window's HBM high-water mark
                for rec in self._exec_reg.drain_records():
                    self.logger.log(rec)
                wm = self._exec_reg.watermark(last_round)
                if wm is not None:
                    self.logger.log(wm)
            self._log_population(last_round)

        def unhealthy(events, current_state):
            """Apply the configured on_unhealthy policy to this window's
            health events (already logged)."""
            if not events or obs_cfg.on_unhealthy == "warn":
                return
            if obs_cfg.on_unhealthy == "checkpoint_abort" and store is not None:
                with self.tracer.span("round.checkpoint"):
                    self._write_state_kind()
                    store.save(
                        int(current_state["round"]),
                        self._state_for_save(current_state),
                        force=True, block=True,
                    )
            flush_obs(int(current_state["round"]))
            kinds = ", ".join(
                f"{e['kind']}@round {e['round']}" for e in events
            )
            raise HealthAbortError(
                f"run.obs.on_unhealthy={obs_cfg.on_unhealthy!r}: {kinds}"
            )

        def flush(current_state):
            nonlocal flush_t0
            if not pending:
                return
            if self._cp_device:
                # drain the device-derived schedules FIRST: the record
                # loop below pops the per-round stats this populates
                self._drain_device_sched()
            with self.tracer.span("round.fetch"):
                fetched = jax.device_get([m for _, m in pending])
            dt = time.perf_counter() - flush_t0
            rounds_per_sec = len(pending) / dt if dt > 0 else 0.0
            updates_per_sec = (
                rounds_per_sec * cfg.server.cohort_size / self.n_chips
            )
            health_events = []
            for (ridx, _), m in zip(pending, fetched):
                record = {
                    "round": ridx + 1,
                    "train_loss": float(m.train_loss),
                    "examples": float(m.examples),
                }
                comm = self._comm_stats.pop(ridx, None)
                fail = self._fail_stats.pop(ridx, None)
                if comm:
                    record.update(comm)
                if fail:
                    record.update(fail)
                if self._digest_window is not None:
                    # fold this round into the digest window (flush
                    # drains pending in round order, so the fold is
                    # invariant to flush cadence and fuse_rounds)
                    self._digest_window.observe(
                        ridx + 1, self._digest_cohorts.pop(ridx, None),
                        comm, fail,
                    )
                if self.health is not None:
                    ev = self.health.observe_loss(ridx + 1, record["train_loss"])
                    if ev is not None:
                        health_events.append(ev)
                if cfg.dp.enabled:
                    record["dp_epsilon"] = round(self.dp_epsilon(ridx + 1), 4)
                if cfg.server.dp_client_noise_multiplier > 0.0:
                    record["dp_client_epsilon"] = round(
                        self.dp_client_epsilon(ridx + 1), 4
                    )
                if ridx in self._async_stats:
                    astat = self._async_stats.pop(ridx)
                    record["mean_staleness"] = round(astat["mean"], 3)
                    record["max_staleness"] = int(astat["max"])
                    record["staleness_p50"] = round(astat["p50"], 3)
                    record["staleness_p90"] = round(astat["p90"], 3)
                    if "version" in astat:
                        # multi-version plane: which model line this
                        # round drove, and any late completions folded
                        # back in from a retired generation
                        record["async_version"] = int(astat["version"])
                    if astat.get("readmitted"):
                        record["version_readmitted"] = int(
                            astat["readmitted"]
                        )
                    if astat.get("clamped"):
                        record["staleness_clamped"] = int(astat["clamped"])
                    if astat.get("bp_dropped"):
                        record["backpressure_dropped"] = int(
                            astat["bp_dropped"]
                        )
                    if astat.get("bp_rejected"):
                        record["backpressure_rejected"] = int(
                            astat["bp_rejected"]
                        )
                    if astat.get("edge_crashed"):
                        record["hier_edge_crashed"] = int(
                            astat["edge_crashed"]
                        )
                    if astat.get("edge_excluded"):
                        record["hier_edge_excluded"] = int(
                            astat["edge_excluded"]
                        )
                if ridx in self._hier_stats:
                    hstat = self._hier_stats.pop(ridx)
                    if hstat.get("edge_crashed"):
                        record["hier_edge_crashed"] = int(
                            hstat["edge_crashed"]
                        )
                for key in ("staleness_clamped", "backpressure_dropped",
                            "backpressure_rejected", "churn_unavailable",
                            "churn_dropped", "churn_crashed",
                            "version_readmitted", "hier_edge_crashed",
                            "hier_edge_excluded"):
                    if key in record:
                        self._traffic_totals[key] = (
                            self._traffic_totals.get(key, 0)
                            + int(record[key])
                        )
                if self._population is not None and any(
                    key in record for key in
                    ("churn_unavailable", "churn_dropped", "churn_crashed")
                ):
                    self._population.observe_churn(
                        record.get("churn_unavailable", 0),
                        record.get("churn_dropped", 0),
                        record.get("churn_crashed", 0),
                    )
                if ridx in self._attack_stats:
                    # compromised clients sampled into this round's
                    # cohort (attack provenance: the "attack" event at
                    # fit start records kind/knobs/the full set)
                    record["byzantine_count"] = self._attack_stats.pop(ridx)
                if hasattr(m, "consensus_dist"):
                    # decentralized health: Σ‖xᵢ−x̄‖²/N after mixing
                    record["consensus_dist"] = float(m.consensus_dist)
                if ridx == pending[-1][0]:
                    record["rounds_per_sec"] = round(rounds_per_sec, 4)
                    record["client_updates_per_sec_per_chip"] = round(updates_per_sec, 4)
                    if cfg.server.eval_every and (ridx + 1) % cfg.server.eval_every == 0:
                        record.update(self.evaluate(current_state["params"]))
                for k in self._run_totals:
                    if k in record:
                        self._run_totals[k] += int(record[k])
                self.logger.log(record)
                pc = self._phase_costs.pop(ridx, None)
                if pc is not None:
                    # the analytic cost record rides next to the round
                    # it describes — `colearn mfu` joins these with the
                    # spans records into the waterfall
                    self.logger.log({
                        "event": "phase_cost", "round": ridx + 1,
                        "process_index": int(self._process_index),
                        "phases": pc,
                    })
            last_round = pending[-1][0] + 1
            self._rounds_done = max(self._rounds_done, last_round)
            pending.clear()
            if (self._digest_on and last_round % self._digest_every == 0
                    and last_round > self._digest_prev_round):
                # digest boundary: current_state is exactly the state
                # after last_round (pending held rounds ..last_round-1)
                self._emit_round_digest(last_round, current_state)
            if (self._ledger_on and self._ledger_cfg.log_every
                    and self._ledger_ref is not None
                    and last_round - self._ledger_logged_round
                    >= self._ledger_cfg.log_every):
                # periodic device-resident-ledger snapshot: one fetch
                # per log_every rounds, at a flush boundary (the fetch
                # is a few KB — never per round)
                self._log_ledger(last_round)
            if self.health is not None and obs_cfg.params_check:
                finite = all(
                    bool(jnp.isfinite(x).all())
                    for x in jax.tree.leaves(current_state["params"])
                )
                ev = self.health.observe_params_finite(last_round, finite)
                if ev is not None:
                    health_events.append(ev)
            for ev in health_events:
                self.logger.log(ev)
            flush_obs(last_round)
            unhealthy(health_events, current_state)
            flush_t0 = time.perf_counter()

        fuse = cfg.run.fuse_rounds if not (
            self.fedbuff or self.gossip or self.stateful
        ) else 1
        if fuse > 1 and start_round % fuse:
            # A warm-start/checkpoint at an unaligned round would shift
            # every chunk boundary: evals/saves (validated as fuse
            # multiples) would never fire and the last chunk would run
            # past num_rounds. Instead of refusing, run UNFUSED rounds
            # (through the lazily-built fuse=1 engine twin) up to the
            # next chunk boundary, then re-enter the fused loop on the
            # re-aligned schedule.
            aligned = min(-(-start_round // fuse) * fuse,
                          cfg.server.num_rounds)
            self.logger.log({
                "event": "warning",
                "warning": "fuse_unaligned_resume",
                "round": start_round,
                "detail": (
                    f"resume/warm-start round {start_round} is not a "
                    f"fuse_rounds={fuse} chunk boundary; running "
                    f"{aligned - start_round} unfused catch-up round(s) "
                    f"to round {aligned}, then re-entering the fused loop"
                ),
            })
            for r in range(start_round, aligned):
                with self.tracer.span("round"):
                    state = self.run_round(state, r, fuse_override=1)
                if self._ledger_on:
                    self._ledger_ref = state.get("ledger")
                self._carry_host_ledger_state(state)
                pending.append((r, state.pop("_metrics")))
                if self._digest_on and (r + 1) % self._digest_every == 0:
                    # a digest needs the state AT its boundary — flush
                    # per catch-up round when one is due
                    flush(state)
            flush(state)
            start_round = aligned
        for r in range(start_round, cfg.server.num_rounds, fuse):
            profiling = r == cfg.run.profile_round
            if profiling:
                flush(state)
                profile_dir = os.path.join(self._run_dir(), "profile")
                jax.profiler.start_trace(profile_dir)
            try:
                with self.tracer.span("round"):
                    state = self.run_round(state, r)
                if self._ledger_on:
                    self._ledger_ref = state.get("ledger")
                # the ACTIVE snapshot/sketch + pager bookkeeping ride
                # every checkpoint so a resume scores mid-window rounds
                # (and replays slot assignment) exactly like the
                # straight run (run_round returns a fresh dict)
                self._carry_host_ledger_state(state)
                ms = state.pop("_metrics")
                if fuse == 1:
                    pending.append((r, ms))
                else:
                    # [F]-stacked fields from the fused scan: tiny device
                    # slices, drained at the same flush boundaries
                    pending.extend(
                        (r + j, jax.tree.map(lambda a, j=j: a[j], ms))
                        for j in range(fuse)
                    )
                if profiling:
                    # A scalar fetch, not block_until_ready: on a relayed
                    # chip only a device_get truly forces execution, and
                    # the trace must contain the round's device compute.
                    jax.device_get(pending[-1][1].train_loss)
            finally:
                if profiling:
                    # stop on the error path too — a raise mid-profiled-
                    # round must not leak an open trace session
                    jax.profiler.stop_trace()
                    self.logger.log({
                        "event": "profile", "round": r + 1,
                        "dir": profile_dir,
                    })
            r_end = r + fuse  # validate() pins eval/ckpt to chunk ends
            at_eval = cfg.server.eval_every and r_end % cfg.server.eval_every == 0
            at_ckpt = store and cfg.server.checkpoint_every and r_end % cfg.server.checkpoint_every == 0
            # digest boundaries force a flush (the digest reads the
            # state AT the boundary); ordered before at_ckpt's save so
            # a checkpoint's head always covers its own round
            at_digest = self._digest_on and r_end % self._digest_every == 0
            if len(pending) >= flush_every or at_eval or at_ckpt or at_digest or r_end == cfg.server.num_rounds:
                flush(state)
            if cfg.run.sanitize:
                finite = all(
                    bool(jnp.isfinite(x).all()) for x in jax.tree.leaves(state["params"])
                )
                if not finite:
                    if self.health is not None:
                        # the structured twin of the raise below, so
                        # post-mortems find it in the JSONL
                        self.logger.log({
                            "event": "health",
                            "kind": "non_finite_params",
                            "round": r_end,
                        })
                    raise FloatingPointError(f"non-finite params after round {r_end}")
            if at_ckpt:
                with self.tracer.span("round.checkpoint"):
                    self._write_state_kind()
                    store.save(r_end, self._state_for_save(state))
                flush_t0 = time.perf_counter()  # keep save time out of the next window
        flush(state)
        state["wall_time"] = time.perf_counter() - t_start
        if store:
            store.wait()  # land in-flight async saves before deciding
            if store.latest_step() != int(state["round"]):
                with self.tracer.span("round.checkpoint"):
                    self._write_state_kind()
                    store.save(int(state["round"]),
                               self._state_for_save(state),
                               force=True, block=True)
        flush_obs(int(state["round"]))  # tail spans (final save, eval)
        return state

    # ---- determinism flight recorder (run.obs.digest) ----------------

    def _state_for_save(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Checkpoint view of the live state: the wall-time scalar out,
        the digest-chain head in (template parity with init_state —
        digest-off runs save the genesis zeros)."""
        out = {k: v for k, v in state.items() if k != "wall_time"}
        out["digest_head"] = digest_mod.head_pack(
            self._digest_prev, self._digest_prev_round
        )
        return out

    def _compute_digest(self, last_round: int,
                        state: Dict[str, Any]) -> Dict[str, Any]:
        """The six digest components over the state after
        ``last_round`` + the window since the previous boundary. ONE
        host fetch (params/opt/ledger together), read-only — the
        digest-on ≡ digest-off bitwise contract lives here."""
        ledger_items = {
            k: state[k]
            for k in digest_mod.LEDGER_STATE_KEYS if k in state
        }
        fetched = jax.device_get({
            "params": state["params"],
            "opt": state["server_opt_state"],
            "ledger": ledger_items,
        })
        sched_hex, wire_hex = self._digest_window.drain(last_round)
        return digest_mod.state_components(
            fetched["params"], fetched["opt"], fetched["ledger"],
            sched_hex, wire_hex,
            {
                "seed": int(self.cfg.run.seed),
                "round": int(last_round),
                "snapshot_round": int(
                    np.asarray(state.get("ledger_snapshot_round", 0))
                ),
            },
        )

    def _emit_round_digest(self, last_round: int,
                           state: Dict[str, Any]) -> None:
        with self.tracer.span("round.digest"):
            comp = self._compute_digest(last_round, state)
            self_hex = digest_mod.chain_digest(
                self._digest_prev, last_round, comp
            )
            self.logger.log({
                "event": "round_digest",
                "round": int(last_round),
                "prev_round": int(self._digest_prev_round),
                "prev": self._digest_prev,
                "self": self_hex,
                "params": comp["params"],
                "params_leaves": comp["params_leaves"],
                "opt": comp["opt"],
                "ledger": comp["ledger"],
                "schedule": comp["schedule"],
                "wire": comp["wire"],
                "rng": comp["rng"],
            })
            self._digest_prev = self_hex
            self._digest_prev_round = int(last_round)

    def _load_own_records(self):
        """This run's already-written JSONL records (resume verify /
        replay read their own log before training continues)."""
        path = self.logger.path
        records = []
        if path and os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail line from a crashed writer
        return records

    def _verify_digest_resume(self, start_round: int) -> None:
        """Resume-time chain verification: the checkpoint's head must
        match a chain-valid ``round_digest`` record in the log
        (truncated/tampered logs fail). Logged as a ``digest_resume``
        event; ``run.obs.digest.strict`` escalates a failure to
        DigestResumeError before any training happens."""
        ok, detail = digest_mod.resume_head_status(
            self._load_own_records(),
            self._digest_prev, self._digest_prev_round,
        )
        self.logger.log({
            "event": "digest_resume",
            "round": int(start_round),
            "ok": bool(ok),
            "head_round": int(self._digest_prev_round),
            "head": self._digest_prev,
            "detail": detail,
        })
        if not ok and self.cfg.run.obs.digest.strict:
            raise digest_mod.DigestResumeError(
                f"digest chain verification failed on resume at round "
                f"{start_round}: {detail}"
            )

    def replay_round(self, target_round: int) -> Dict[str, Any]:
        """Re-execute exactly one logged digest round — the "reproduce
        round 4 317 on my desk" workflow behind ``colearn replay``.

        Restores the nearest checkpoint at or before the target
        record's window start (round 0's deterministic init is the
        virtual step-0 checkpoint), re-runs the intervening rounds
        UNFUSED (the catch-up twin — digest streams are fuse-invariant
        by construction), recomputes the target boundary's digest from
        the re-realized schedule/wire/state, and compares it component
        by component against the logged record. Sync rounds replay
        exactly; snapshot-fed sampling (adaptive/streaming) replays
        exactly only when the window does not cross a sampler-refresh
        boundary (the refresh rides metrics flushes the replay loop
        does not perform) — the schedule component catches the
        difference rather than hiding it."""
        if not self._digest_on:
            raise ValueError(
                "replay requires run.obs.digest.enabled=true (the "
                "digest config must match the recorded run)"
            )
        target_round = int(target_round)
        records = self._load_own_records()
        stream = digest_mod.digest_records(records)
        by_round = {int(r["round"]): r for r in stream}
        rec = by_round.get(target_round)
        if rec is None:
            have = ", ".join(str(r) for r in sorted(by_round)[:12])
            raise ValueError(
                f"no round_digest record at round {target_round} in "
                f"{self.logger.path} (digest rounds: {have or 'none'})"
            )
        window_start = int(rec["prev_round"])
        store = self._ckpt_store()
        steps = [
            s for s in (store.steps() if store else [])
            if s <= window_start
        ]
        if steps:
            state, step = store.restore(template=self.init_state(),
                                        step=steps[-1])
        else:
            # round 0: init_state is seed-deterministic — the virtual
            # step-0 checkpoint every run starts from
            state, step = self.init_state(), 0
        if store is not None:
            store.close()
        state.pop("digest_head", None)
        state = self._place_state(state)
        if self._ledger_on:
            self._ledger_ref = state.get("ledger")
        if self._snapshot_refresh:
            self._seed_sampler_from_state(state)
        self._digest_cohorts.clear()
        self._digest_window = digest_mod.RoundWindow()
        for r in range(step, target_round):
            state = self.run_round(state, r, fuse_override=1)
            if self._ledger_on:
                self._ledger_ref = state.get("ledger")
            self._carry_host_ledger_state(state)
            state.pop("_metrics", None)
            comm = self._comm_stats.pop(r, None)
            fail = self._fail_stats.pop(r, None)
            cohort = self._digest_cohorts.pop(r, None)
            for scratch in (self._async_stats, self._hier_stats,
                            self._attack_stats, self._phase_costs):
                scratch.pop(r, None)
            if r + 1 > window_start:
                # rounds at or before the window start were digested
                # by an EARLIER boundary in the original run
                self._digest_window.observe(r + 1, cohort, comm, fail)
        comp = self._compute_digest(target_round, state)
        replayed_self = digest_mod.chain_digest(
            rec.get("prev", digest_mod.GENESIS), target_round, comp
        )
        logged = digest_mod.components_from_record(rec)
        components = {
            name: comp[name] == logged.get(name)
            for name in digest_mod.COMPONENT_ORDER
        }
        leaves = sorted(
            set(comp["params_leaves"]) | set(logged["params_leaves"])
        )
        return {
            "round": target_round,
            "checkpoint_step": int(step),
            "replayed_rounds": target_round - int(step),
            "match": replayed_self == rec.get("self"),
            "logged": rec.get("self"),
            "replayed": replayed_self,
            "components": components,
            "params_leaves_diverged": [
                k for k in leaves
                if comp["params_leaves"].get(k)
                != logged["params_leaves"].get(k)
            ],
        }

    # ------------------------------------------------------------------

    def dp_epsilon(self, rounds_done: int) -> float:
        """(ε, δ) spent so far: example-level DP-SGD accounting composed
        over every local step executed across rounds.

        The sampling rate uses the **minimum** client shard size (the
        worst case over participants), so the reported ε upper-bounds
        every client's spend. See privacy/dp.py for the Poisson-vs-
        shuffle accounting caveat.
        """
        from colearn_federated_learning_tpu.privacy.dp import rdp_epsilon

        min_shard = float(min(self.shape.cap, int(self.fed.client_sizes().min())))
        q = min(1.0, self.cfg.client.batch_size / max(min_shard, 1.0))
        total_steps = rounds_done * self.shape.steps
        return rdp_epsilon(
            self.cfg.dp.noise_multiplier, q, total_steps, self.cfg.dp.delta
        )

    def dp_client_epsilon(self, rounds_done: int) -> float:
        """Client-level (ε, δ) spent by central DP-FedAvg noise: the
        sampled-Gaussian RDP accountant (same closed form as the
        example-level accountant) composed over rounds with client
        sampling rate q = cohort/num_clients; δ from cfg.dp.delta.
        config.validate() REJECTS weighted sampling under client DP
        (size-proportional sampling would push a big client's per-round
        inclusion probability above q).

        Exactness depends on ``server.sampling``:

        - ``"poisson"`` — every client independently participates with
          probability q each round, which is PRECISELY the mechanism
          the Poisson subsampled-Gaussian RDP bound is derived for: the
          reported ε is a sound upper bound at δ + δ_abort, where
          δ_abort (:meth:`dp_delta_abort`, logged at fit start) is the
          exact probability that some round's realized cohort overflows
          the static cap and the run ABORTS (observable, never silent).
        - ``"uniform"`` — cohorts are fixed-size samples without
          replacement, while the bound is derived for Poisson
          subsampling at rate q — the standard approximation in the
          DP-FedAvg literature (McMahan et al. 2018 §3.1 make the same
          substitution), not a strict upper bound for WOR sampling.
        """
        from colearn_federated_learning_tpu.privacy.dp import rdp_epsilon

        q = min(1.0, self.cfg.server.cohort_size / self.fed.num_clients)
        return rdp_epsilon(
            self.cfg.server.dp_client_noise_multiplier, q, rounds_done,
            self.cfg.dp.delta,
        )

    def dp_delta_abort(self, rounds: Optional[int] = None) -> float:
        """Exact probability that ANY of the run's poisson rounds
        realizes a cohort above the static cap (union bound over rounds
        on the exact Binomial(N, q) upper tail, computed in log space).
        This is the δ_abort of the aborting mechanism's
        (ε, δ + δ_abort)-DP guarantee; with the 5σ default cap it is
        ~1e-8 per run. 0.0 when not poisson or cap == N."""
        if not self._poisson:
            return 0.0
        n, cap = self.fed.num_clients, self._poisson_cap
        if cap >= n:
            return 0.0
        q = self.sampler.q
        from math import exp, lgamma, log

        lq, l1q = log(q), log(1.0 - q)
        tail = 0.0
        for b in range(cap + 1, n + 1):
            tail += exp(
                lgamma(n + 1) - lgamma(b + 1) - lgamma(n - b + 1)
                + b * lq + (n - b) * l1q
            )
        t = self.cfg.server.num_rounds if rounds is None else rounds
        return min(1.0, t * tail)

    def evaluate(self, params) -> Dict[str, float]:
        with self.tracer.span("round.eval"):
            xb, yb, mb = self._eval_data
            loss, acc, n = jax.device_get(self._eval_all(params, xb, yb, mb))
            return {"eval_loss": float(loss / n), "eval_acc": float(acc / n)}

    def evaluate_federated(self, params, max_clients: int = 64,
                           seed: Optional[int] = None) -> Dict[str, float]:
        """Federated (per-client) evaluation of the GLOBAL model: run the
        model on each client's OWN shard and report the accuracy
        distribution across clients — the fairness view centralized eval
        averages away (a model can hold 90% central accuracy while its
        worst-decile clients sit near chance under label skew).

        Simulation caveat, stated rather than hidden: clients have no
        separate local test split (the reference's datasets don't ship
        one), so this evaluates on each client's local data — the
        standard simulator proxy for federated evaluation; it measures
        the global model's FIT to each client's distribution, not
        held-out generalization (``evaluate`` does that centrally,
        ``evaluate_personalized`` does per-client holdouts).

        Deterministic in ``seed`` (client subsample when
        num_clients > max_clients). Reports mean/std/median, the 10th
        percentile, and the worst client. Runs as ONE device dispatch:
        every client's batches are padded to a common count (zero-mask
        pad batches contribute nothing) and stacked ``[clients, batches,
        batch, ...]``, then a nested ``lax.scan`` computes all per-client
        sums — not clients × batches jitted calls (the dispatch-bound
        pattern ``_eval_all`` exists to avoid)."""
        if max_clients < 1:
            raise ValueError(f"max_clients must be >= 1, got {max_clients}")
        seed = self.cfg.run.seed if seed is None else seed
        rng = np.random.default_rng((seed, 60013))
        eligible = [
            cid for cid in range(self.fed.num_clients)
            if len(self.fed.client_indices[cid]) >= 1
        ]
        if len(eligible) > max_clients:
            eligible = sorted(
                rng.choice(eligible, size=max_clients, replace=False)
            )
        batch = self.cfg.client.batch_size
        # every client pads to the same batch count (one trace; pad
        # batches are zero-mask, contributing nothing)
        nb = max(
            -(-len(self.fed.client_indices[cid]) // batch) for cid in eligible
        )

        def pad(a):
            if a.shape[0] == nb:
                return a
            fill = np.zeros((nb - a.shape[0],) + a.shape[1:], a.dtype)
            return np.concatenate([a, fill])

        # chunk clients so the stacked [chunk, nb, batch, ...] buffer
        # stays bounded in BOTH host RAM and HBM (real federated-
        # ImageNet shards would otherwise stack to many GB); batches
        # are built per chunk, so peak host memory is one chunk, and
        # it is still one dispatch per CHUNK, never per batch
        bytes_per_client = nb * batch * (
            int(np.prod(self.fed.train_x.shape[1:])) * self.fed.train_x.itemsize
            + int(np.prod(self.fed.train_y.shape[1:]) or 1) * self.fed.train_y.itemsize
            + 4  # mask f32
        )
        chunk = max(1, min(len(eligible), (512 << 20) // max(bytes_per_client, 1)))
        # per-client rows stream through iter_client_slabs: under a
        # store backend consecutive client ids coalesce into bounded
        # contiguous-range gathers (eval_buffer_mb) instead of one
        # transient arange materialization per client — bitwise the
        # same bytes as the in-memory fancy-index (test-pinned in
        # tests/test_store_data_plane.py)
        eval_buf = self.cfg.data.store.eval_buffer_mb << 20
        cs, ns = [], []
        for lo in range(0, len(eligible), chunk):
            part = [
                eval_batches(cx, cy, batch)
                for _cid, cx, cy in iter_client_slabs(
                    self.fed.train_x, self.fed.train_y,
                    self.fed.client_indices, eligible[lo:lo + chunk],
                    eval_buf,
                )
            ]
            xs, ys, ms = (
                np.stack([pad(t[i]) for t in part]) for i in range(3)
            )
            c, n = jax.device_get(self._fed_eval_all(
                params, jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(ms)
            ))
            cs.append(np.asarray(c))
            ns.append(np.asarray(n))
        a = np.concatenate(cs) / np.maximum(np.concatenate(ns), 1.0)
        return {
            "federated_acc_mean": float(a.mean()),
            "federated_acc_std": float(a.std()),
            "federated_acc_median": float(np.median(a)),
            "federated_acc_p10": float(np.percentile(a, 10)),
            "federated_acc_worst": float(a.min()),
            "federated_clients": len(a),
        }

    def evaluate_personalized(self, params, epochs: int = 1,
                              holdout_frac: float = 0.2,
                              max_clients: int = 32,
                              seed: Optional[int] = None,
                              round_idx: int = 0) -> Dict[str, float]:
        """Per-client personalization metric (pFL evaluation protocol):
        fine-tune the GLOBAL model ``epochs`` epochs on each client's
        train split, then evaluate on that client's held-out split;
        ``baseline_*`` is the un-tuned global model on the SAME holdouts,
        so the personalization gain is read directly off the pair.

        Deterministic in ``seed`` (splits, batch order, sampled client
        subset). Clients with fewer than 2 examples are skipped. Uses a
        per-client slab gather (host → device) so it works under both
        ``data.placement`` modes; cost is one local-training call per
        evaluated client — cap via ``max_clients``.

        ``round_idx``: the round the evaluated params came from — the
        fine-tune runs at the same decayed lr (``lr·decay^round``) the
        run's clients would use, not the hot initial lr."""
        if epochs < 1:
            raise ValueError(f"personalize epochs must be >= 1, got {epochs}")
        if not 0.0 < holdout_frac < 1.0:
            raise ValueError(
                f"holdout_frac must be in (0, 1), got {holdout_frac}"
            )
        if max_clients < 1:
            raise ValueError(f"max_clients must be >= 1, got {max_clients}")
        seed = self.cfg.run.seed if seed is None else seed
        rng = np.random.default_rng((seed, 104729))
        eligible = [
            cid for cid in range(self.fed.num_clients)
            if len(self.fed.client_indices[cid]) >= 2
        ]
        if len(eligible) > max_clients:
            eligible = sorted(
                rng.choice(eligible, size=max_clients, replace=False)
            )
        batch = self.cfg.client.batch_size
        cap = self.shape.cap
        steps = epochs * self.shape.steps_per_epoch
        if getattr(self, "_personal_train", None) is None:
            # built once — jax.jit retraces per input shape on its own;
            # local_dtype matches the run so the personalization metric
            # is measured under the precision clients actually train with
            self._personal_train = exec_mod.instrument(
                "personal.local_train",
                jax.jit(make_local_train_fn(
                    self.model, self.cfg.client, DPConfig(), self.task,
                    local_dtype=self._local_dtype(),
                )),
            )

        pers, base = [], []
        # clients stream through iter_client_slabs (store-coalesced
        # contiguous gathers, bounded by eval_buffer_mb); the
        # holdout/train split permutes LOCAL positions into each
        # client's natural-order slab — rng.permutation(n) consumes the
        # generator identically to the former rng.permutation(ids)
        # (Fisher–Yates swaps are index-based), and cx[perm] is the
        # same bytes, so splits/batch order/metrics stay bitwise
        for cid, cx, cy in iter_client_slabs(
            self.fed.train_x, self.fed.train_y, self.fed.client_indices,
            eligible, self.cfg.data.store.eval_buffer_mb << 20,
        ):
            perm = rng.permutation(len(cx))
            n_hold = min(max(1, int(round(holdout_frac * len(perm)))),
                         len(perm) - 1)
            hold, train = perm[:n_hold], perm[n_hold:]
            if len(train) > cap:
                train = train[:cap]
            n = len(train)
            # slab-local finetune grid, same layout as make_round_indices
            idx = np.zeros((steps * batch,), np.int32)
            mask = np.zeros((steps * batch,), np.float32)
            per_epoch = self.shape.steps_per_epoch * batch
            for e in range(epochs):
                off = e * per_epoch
                idx[off : off + n] = rng.permutation(n).astype(np.int32)
                mask[off : off + n] = 1.0
            pad = cap - n
            slab_x = cx[train]
            slab_y = cy[train]
            if pad:
                slab_x = np.concatenate(
                    [slab_x, np.repeat(slab_x[:1], pad, axis=0)]
                )
                slab_y = np.concatenate(
                    [slab_y, np.repeat(slab_y[:1], pad, axis=0)]
                )
            extra = ()
            if self.cfg.client.lr_decay != 1.0:
                extra = (jnp.float32(self.cfg.client.lr_decay ** round_idx),)
            p_i, _ = self._personal_train(
                params, jnp.asarray(slab_x), jnp.asarray(slab_y),
                jnp.asarray(idx.reshape(steps, batch)),
                jnp.asarray(mask.reshape(steps, batch)),
                jax.random.fold_in(jax.random.PRNGKey(seed), cid),
                *extra,
            )
            xb, yb, mb = eval_batches(cx[hold], cy[hold], batch)
            accs = {}
            for tag, p in (("personalized", p_i), ("baseline", params)):
                c_sum = n_sum = 0.0
                for b in range(xb.shape[0]):
                    _, c, m = self._eval_fn(
                        p, jnp.asarray(xb[b]), jnp.asarray(yb[b]),
                        jnp.asarray(mb[b]),
                    )
                    c_sum += float(c)
                    n_sum += float(m)
                accs[tag] = c_sum / max(n_sum, 1.0)
            pers.append(accs["personalized"])
            base.append(accs["baseline"])
        if not pers:
            # nothing eligible (all shards < 2 examples): report the
            # count honestly instead of NaN means (which break JSON)
            return {"personalized_clients": 0, "personalize_epochs": epochs}
        pers_a, base_a = np.asarray(pers), np.asarray(base)
        return {
            "personalized_acc_mean": float(pers_a.mean()),
            "personalized_acc_std": float(pers_a.std()),
            "baseline_acc_mean": float(base_a.mean()),
            "baseline_acc_std": float(base_a.std()),
            "personalized_clients": len(pers),
            "personalize_epochs": epochs,
        }

    def export_checkpoint(self, path: str, step: Optional[int] = None) -> Dict[str, Any]:
        """Export a checkpoint's GLOBAL MODEL PARAMS to a single flax
        msgpack file (`colearn export`) — the deployment artifact; see
        utils/checkpoint.export_params / load_params for the consumer
        side."""
        from colearn_federated_learning_tpu.utils.checkpoint import export_params

        store = CheckpointStore(os.path.join(self._run_dir(), "ckpt"))
        state, step = store.restore(step=step, template=self.init_state())
        store.close()
        params = state["params"]
        if self._lora:
            # the deployment artifact is the MERGED model (W +
            # (alpha/r)·A·B over the seed-derived frozen base) — a
            # consumer of the export never needs the adapter structure
            params = self.model.merged_params(params)
        out_path = export_params(params, path)
        n_params = sum(
            int(np.prod(p.shape)) for p in jax.tree.leaves(params)
        )
        return {"event": "exported", "path": out_path, "round": int(state["round"]),
                "num_params": n_params}

    def evaluate_checkpoint(self, step: Optional[int] = None,
                            personalize: bool = False,
                            federated: bool = False,
                            federated_clients: int = 64,
                            **personalize_kwargs) -> Dict[str, float]:
        store = CheckpointStore(os.path.join(self._run_dir(), "ckpt"))
        template = self.init_state()
        state, step = store.restore(step=step, template=template)
        store.close()
        state = self._place_state(state)
        out = self.evaluate(state["params"])
        if federated:
            out.update(
                self.evaluate_federated(
                    state["params"], max_clients=federated_clients,
                )
            )
        if personalize:
            out.update(
                self.evaluate_personalized(
                    state["params"], round_idx=int(state["round"]),
                    **personalize_kwargs,
                )
            )
        out["round"] = int(state["round"])
        return out
