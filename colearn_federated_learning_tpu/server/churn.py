"""Seed-pure availability/churn model (``run.churn``, ROADMAP item 4).

Production federations of the CoLearn class see diurnal availability
waves, dropouts, and mid-round crashes (FedScale, Lai et al. 2022 —
trace-shaped device behavior); the synchronous lab loop sees none of
them. This module is the repo's churn source of truth: every realized
churn event is a **pure function of (run.seed, round, client_id)** —
no RNG state, no host clock — computed by counter-mode integer hashing
(SplitMix64 over the packed key), so:

- schedules are **resume-replayable**: a run restored from any
  checkpoint re-derives the exact availability/dropout/crash draws the
  straight run made (nothing churn-related rides the checkpoint);
- draws are **engine-invariant**: the sharded engine, the sequential
  oracle, and the prefetch worker thread all evaluate the same pure
  function and agree bitwise;
- evaluation is **O(len(ids))**: the streaming sampler can gate a
  million-client universe without ever materializing an O(N) schedule.

The model has three planes, all gated by ``ChurnConfig``:

- **Diurnal availability**: each client carries a fixed phase
  ``phase_i = hash01(seed, PHASE, 0, i)`` and is available in round
  ``r`` with probability ``clip(base + amplitude·sin(2π(r/period +
  phase_i)), min_availability, 1)`` — the classic day/night
  participation wave with per-client timezone offsets. The realized
  availability bit is an independent hash draw against that
  probability.
- **Dropout hazard**: a *sampled* (or fedbuff-popped) client fails
  mid-round with probability ``dropout_hazard`` — total failure, its
  aggregation weight zeroes through the same ``n_ex`` path as
  ``server.dropout_rate``.
- **Crash-mid-round injection**: with probability ``crash_rate`` a
  participant crashes after a hash-drawn fraction of its local steps —
  realized through the existing straggler/mask-spec truncation path
  (the partial update still aggregates, weighted by the work done),
  which is exactly what a device killed mid-training uploads under
  FedBuff-style partial-work semantics.

Where it hooks in: the cohort samplers reject unavailable candidates
(server/sampler.py ``availability_fn``), the round driver's
``_apply_failures`` realizes dropout/crash on the dispatched cohort,
and the fedbuff scheduler defers offline completions (growing realized
staleness — the regime the bounded-staleness admission gate exists
for). ``enabled=False`` constructs no model anywhere: schedules and
params are bitwise-identical to a build without this module.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# domain-separation tags for the per-plane hash streams (arbitrary odd
# constants; distinct so the planes are independent draws)
_TAG_PHASE = np.uint64(0x9E3779B97F4A7C15)
_TAG_AVAIL = np.uint64(0xC2B2AE3D27D4EB4F)
_TAG_DROP = np.uint64(0x165667B19E3779F9)
_TAG_CRASH = np.uint64(0x27D4EB2F165667C5)
_TAG_FRAC = np.uint64(0x85EBCA6B2C2B2AE3)
_TAG_ROW = np.uint64(0xD6E8FEB86659FD93)   # client → trace-row mapping
_TAG_EDGE = np.uint64(0xA0761D6478BD642F)  # per-(round, edge) crash draw
_TAG_ORDER = np.uint64(0x2545F4914F6CDD1D)  # device-plane shard rotation

_TWO_PI = 2.0 * np.pi


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """SplitMix64 finalizer over uint64 (the same mixer the population
    HLL uses) — a bijective avalanche, vectorized."""
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
        x = ((x ^ (x >> np.uint64(30)))
             * np.uint64(0xBF58476D1CE4E5B9)).astype(np.uint64)
        x = ((x ^ (x >> np.uint64(27)))
             * np.uint64(0x94D049BB133111EB)).astype(np.uint64)
        return (x ^ (x >> np.uint64(31))).astype(np.uint64)


def hash_u64(seed: int, tag: np.uint64, round_idx: int,
             ids: np.ndarray) -> np.ndarray:
    """Raw uint64 hash per id, pure in (seed, tag, round, id): three
    chained SplitMix64 rounds over the packed key. This is the shared
    host/device draw core — ``server/device_plane.py`` lowers exactly
    this chain as uint32 pairs and is test-pinned bitwise against it,
    so every in-program churn gate agrees with the host oracle."""
    ids64 = np.asarray(ids, dtype=np.int64).astype(np.uint64)
    with np.errstate(over="ignore"):
        h = _splitmix64(np.uint64(np.uint64(seed & 0xFFFFFFFFFFFFFFFF)) ^ tag)
        h = _splitmix64(h + np.uint64(round_idx & 0xFFFFFFFFFFFFFFFF))
        h = _splitmix64(h ^ _splitmix64(ids64))
    return h


def hash_k53(seed: int, tag: np.uint64, round_idx: int,
             ids: np.ndarray) -> np.ndarray:
    """Top 53 hash bits as uint64 — the integer the float draw is built
    from. ``hash_k53(...) < ceil(p * 2**53)`` is exactly equivalent to
    ``_hash01(...) < p`` for p in [0, 1] (p * 2**53 is exact in
    float64, so the ceiling is the true integer threshold), which is
    how the device plane evaluates probability gates without floats."""
    return hash_u64(seed, tag, round_idx, ids) >> np.uint64(11)


def threshold_u53(p) -> np.ndarray:
    """ceil(p * 2**53) clipped to [0, 2**53] as uint64: the integer
    threshold equivalent of comparing the 53-bit draw against float
    probability ``p`` (see ``hash_k53``)."""
    p = np.clip(np.asarray(p, dtype=np.float64), 0.0, 1.0)
    # p * 2**53 is exact in float64 for p in [0, 1] (exponent shift of
    # a <=53-bit significand), so ceil is the exact integer threshold
    return np.ceil(p * float(1 << 53)).astype(np.uint64)


def _hash01(seed: int, tag: np.uint64, round_idx: int,
            ids: np.ndarray) -> np.ndarray:
    """Uniform [0, 1) draw per id, pure in (seed, tag, round, id) —
    the top 53 bits of ``hash_u64`` mapped to float64 (enough avalanche
    that adjacent (round, id) pairs are independent to the 53-bit
    double precision the [0,1) map keeps)."""
    # top 53 bits → [0, 1) exactly representable in float64
    return (hash_k53(seed, tag, round_idx, ids)).astype(np.float64) \
        / float(1 << 53)


class ChurnModel:
    """The seed-pure churn oracle (see module docstring). Stateless by
    construction: every method is a pure function of its arguments and
    the frozen config, so instances are free to construct anywhere
    (driver, sampler closure, tests) and always agree."""

    def __init__(self, cfg, seed: int):
        # cfg is config.ChurnConfig (duck-typed so tests can pass a
        # stand-in); values frozen here — mutation after construction
        # must not silently change schedules mid-run
        self.seed = int(seed)
        self.period = int(cfg.diurnal_period)
        self.amplitude = float(cfg.diurnal_amplitude)
        self.base = float(cfg.base_availability)
        self.floor = float(cfg.min_availability)
        self.dropout_hazard = float(cfg.dropout_hazard)
        self.crash_rate = float(cfg.crash_rate)

    # ---- diurnal availability ---------------------------------------

    def availability_prob(self, round_idx: int, ids) -> np.ndarray:
        """[len(ids)] per-client availability probability for this
        round: the diurnal wave at each client's fixed phase, clipped
        to [min_availability, 1] so no client is ever permanently
        unreachable (the exploration-floor principle)."""
        ids = np.asarray(ids, dtype=np.int64)
        phase = _hash01(self.seed, _TAG_PHASE, 0, ids)
        wave = np.sin(_TWO_PI * (round_idx / max(1, self.period) + phase))
        return np.clip(self.base + self.amplitude * wave, self.floor, 1.0)

    def available(self, round_idx: int, ids) -> np.ndarray:
        """[len(ids)] bool: is each client online in this round?"""
        ids = np.asarray(ids, dtype=np.int64)
        u = _hash01(self.seed, _TAG_AVAIL, round_idx, ids)
        return u < self.availability_prob(round_idx, ids)

    # ---- in-round failures ------------------------------------------

    def dropped(self, round_idx: int, ids) -> np.ndarray:
        """[len(ids)] bool: mid-round total failure (dropout hazard)."""
        ids = np.asarray(ids, dtype=np.int64)
        if self.dropout_hazard <= 0.0:
            return np.zeros(len(ids), dtype=bool)
        return _hash01(self.seed, _TAG_DROP, round_idx, ids) < self.dropout_hazard

    def crashed(self, round_idx: int, ids) -> Tuple[np.ndarray, np.ndarray]:
        """(crashed [bool], work_fraction [float64]) per client: a
        crash kills the client after ``work_fraction`` of its local
        steps — the fraction is itself a hash draw in (0, 1], so two
        crashes in different rounds truncate at different points."""
        ids = np.asarray(ids, dtype=np.int64)
        if self.crash_rate <= 0.0:
            return (np.zeros(len(ids), dtype=bool),
                    np.ones(len(ids), dtype=np.float64))
        crashed = _hash01(self.seed, _TAG_CRASH, round_idx, ids) < self.crash_rate
        # (0, 1]: a crash always completes at least the fraction the
        # truncation floor maps to >= 1 step
        frac = 1.0 - _hash01(self.seed, _TAG_FRAC, round_idx, ids)
        return crashed, frac


class TraceChurnModel(ChurnModel):
    """Trace-replay availability (``run.churn.trace``): the diurnal
    wave is replaced by playback of a FedScale-style per-device on/off
    trace — a ``.npy`` uint8 bitmap ``[trace_rounds, trace_rows]``
    opened as a read-only memmap (never materialized; a million-client
    run touches O(cohort) bytes of it per draw).

    Client ``i`` maps to a STABLE hash-derived trace row (real traces
    carry fewer devices than the simulated universe, so clients share
    rows — the standard FedScale replay convention), and round ``r``
    plays row bit ``[r mod trace_rounds]``. The availability
    probability is the bit clipped to ``[min_availability, 1]`` — an
    off-bit client keeps the exploration-floor probability — and the
    realized bit is the SAME seed-pure hash draw the analytic wave
    uses, so trace schedules inherit every churn invariant: engine-
    invariant, resume-replayable with zero checkpoint state, and
    O(len(ids)) per evaluation. Dropout hazard and crash injection
    compose unchanged (they are independent hash planes)."""

    def __init__(self, cfg, seed: int):
        super().__init__(cfg, seed)
        self.trace_path = str(cfg.trace)
        # mmap the bitmap: round playback gathers single rows, client
        # lookups gather single bytes — O(cohort) I/O per draw
        bitmap = np.load(self.trace_path, mmap_mode="r")
        if bitmap.ndim != 2 or bitmap.dtype != np.uint8:
            raise ValueError(
                f"run.churn.trace {self.trace_path!r}: expected a 2-D "
                f"uint8 bitmap [trace_rounds, trace_rows], got "
                f"{bitmap.dtype} {bitmap.shape}"
            )
        if bitmap.shape[0] < 1 or bitmap.shape[1] < 1:
            raise ValueError(
                f"run.churn.trace {self.trace_path!r}: empty bitmap "
                f"{bitmap.shape}"
            )
        self._bitmap = bitmap
        self.trace_rounds, self.trace_rows = map(int, bitmap.shape)

    def _rows(self, ids: np.ndarray) -> np.ndarray:
        """Stable client → trace-row assignment (seed-pure hash)."""
        ids64 = np.asarray(ids, dtype=np.int64).astype(np.uint64)
        with np.errstate(over="ignore"):
            h = _splitmix64(
                _splitmix64(np.uint64(self.seed & 0xFFFFFFFFFFFFFFFF)
                            ^ _TAG_ROW) ^ _splitmix64(ids64)
            )
        return (h % np.uint64(self.trace_rows)).astype(np.int64)

    def availability_prob(self, round_idx: int, ids) -> np.ndarray:
        ids = np.asarray(ids, dtype=np.int64)
        row = self._bitmap[int(round_idx) % self.trace_rounds]
        bit = np.asarray(row[self._rows(ids)], dtype=np.float64)
        return np.clip(bit, self.floor, 1.0)


def build_synthetic_trace(path: str, rounds: int, rows: int, seed: int,
                          diurnal_period: int = 24,
                          base_availability: float = 0.7,
                          diurnal_amplitude: float = 0.5) -> str:
    """Write a synthetic FedScale-shaped on/off trace bitmap: per-row
    hash phases on a thresholded diurnal wave, so the replayed traffic
    has the day/night shape real device traces show. Deterministic in
    its arguments (hash draws, no RNG state). Returns ``path``."""
    rounds, rows = int(rounds), int(rows)
    if rounds < 1 or rows < 1:
        raise ValueError(f"trace needs rounds, rows >= 1, got "
                         f"({rounds}, {rows})")
    row_ids = np.arange(rows, dtype=np.int64)
    phase = _hash01(seed, _TAG_PHASE, 0, row_ids)
    bitmap = np.empty((rounds, rows), dtype=np.uint8)
    for r in range(rounds):
        prob = np.clip(
            base_availability + diurnal_amplitude
            * np.sin(_TWO_PI * (r / max(1, diurnal_period) + phase)),
            0.0, 1.0,
        )
        bitmap[r] = (_hash01(seed, _TAG_AVAIL, r, row_ids) < prob)
    np.save(path, bitmap)
    # np.save appends .npy when absent; report the real filename
    return path if path.endswith(".npy") else path + ".npy"


def edge_crashed(seed: int, round_idx: int, num_edges: int,
                 rate: float) -> np.ndarray:
    """[num_edges] bool: which edge aggregators crash this round
    (``server.hierarchy.edge_dropout_rate``). A module-level pure
    function — hierarchy fault injection must not require the churn
    model to be enabled, and every engine/driver path that asks must
    agree bitwise (same contract as the client-level planes)."""
    if rate <= 0.0:
        return np.zeros(num_edges, dtype=bool)
    u = _hash01(seed, _TAG_EDGE, round_idx,
                np.arange(num_edges, dtype=np.int64))
    return u < rate


def build_churn_model(cfg) -> "ChurnModel | None":
    """Driver entry: the model iff ``cfg.run.churn.enabled`` (None
    otherwise — churn-off code paths must construct nothing, the
    bitwise-identity contract). ``run.churn.trace`` selects the
    trace-replay availability model (construction raises if the trace
    file is missing or malformed)."""
    if not cfg.run.churn.enabled:
        return None
    if cfg.run.churn.trace:
        return TraceChurnModel(cfg.run.churn, cfg.run.seed)
    return ChurnModel(cfg.run.churn, cfg.run.seed)
