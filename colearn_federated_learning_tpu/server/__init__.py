"""Server side: cohort sampling, aggregation, round driver (layers L3/L4)."""
