"""Byzantine adversary simulation (the attack half of the robustness
story; the defenses live in server/aggregation.py).

The robust aggregation stack (krum / median / trimmed_mean) exists to
survive *adversarial* clients, but without an attacker in the loop those
code paths can only be unit-tested against hand-crafted tensors. This
module puts a live adversary inside ``fit``: ``AttackConfig``
(config.py) selects a deterministic ``(seed)``-pure set of compromised
client ids, and per round the engines transform those clients' uploads
*inside the round program* — a ``[K]`` byzantine-mask input rides
alongside ``n_ex``, so nothing retraces and the sharded and sequential
engines stay in exact parity on attacked rounds.

Attack models, placed where a real attacker sits:

**Upload attacks** (``UPLOAD_ATTACKS``) — the compromised client
controls its wire message. Applied to the per-client delta stack after
clipping/compression (which an honest client performs as part of its
update rule) and immediately before aggregation:

- ``sign_flip`` — ``Δ ← −scale·Δ``: the scaled sign-flipping /
  gradient-reversal attack (the classic baseline in Blanchard et al.
  2017). ``scale = 1`` is the pure flip; the default boost makes
  ``f = 2/8`` reliably destroy an undefended weighted mean.
- ``gauss``     — ``Δ ← eps·N(0, I)``: noise *replacement* (the
  "Gaussian" Byzantine worker of Blanchard et al. 2017) — the upload
  carries no signal at all.
- ``scale``     — ``Δ ← scale·Δ``: model-replacement boosting
  (Bagdasaryan et al. 2020): the attacker amplifies its local update
  so it dominates the mean.
- ``alie``      — "a little is enough" (Baruch et al. 2019): the
  colluding attackers estimate the per-coordinate mean μ and std σ of
  the *honest* cohort updates and all upload ``μ − eps·σ`` — a
  perturbation small enough to hide inside the empirical spread (defeats
  naive outlier filters) yet consistently biased.

**Data attack** — ``label_flip``: the compromised clients' *training
labels* are flipped ``y → (C−1) − y`` in the host data path before the
corpus is placed (data poisoning; the upload itself is an honest
gradient of poisoned data). No engine involvement.

Config-level pairing rules (which combinations are rejected and why)
live in config.validate(); the engine-level mirror is
round_engine._check_engine_compat.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# attacks applied to the upload (engine-side); label_flip is host-side
UPLOAD_ATTACKS = ("sign_flip", "gauss", "scale", "alie")
ATTACK_KINDS = UPLOAD_ATTACKS + ("label_flip",)

# fold constant separating the gauss-attack noise streams from every
# other per-client stream family (trainer steps, qsgd dither) — MUST be
# identical in both engines (the noise is part of the parity contract)
_ATTACK_FOLD = 0xBAD5EED


def select_compromised(num_clients: int, fraction: float, seed: int) -> np.ndarray:
    """The run's compromised client ids: a deterministic pure function
    of ``(seed, num_clients, fraction)`` — the same federation attacked
    twice is attacked identically, and the sharded/sequential engines
    (and any resumed run) agree on who the adversary owns.

    ``round(fraction · N)`` clients, floored at 1 (an attack config
    with zero attackers would silently be a benign run), drawn without
    replacement and sorted for stable logging."""
    n_byz = max(1, int(round(fraction * num_clients)))
    n_byz = min(n_byz, num_clients)
    rng = np.random.default_rng((seed, 0xB12A))
    ids = rng.choice(num_clients, size=n_byz, replace=False)
    return np.sort(ids).astype(np.int64)


def flip_labels(train_y: np.ndarray, client_indices, compromised: np.ndarray,
                num_classes: int) -> np.ndarray:
    """Label-flip data poisoning: ``y → (C−1) − y`` on the compromised
    clients' shards only. Client shards are disjoint example-id sets,
    so flipping their rows in a COPY of the corpus poisons exactly the
    attackers' local datasets — honest clients (and the test set) are
    untouched."""
    out = np.array(train_y, copy=True)
    for cid in compromised:
        rows = client_indices[int(cid)]
        out[rows] = (num_classes - 1) - out[rows]
    return out


def apply_upload_attack(deltas, byz, keys, kind: str, scale: float,
                        eps: float, participation=None):
    """Transform the compromised rows of a ``[K, ...]`` stacked delta
    tree (f32). ``byz``: ``[K]`` 0/1 mask of compromised cohort slots;
    ``keys``: the cohort's ``[K]`` per-round PRNG keys (the same array
    both engines derive via ``jax.random.split(rng, K)`` — the gauss
    streams fold from them per (client, leaf), so the result is
    independent of lane/vmap blocking); ``participation``: ``[K]`` bool
    (``n_ex > 0``), required by ``alie`` to estimate honest statistics.

    Shared verbatim by the sharded engine (on the client-sharded stack,
    under jit — GSPMD inserts any cross-lane collectives), the
    sequential oracle, and the gossip engine (on local-update deltas) —
    one implementation is the parity argument."""
    if kind not in UPLOAD_ATTACKS:
        raise ValueError(f"unknown upload attack {kind!r}")
    b = (byz > 0).astype(jnp.float32)  # [K]

    def bshape(v, d):
        return v.reshape((v.shape[0],) + (1,) * (d.ndim - 1))

    if kind == "sign_flip":
        # Δ·(1 − b·(1 + scale)) == Δ honest, −scale·Δ compromised
        return jax.tree.map(
            lambda d: d * (1.0 - bshape(b, d) * (1.0 + scale)), deltas
        )
    if kind == "scale":
        return jax.tree.map(
            lambda d: d * (1.0 + bshape(b, d) * (scale - 1.0)), deltas
        )
    if kind == "gauss":
        leaves, treedef = jax.tree.flatten(deltas)
        out = []
        for i, d in enumerate(leaves):
            ks = jax.vmap(
                lambda k, i=i: jax.random.fold_in(
                    jax.random.fold_in(k, _ATTACK_FOLD), i
                )
            )(keys)
            noise = jax.vmap(
                lambda kk, s=d.shape[1:]: jax.random.normal(kk, s, jnp.float32)
            )(ks)
            out.append(jnp.where(bshape(b, d) > 0, eps * noise, d))
        return jax.tree.unflatten(treedef, out)
    # alie: per-coordinate honest mean/std → μ − eps·σ on every
    # compromised row (the colluders all send the identical message)
    part = (
        jnp.ones_like(b) if participation is None
        else (participation > 0).astype(jnp.float32)
    )
    h = part * (1.0 - b)  # honest participants
    n_h = jnp.maximum(h.sum(), 1.0)

    def leaf(d):
        hb = bshape(h, d)
        mu = (hb * d).sum(0) / n_h
        sigma = jnp.sqrt((hb * (d - mu[None]) ** 2).sum(0) / n_h)
        poisoned = mu - eps * sigma
        return jnp.where(bshape(b, d) > 0, poisoned[None], d)

    return jax.tree.map(leaf, deltas)


def stack_weighted_mean(deltas, n_ex, mode: str, params, trust=None):
    """FedAvg weighted mean over a ``[K, ...]`` stacked delta tree —
    the stacked-path twin of the engines' in-lane psum accumulation,
    used on attacked rounds (the attack transform needs the per-client
    stack, so the weighted mean runs after it). Identical jnp ops in
    both engines ⇒ attacked-round aggregation parity is exact given
    identical stacks. Result cast to the params dtype, matching the
    psum path's accumulator. ``trust``: optional ``[K]`` reputation
    weights (server/aggregation.py ``reputation_weights``) folded
    multiplicatively into the FedAvg weights — numerator and
    denominator, a true reweighted mean."""
    w = (
        n_ex.astype(jnp.float32) if mode == "examples"
        else (n_ex > 0).astype(jnp.float32)
    )
    if trust is not None:
        w = w * trust.astype(jnp.float32)
    w_sum = w.sum()
    denom = jnp.where(w_sum > 0, w_sum, 1.0)
    return jax.tree.map(
        lambda d, p: (
            jnp.einsum("k,k...->...", w, d.astype(jnp.float32)) / denom
        ).astype(p.dtype),
        deltas, params,
    )
