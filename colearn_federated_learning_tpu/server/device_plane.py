"""Device-resident control plane (``run.control_plane = "device"``).

ROADMAP item 4's residue: with the corpus HBM-resident and the round
program fused, the host still runs sampler draws, churn gating, slab
construction, and ledger slot assignment in Python between dispatches
— the control plane is host-exposed even though ``server/churn.py``'s
counter-mode SplitMix64 discipline is already a pure function of
(seed, round, id). This module lowers that control plane into the
round program itself:

- **Cohort ids** come from a tiny precomputed ``[num_rounds, K]``
  int32 table, built ONCE at driver init by running the unmodified
  host sampler over every round — so the device-mode cohorts are
  bitwise-equal to host mode by construction (the PCG64 ``rng.choice``
  draw is not XLA-lowerable; a 4-byte/round-slot table is, and it
  costs less wire than one round's index slab).
- **Churn gates** (availability / dropout hazard / crash) are evaluated
  in-program by a uint32-pair lowering of the SAME SplitMix64 chain
  ``churn.hash_u64`` computes on host. Probability gates compare the
  top-53-bit integer draw against ``ceil(p * 2**53)`` thresholds —
  exactly equivalent to the host's ``float64 u < p`` compare (``p *
  2**53`` is exact in float64 for p in [0, 1]), so realized
  availability/drop/crash bits are bitwise-equal to ``ChurnModel``.
  The diurnal probability itself involves ``np.sin``, which has no
  bitwise XLA twin — so the thresholds are precomputed on host as a
  ``[num_rounds, N]`` uint64 table (uint32 pairs on device), gathered
  per (round, id) in-program. ``config.validate`` bounds the table.
- **The index slab** is derived in-program from a device-resident
  padded shard table: epoch ``e`` of round ``r`` reads client ``c``'s
  shard rotated by ``hash_u64(seed, ORDER, r, c*E + e) % len(c)`` —
  a seed-pure rotation that (a) preserves the contiguous-head padding
  invariant the engines' mask-spec reconstruction relies on and (b)
  covers every example of every shard across rounds. This is a
  DIFFERENT (documented) data order than the host path's PCG64
  shuffle: cohorts, churn gates, specs, and weights are bitwise-equal
  to host mode, but per-batch example composition is the device
  plane's own discipline — ``reference_schedule`` below is its exact
  NumPy twin and the parity oracle the jnp program is pinned against.
- **Crash work fractions** use the shared integer formula ``done =
  max(1, ((2**53 - k53) * steps) >> 53)`` in both the NumPy reference
  and the jnp program (the host float path ``floor(frac * steps)``
  can differ from it only when float64 rounding crosses an integer
  boundary — probability ~2**-43 per draw; the realized *crash bit*
  is always bitwise-equal).

Everything here is pure in (seed, round): resume from any checkpoint
re-derives the identical schedule with zero checkpoint state, and the
fused scan body can derive each sub-round's schedule itself so host
I/O collapses to flush boundaries.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional

import numpy as np

from colearn_federated_learning_tpu.server.churn import (
    _TAG_AVAIL,
    _TAG_CRASH,
    _TAG_DROP,
    _TAG_FRAC,
    _TAG_ORDER,
    ChurnModel,
    hash_k53,
    hash_u64,
    threshold_u53,
)

_MASK32 = 0xFFFFFFFF
_U64 = np.uint64


# ---------------------------------------------------------------------------
# plan: everything static the device program needs, built once at init
# ---------------------------------------------------------------------------


@dataclass
class DevicePlan:
    """Static schedule inputs for the device control plane. Arrays are
    host NumPy; the driver ships them to HBM once (uint64 tables as
    uint32 pairs — XLA has no uint64 on the default build). Total
    footprint: ``R*K + N*Lmax + 3N`` int32 plus ``2*R*N`` uint32 when
    churn is on (bounded by config.validate)."""

    seed: int
    num_clients: int
    cohort: int
    num_rounds: int
    local_epochs: int
    steps_per_epoch: int
    batch: int
    steps: int
    cap: int
    churn: bool
    dropout_thr: int  # ceil(p * 2**53) integer gate thresholds
    crash_thr: int
    cohort_table: np.ndarray  # [R, K] int32
    shard_table: np.ndarray   # [N, Lmax] int32, rows zero-padded
    shard_len: np.ndarray     # [N] int32
    take: np.ndarray          # [N] int32 = min(len, cap)
    avail_thr: Optional[np.ndarray]  # [R, N] uint64 (None without churn)


def build_device_plan(fed, shape, sample_fn: Callable[[int], np.ndarray],
                      churn: Optional[ChurnModel], seed: int,
                      num_rounds: int) -> DevicePlan:
    """Build the device plan: run the (unmodified) host sampler over
    every round for the cohort table, pad the shard index lists into
    one gatherable matrix, and precompute the churn availability
    thresholds. Pure in (seed, config) — rebuilt identically on
    resume."""
    n = int(fed.num_clients)
    steps = int(shape.steps)
    if steps > 2048:
        raise ValueError(
            f"control_plane='device' supports steps <= 2048 (crash "
            f"fraction fixed-point bound), got {steps}"
        )
    if n * shape.local_epochs >= 1 << 31:
        raise ValueError(
            "control_plane='device': num_clients * local_epochs must "
            "fit int32 for the rotation hash key"
        )
    cohorts = np.stack([
        np.asarray(sample_fn(r), np.int64) for r in range(num_rounds)
    ])
    if cohorts.size and (cohorts.min() < 0 or cohorts.max() >= n):
        raise ValueError(
            "control_plane='device' requires cohort ids in [0, "
            f"num_clients); sampler drew outside [0, {n})"
        )
    shards = [np.asarray(fed.client_indices[c], np.int64) for c in range(n)]
    lens = np.array([len(s) for s in shards], np.int64)
    if (lens < 1).any():
        raise ValueError(
            "control_plane='device' requires non-empty client shards "
            "(rotation is modulo the shard length)"
        )
    lmax = int(lens.max())
    shard_table = np.zeros((n, lmax), np.int32)
    for c, s in enumerate(shards):
        shard_table[c, : len(s)] = s.astype(np.int32)
    take = np.minimum(lens, int(shape.cap)).astype(np.int32)
    avail_thr = None
    dropout_thr = crash_thr = 0
    if churn is not None:
        ids = np.arange(n, dtype=np.int64)
        avail_thr = np.stack([
            threshold_u53(churn.availability_prob(r, ids))
            for r in range(num_rounds)
        ])
        dropout_thr = int(threshold_u53(churn.dropout_hazard))
        crash_thr = int(threshold_u53(churn.crash_rate))
    return DevicePlan(
        seed=int(seed), num_clients=n, cohort=int(cohorts.shape[1]),
        num_rounds=int(num_rounds), local_epochs=int(shape.local_epochs),
        steps_per_epoch=int(shape.steps_per_epoch),
        batch=int(shape.batch_size), steps=steps, cap=int(shape.cap),
        churn=churn is not None, dropout_thr=dropout_thr,
        crash_thr=crash_thr, cohort_table=cohorts.astype(np.int32),
        shard_table=shard_table, shard_len=lens.astype(np.int32),
        take=take, avail_thr=avail_thr,
    )


def plan_arrays(plan: DevicePlan) -> Dict[str, np.ndarray]:
    """The plan's device-resident tensors, uint64 tables split into
    (hi, lo) uint32 pairs. The driver device_puts this dict once."""
    arrs = {
        "cohort_table": plan.cohort_table,
        "shard_table": plan.shard_table,
        "shard_len": plan.shard_len,
        "take": plan.take,
    }
    if plan.avail_thr is not None:
        arrs["avail_hi"] = (plan.avail_thr >> _U64(32)).astype(np.uint32)
        arrs["avail_lo"] = (plan.avail_thr & _U64(_MASK32)).astype(np.uint32)
    return arrs


# ---------------------------------------------------------------------------
# shared integer disciplines (NumPy side)
# ---------------------------------------------------------------------------


def crash_done_steps(k_frac: np.ndarray, steps: int) -> np.ndarray:
    """Steps completed before a crash, from the raw 53-bit fraction
    draw: ``max(1, ((2**53 - k53) * steps) >> 53)`` — pure integer
    math, shared verbatim (as a uint32-pair program) by the device
    twin. ``steps <= 2048`` keeps the product inside uint64."""
    k = np.asarray(k_frac, _U64)
    with np.errstate(over="ignore"):
        m = _U64(1 << 53) - k
        done = (m * _U64(steps)) >> _U64(53)
    return np.maximum(_U64(1), done).astype(np.int64)


def _rotation_offsets(seed: int, round_idx: int, cohort: np.ndarray,
                      epochs: int, lens: np.ndarray) -> np.ndarray:
    """[K, E] rotation offset per (cohort member, epoch): low 32 hash
    bits mod the shard length (uint32 modulo — the device twin's
    native width)."""
    keys = (cohort.astype(np.int64)[:, None] * epochs
            + np.arange(epochs, dtype=np.int64)[None, :])
    h = hash_u64(seed, _TAG_ORDER, round_idx, keys.reshape(-1))
    lo = (h & _U64(_MASK32)).reshape(len(cohort), epochs)
    return (lo % lens.astype(_U64)[:, None]).astype(np.int64)


def reference_schedule(plan: DevicePlan, round_idx: int) -> Dict[str, np.ndarray]:
    """Exact NumPy twin of the in-program schedule derivation — the
    parity oracle ``device_schedule`` is test-pinned against, and the
    host-side schedule source when the driver needs one under device
    mode (unfused catch-up, tests). Returns cohort [K] i32, idx
    [K, steps, batch] i32, spec [K, 2] i32, n_ex [K] f32, and the
    realized churn stats (unavailable / dropped / crashed counts)."""
    r = int(round_idx)
    k = plan.cohort
    epochs, spe, batch = plan.local_epochs, plan.steps_per_epoch, plan.batch
    per_epoch = spe * batch
    cohort = plan.cohort_table[r].astype(np.int64)
    take = plan.take[cohort].astype(np.int64)
    lens = plan.shard_len[cohort].astype(np.int64)

    # -- churn gates (bitwise == ChurnModel via integer thresholds) --
    offline = np.zeros(k, bool)
    hazard = np.zeros(k, bool)
    crashed = np.zeros(k, bool)
    vsteps = np.full(k, plan.steps, np.int64)
    if plan.churn:
        offline = ~(hash_k53(plan.seed, _TAG_AVAIL, r, cohort)
                    < plan.avail_thr[r, cohort])
        hazard = hash_k53(plan.seed, _TAG_DROP, r, cohort) \
            < _U64(plan.dropout_thr)
        crashed = hash_k53(plan.seed, _TAG_CRASH, r, cohort) \
            < _U64(plan.crash_thr)
        if crashed.any():
            done = crash_done_steps(
                hash_k53(plan.seed, _TAG_FRAC, r, cohort), plan.steps
            )
            vsteps = np.where(crashed, np.minimum(vsteps, done), vsteps)

    # -- spec + weights (host closed form, integer math) --
    spec = np.stack([take, vsteps], axis=1).astype(np.int32)
    total = np.zeros(k, np.int64)
    for e in range(epochs):
        avail = np.clip(vsteps - e * spe, 0, spe)
        total += np.minimum(take, avail * batch)
    n_ex = np.where(offline | hazard, 0.0, total.astype(np.float32))
    n_ex = n_ex.astype(np.float32)

    # -- index slab: rotated shard reads, contiguous-head padding --
    off = _rotation_offsets(plan.seed, r, cohort, epochs, lens)
    pos = np.arange(per_epoch, dtype=np.int64)
    col = (off[:, :, None] + pos[None, None, :]) % lens[:, None, None]
    vals = plan.shard_table[cohort[:, None, None], col]
    idx = np.where(pos[None, None, :] < take[:, None, None], vals, 0)
    idx = idx.astype(np.int32).reshape(k, plan.steps, batch)

    return {
        "cohort": cohort.astype(np.int32),
        "idx": idx,
        "spec": spec,
        "n_ex": n_ex,
        "unavailable": int(offline.sum()),
        "dropped": int((hazard & ~offline).sum()),
        "crashed": int(crashed.sum()),
    }


# ---------------------------------------------------------------------------
# uint32-pair SplitMix64 (jnp lowering of churn.hash_u64)
# ---------------------------------------------------------------------------
#
# XLA's default build has no uint64, so the 64-bit hash chain runs as
# (hi, lo) uint32 pairs: wide 32x32 multiply via 16-bit limbs, add with
# carry, cross-pair shifts. Pinned bitwise against churn.hash_u64 by
# tests/test_device_plane.py.


def _pair_const(c, jnp):
    c = int(c) & 0xFFFFFFFFFFFFFFFF
    return jnp.uint32(c >> 32), jnp.uint32(c & _MASK32)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(lo.dtype)
    return ah + bh + carry, lo


def _sub64(ah, al, bh, bl):
    lo = al - bl
    borrow = (al < bl).astype(al.dtype)
    return ah - bh - borrow, lo


def _mul32_wide(a, b):
    a0, a1 = a & 0xFFFF, a >> 16
    b0, b1 = b & 0xFFFF, b >> 16
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> 16) + (p01 & 0xFFFF) + (p10 & 0xFFFF)
    lo = (p00 & 0xFFFF) | ((mid & 0xFFFF) << 16)
    hi = p11 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)
    return hi, lo


def _mul64(ah, al, bh, bl):
    # (ah*2^32 + al) * (bh*2^32 + bl) mod 2^64
    hi, lo = _mul32_wide(al, bl)
    return hi + al * bh + ah * bl, lo


def _shr64(h, l, n: int):
    # 0 < n < 32 (the splitmix shifts are 30 / 27 / 31)
    return h >> n, (l >> n) | (h << (32 - n))


def _splitmix64_pair(h, l, jnp):
    gh, gl = _pair_const(0x9E3779B97F4A7C15, jnp)
    h, l = _add64(h, l, gh, gl)
    xh, xl = _shr64(h, l, 30)
    h, l = h ^ xh, l ^ xl
    h, l = _mul64(h, l, *_pair_const(0xBF58476D1CE4E5B9, jnp))
    xh, xl = _shr64(h, l, 27)
    h, l = h ^ xh, l ^ xl
    h, l = _mul64(h, l, *_pair_const(0x94D049BB133111EB, jnp))
    xh, xl = _shr64(h, l, 31)
    return h ^ xh, l ^ xl


def hash_u64_pair(seed: int, tag, round_idx, ids_lo, jnp):
    """jnp twin of ``churn.hash_u64`` for non-negative 32-bit ids:
    ``round_idx`` is a traced uint32 scalar (or [F] vector under the
    fused vmap), ``ids_lo`` a uint32 array. Returns (hi, lo) uint32."""
    z = jnp.uint32(0)
    sh, sl = _pair_const(int(seed) ^ int(tag), jnp)
    h, l = _splitmix64_pair(
        jnp.broadcast_to(sh, ()), jnp.broadcast_to(sl, ()), jnp
    )
    h, l = _add64(h, l, z, round_idx.astype(jnp.uint32))
    h, l = _splitmix64_pair(h, l, jnp)
    ih, il = _splitmix64_pair(
        jnp.zeros_like(ids_lo), ids_lo.astype(jnp.uint32), jnp
    )
    h, l = h ^ ih, l ^ il
    return _splitmix64_pair(h, l, jnp)


def _k53_pair(h, l):
    # top 53 bits as a (21-bit hi, 32-bit lo) pair: (h:l) >> 11
    return h >> 11, (l >> 11) | (h << 21)


def _lt_pair(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al < bl))


def _k53_lt_const(kh, kl, thr: int, jnp):
    th = jnp.uint32((int(thr) >> 32) & _MASK32)
    tl = jnp.uint32(int(thr) & _MASK32)
    return _lt_pair(kh, kl, th, tl)


def make_schedule_fn(plan: DevicePlan):
    """The in-program schedule derivation: a pure jnp function
    ``schedule(arrays, round_idx) -> dict`` with the plan's statics
    closed over. ``round_idx`` is a traced int32 scalar, so ONE
    compiled program serves every round (and the fused path vmaps it
    over the chunk's round vector). Output is bitwise-equal to
    ``reference_schedule`` (test-pinned)."""
    import jax.numpy as jnp

    seed = plan.seed
    k = plan.cohort
    epochs, spe, batch = plan.local_epochs, plan.steps_per_epoch, plan.batch
    steps, per_epoch = plan.steps, plan.steps_per_epoch * plan.batch

    def schedule(arrays, round_idx):
        r = round_idx.astype(jnp.int32)
        ru = r.astype(jnp.uint32)
        cohort = arrays["cohort_table"][r]  # [K] i32
        cu = cohort.astype(jnp.uint32)
        take = arrays["take"][cohort].astype(jnp.int32)
        lens = arrays["shard_len"][cohort].astype(jnp.uint32)

        offline = jnp.zeros((k,), bool)
        hazard = jnp.zeros((k,), bool)
        crashed = jnp.zeros((k,), bool)
        vsteps = jnp.full((k,), steps, jnp.int32)
        if plan.churn:
            ah, al = hash_u64_pair(seed, _TAG_AVAIL, ru, cu, jnp)
            kh, kl = _k53_pair(ah, al)
            t_hi = arrays["avail_hi"][r, cohort]
            t_lo = arrays["avail_lo"][r, cohort]
            offline = ~_lt_pair(kh, kl, t_hi, t_lo)
            dh, dl = hash_u64_pair(seed, _TAG_DROP, ru, cu, jnp)
            hazard = _k53_lt_const(*_k53_pair(dh, dl), plan.dropout_thr, jnp)
            ch, cl = hash_u64_pair(seed, _TAG_CRASH, ru, cu, jnp)
            crashed = _k53_lt_const(*_k53_pair(ch, cl), plan.crash_thr, jnp)
            fh, fl = hash_u64_pair(seed, _TAG_FRAC, ru, cu, jnp)
            fkh, fkl = _k53_pair(fh, fl)
            # done = max(1, ((2^53 - k53) * steps) >> 53): the shared
            # integer crash-fraction discipline (crash_done_steps)
            mh, ml = _sub64(jnp.full((k,), 1 << 21, jnp.uint32),
                            jnp.zeros((k,), jnp.uint32), fkh, fkl)
            ph, _pl = _mul64(mh, ml, jnp.zeros((k,), jnp.uint32),
                             jnp.full((k,), steps, jnp.uint32))
            done = jnp.maximum(1, (ph >> 21).astype(jnp.int32))
            vsteps = jnp.where(crashed, jnp.minimum(vsteps, done), vsteps)

        spec = jnp.stack([take, vsteps], axis=1).astype(jnp.int32)
        total = jnp.zeros((k,), jnp.int32)
        for e in range(epochs):
            avail = jnp.clip(vsteps - e * spe, 0, spe)
            total = total + jnp.minimum(take, avail * batch)
        n_ex = jnp.where(offline | hazard, jnp.float32(0.0),
                         total.astype(jnp.float32))

        # rotation slab: epoch e reads the shard rotated by the
        # seed-pure per-(round, client, epoch) offset
        ekeys = (cu[:, None] * jnp.uint32(epochs)
                 + jnp.arange(epochs, dtype=jnp.uint32)[None, :])
        oh, ol = hash_u64_pair(seed, _TAG_ORDER, ru, ekeys, jnp)
        del oh
        off = ol % lens[:, None]  # [K, E] uint32
        pos = jnp.arange(per_epoch, dtype=jnp.uint32)
        col = (off[:, :, None] + pos[None, None, :]) % lens[:, None, None]
        vals = arrays["shard_table"][cohort[:, None, None],
                                     col.astype(jnp.int32)]
        idx = jnp.where(
            pos.astype(jnp.int32)[None, None, :] < take[:, None, None],
            vals, 0,
        ).astype(jnp.int32).reshape(k, steps, batch)

        return {
            "cohort": cohort,
            "idx": idx,
            "spec": spec,
            "n_ex": n_ex,
            "unavailable": offline.sum().astype(jnp.int32),
            "dropped": (hazard & ~offline).sum().astype(jnp.int32),
            "crashed": crashed.sum().astype(jnp.int32),
        }

    return schedule
