"""Experiment configuration system (SURVEY.md §2 C2, layer L5).

Typed dataclass configs + YAML files + the five named BASELINE configs
(BASELINE.json:7-11). ``colearn fit --config <name-or-path>`` resolves a
name through :func:`get_named_config` or loads a YAML file; dotted CLI
overrides (``--set server.num_rounds=5``) mutate fields after load.

Everything that affects traced XLA shapes (cohort size, local steps,
batch size, pad length) is pinned here so a config change — not runtime
data — is the only thing that can trigger recompilation.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import yaml


@dataclass
class LoRAConfig:
    """LoRA adapter plane (``model.lora``, models/lora.py — ROADMAP
    item 3): freeze the transformer base and train/ship/aggregate ONLY
    rank-r adapter pairs. Every targeted dense kernel ``W [d_in,
    d_out]`` gains ``A [d_in, r]`` / ``B [r, d_out]`` and the
    effective weight is ``W + (alpha/r)·A·B`` (``B`` starts at zero,
    so the merged model initially equals the base). The params pytree
    the whole round stack sees (engines, aggregation — weighted_mean
    AND krum/median over flattened factors — compression, upload
    attacks, the forensic ledger's norm/cosine stats, reputation,
    checkpoints, wire counters) IS the adapter set, so every subsystem
    operates in adapter space by construction and the per-client
    upload drops ~d/(2r) per target (the realized ratio is logged as
    ``wire_reduction_vs_full`` in the round counters, ``run_summary``,
    and bench extras). Eval and export run against the merged model.
    The frozen base params are a pure function of ``run.seed`` (the
    init rng) — re-derived on resume, never checkpointed or shipped
    (the one-time base broadcast is out of the per-round wire model,
    like any deployed-base LoRA system). Supported model families:
    ``bert_tiny``, ``vit_b16`` (the transformer-block injection map);
    other zoo members are rejected with a clear error. With
    ``enabled=false`` no wrapper is constructed anywhere and runs are
    bitwise-identical to pre-LoRA builds (test-pinned)."""

    enabled: bool = False
    # adapter rank r (must be < min(d_in, d_out) of every target kernel
    # — checked at model construction with the offending kernel named)
    rank: int = 4
    # merge scale numerator: the effective weight is W + (alpha/r)·A·B
    # (Hu et al.'s parameterization — tune lr and alpha together)
    alpha: float = 8.0
    # which dense kernels inside each transformer block get adapters:
    #   attention — the fused qkv projection + the attention output
    #   mlp       — the MLP in/out projections
    #   all       — both sets
    target: str = "attention"


@dataclass
class ModelConfig:
    name: str = "lenet5"
    num_classes: int = 10
    # model-family extras (e.g. vocab_size / seq_len for LMs, image_size)
    kwargs: Dict[str, Any] = field(default_factory=dict)
    # LoRA adapter plane — see LoRAConfig.
    lora: LoRAConfig = field(default_factory=LoRAConfig)


@dataclass
class StoreConfig:
    """On-disk memory-mapped client store (``data.store``, data/store.py
    — ROADMAP item 1, the million-client data path). With ``dir`` set,
    the training corpus comes from fixed-record binary shards plus a
    small per-client offset/length index built by ``colearn store
    build``: example bytes stay on disk behind ``np.memmap`` views, the
    per-client partition IS the store's index (``data.partition`` /
    synthetic knobs are ignored — they were baked in at build time),
    and the host pipeline gathers only the sampled cohort's records
    into each round's slab. Pair with ``data.placement="stream"`` for
    the O(cohort) host-RAM path (``"hbm"`` still works — the whole
    store is materialized to device once, for small stores / big
    chips). Store-backed runs are BITWISE-equal to the in-memory run
    the store was converted from, on the same seed (test-pinned across
    engines and fuse_rounds). ``data.num_clients`` must match the
    store's client count (checked with a clear error). Rejected
    pairings: ``attack.kind="label_flip"`` (poisons labels host-side;
    the store is a read-only mmap) and ``run.host_pipeline="native"``
    (the C++ pipeline materializes the per-client index lists;
    ``"auto"`` degrades to NumPy)."""

    # store directory ("" = off, classic in-memory data path)
    dir: str = ""
    # load the whole store into plain host arrays and run the classic
    # in-memory path — the "in-memory twin" for store↔in-memory parity
    # checks; only sensible for stores that fit in RAM
    materialize: bool = False
    # parallel shard-gather pool width (data/store.py): a slab's row
    # set is split by owning shard and the per-shard mmap copies run
    # concurrently on a shared worker pool. 0 = auto (min(4, cores)),
    # 1 = serial, N = exactly N threads. Deterministic at EVERY
    # setting — workers write disjoint output rows, so the gathered
    # bytes never depend on the worker count (test-pinned).
    gather_workers: int = 0
    # bounded reassembly buffer (MB) for store-backed federated /
    # personalized eval: eval batches stream through the contiguous
    # client-index ranges in bounded multi-client slabs instead of
    # materializing a transient per-client arange gather each —
    # bitwise-identical metrics, O(buffer) host residency.
    eval_buffer_mb: int = 256


@dataclass
class DataConfig:
    name: str = "mnist"
    num_clients: int = 2
    partition: str = "iid"  # iid | dirichlet | natural | silo
    dirichlet_alpha: float = 0.5
    data_dir: str = "~/.cache/colearn_data"
    # When real dataset files are absent (this sandbox has zero egress),
    # fall back to a deterministic synthetic dataset with the same
    # shapes/cardinality so every config stays runnable end-to-end.
    synthetic_fallback: bool = True
    synthetic_train_size: int = 2048
    synthetic_test_size: int = 512
    # Synthetic image SNR: x = w·class_template + (1−w)·noise. 0.7 is an
    # easy task (saturates at acc 1.0 — right for smoke tests); the
    # convergence regression lowers it so the plateau sits strictly
    # below 1.0 and a mid-curve band can catch subtle aggregation drift.
    synthetic_template_weight: float = 0.7
    # Synthetic task family (VERDICT r4 weak-#4 — one family can't
    # catch structure-sensitive regressions):
    #   template    — x = w·T_class + (1−w)·noise; linearly separable
    #                 (class means recover it), the fast smoke default.
    #   template_pair — x superposes TWO templates, label = (a+b) mod
    #                 C: spatially structured (convnet-learnable) but a
    #                 linear model's additive scores cap far below the
    #                 ceiling; pair with synthetic_label_noise for a
    #                 strict ceiling below 1.
    synthetic_task: str = "template"  # template | template_pair
    # template_pair only: fraction of labels flipped uniformly at random
    synthetic_label_noise: float = 0.0
    # Cap on examples a client contributes per round (static-shape pad target;
    # 0 = derive from the largest client shard).
    max_examples_per_client: int = 0
    # Where the training corpus lives during the round loop:
    #   hbm    — whole corpus uploaded once, rounds gather on device
    #            (fastest; requires the corpus to fit in device memory)
    #   stream — corpus stays in host RAM; each round only the cohort's
    #            examples are gathered into a slab and uploaded, with the
    #            index tensors remapped into it. Unlocks corpora larger
    #            than HBM (e.g. real ImageNet at 224px) at the cost of a
    #            per-round host→device transfer.
    placement: str = "hbm"  # hbm | stream
    # On-disk mmap client store — see StoreConfig.
    store: StoreConfig = field(default_factory=StoreConfig)


@dataclass
class ClientConfig:
    local_epochs: int = 1
    batch_size: int = 32
    optimizer: str = "sgd"  # sgd | adamw
    lr: float = 0.1
    # per-round multiplicative LR decay: round r trains at lr·decay^r
    # (1.0 = constant). Computed inside the compiled round program from
    # the server state's round counter — no retracing.
    lr_decay: float = 1.0
    momentum: float = 0.9
    weight_decay: float = 0.0
    # FedProx proximal coefficient μ (0.0 == plain FedAvg local training)
    prox_mu: float = 0.0


@dataclass
class ReputationConfig:
    """Reputation-weighted aggregation (``server.reputation``,
    server/aggregation.py ``reputation_weights``): the closed control
    loop over the per-client forensic ledger. Each round program
    converts every cohort member's ledger row — cumulative flag rate
    ``flagged/count`` and the norm/cosine robust-z EMA — into a
    multiplicative TRUST weight in ``[floor, 1]``:

        score = flag_rate + z_gain * max(ema_z/zmax - 1, 0)
        trust = floor + (1 - floor) * exp(-strength * score)

    (unseen clients — ``count == 0`` — get trust exactly 1, so
    reputation never suppresses a client before the ledger has
    evidence). The trust is computed INSIDE the round program from the
    device-resident ``[num_clients, LEDGER_WIDTH]`` ledger carried from
    the PREVIOUS rounds (this round's stats update lands after
    aggregation), so the single-psum weighted-mean path stays host-free
    and under ``run.fuse_rounds`` the trust derives from the fused scan
    carry. Where it applies:

    - ``aggregator="weighted_mean"``: the FedAvg weight becomes
      ``w_i · trust_i`` (numerator AND denominator — a true reweighted
      mean; the reported ``train_loss`` is the same trust-weighted
      mean). This is the soft complement to krum's hard rejection:
      near ``f ≈ K/2`` krum's selection guarantee is void (the
      Blanchard bound 2f+2 < n cannot be satisfied) while the
      reputation-weighted mean degrades the attackers' mass gradually
      as ledger evidence accumulates.
    - robust aggregators (median/trimmed_mean/krum): order statistics
      are unweighted by design, so trust instead SCALES each client's
      delta (``trust_i · Δ_i``) before the reduction — a suppressed
      client's upload shrinks toward the zero update rather than being
      ejected, so false flags cost a fraction of one update instead of
      a cohort slot.

    Requires ``run.obs.client_ledger.enabled`` (trust is a function of
    the ledger); the ledger's pairing exclusions (secure aggregation,
    client-level DP, gossip/fedbuff, scaffold/feddyn) therefore apply
    verbatim — see ClientLedgerConfig for the reasons. With
    ``enabled=false`` (default) no trust input exists anywhere and runs
    are bitwise-identical to pre-reputation builds."""

    enabled: bool = False
    # minimum trust weight: a fully-flagged client keeps this fraction
    # of its voice (soft weighting — never a hard zero, so a falsely
    # accused client can still earn its reputation back)
    floor: float = 0.05
    # exponential decay rate of trust in the anomaly score; flag_rate=1
    # drives trust to ~floor + (1-floor)*exp(-strength)
    strength: float = 6.0
    # weight of the z-history term: only the part of the EMA'd robust z
    # ABOVE the flag threshold (ema_z/zmax - 1) contributes, so honest
    # clients' sub-threshold z noise never erodes their trust
    z_gain: float = 1.0


@dataclass
class AdaptiveSamplerConfig:
    """Knobs for ``server.sampling="adaptive"`` (server/sampler.py):
    Oort-style utility-aware cohort selection (Lai et al., OSDI'21)
    scored from the client ledger's periodic host-side snapshots. Per
    client the score is

        util      = ema_loss (unseen clients: the max seen utility —
                    optimistic initialization, explore-eagerly)
        staleness = 1 + staleness_gain * max(expected - count, 0)
                    / max(expected, 1),  expected = round * K / N
        score     = (util + eps) * staleness * exp(-flag_suppress
                    * flag_rate)

    and the draw probabilities are ``(1 - explore) * score/Σscore +
    explore/N`` — the exploration floor keeps every client drawable
    forever. The snapshot refreshes from the device-resident ledger at
    ``run.obs.client_ledger.log_every`` round boundaries (one host
    fetch per refresh, logged as the same ``client_ledger`` JSONL
    record), so the cohort for round ``r`` is a pure function of
    ``(seed, r, ledger_snapshot)`` and a resumed run replays the exact
    straight-run schedule — the active snapshot rides the checkpoint.
    See DataConfig/RunConfig pairing rejections in ``validate()``."""

    # fraction of each draw's probability mass spread uniformly over
    # ALL clients (the exploration floor; must be in (0, 1])
    explore: float = 0.1
    # boost for under-sampled clients (participation deficit vs the
    # uniform expectation) — Oort's staleness/fairness term
    staleness_gain: float = 1.0
    # exponential suppression of high-flag-rate clients in the draw
    # probabilities (the selection-side twin of reputation weighting)
    flag_suppress: float = 4.0
    # sampling="streaming" only: max rows in the compact adaptive-score
    # sketch (the columnar {id, count, flagged, ema_loss} table the
    # streaming draw scores from). When more clients than this have
    # ledger evidence, the highest-participation rows are kept; clients
    # outside the sketch draw from the closed-form optimistic unseen
    # pool. Bounds the sampler's host memory and checkpoint footprint
    # regardless of num_clients.
    sketch_size: int = 4096


@dataclass
class HierarchyConfig:
    """Two-tier (device → edge → core) federation (``server.hierarchy``,
    server/round_driver.py). ``num_edges = E > 0`` splits the client
    universe into E deterministic contiguous blocks (client ``i``
    belongs to edge ``i·E // num_clients``); each edge aggregator runs
    the EXISTING round program over a cohort drawn from its own block
    (per-edge deterministic samplers), and the core round aggregates the
    E edge deltas — the engine reused recursively, one tier down.

    Per-tier robust aggregation composes: ``server.aggregator`` is the
    EDGE tier's defense (e.g. krum over each edge's cohort) and
    ``core_aggregator`` the core tier's (e.g. the reputation-weighted
    mean over edge deltas) — a compromised edge is degraded at the core
    even when its in-edge defense was overwhelmed. Edge-dropout fault
    injection (``edge_dropout_rate``) crashes whole edges with a
    seed-pure per-(round, edge) hash draw: a crashed edge's delta is
    EXCLUDED from the core aggregate and counted
    (``hier_edge_crashed``), never NaN-poisoning the core.

    Under ``algorithm="fedbuff"`` the hierarchy rides the async
    scheduler instead: each popped completion is grouped by its
    client's edge, a crashed edge's completions are excluded for that
    server step, and per-edge trust (``core_aggregator="reputation"``)
    multiplies the staleness-decayed weights — per-tier absorbed/
    staleness accounting lands in round records and run_summary.

    Sync-path pairing restrictions live in ``validate()`` with reasons
    (stateful algorithms, secure aggregation, DP accounting, the client
    ledger, stream placement, fused rounds — each assumes exactly one
    cohort dispatch per round). ``num_edges = 0`` constructs nothing
    and is bitwise-identical to the flat plane (test-pinned)."""

    # number of edge aggregators; 0 = hierarchy off (the flat plane)
    num_edges: int = 0
    # core-tier aggregation over the [E] stacked edge deltas:
    #   mean        — participation-weighted mean (crashed edges excluded)
    #   median | trimmed_mean | krum — the robust_reduce order
    #                 statistics, one tier up (sync path only)
    #   reputation  — trust-weighted mean; per-edge trust is an EMA of
    #                 the edge's crash/alive history (edge_trust rides
    #                 the checkpoint, so resume replays core weights)
    core_aggregator: str = "mean"
    # trimmed_mean core only: fraction trimmed from each side
    core_trim_ratio: float = 0.1
    # krum core only: assumed Byzantine edge count f
    core_krum_byzantine: int = 0
    # core_aggregator="reputation" only: EMA rate of the per-edge trust
    # update trust ← (1-decay)·trust + decay·alive
    core_trust_decay: float = 0.25
    # per-(round, edge) probability that an edge aggregator crashes for
    # that round (seed-pure hash draw; its delta is excluded + counted)
    edge_dropout_rate: float = 0.0


@dataclass
class ServerConfig:
    num_rounds: int = 10
    cohort_size: int = 2
    eval_every: int = 1
    checkpoint_every: int = 0  # 0 = only at end
    # Server-side optimizer applied to the aggregated delta:
    #   mean (plain FedAvg) | fedavgm (server momentum) | fedadam | fedyogi
    optimizer: str = "mean"
    server_lr: float = 1.0
    server_momentum: float = 0.9
    # Cohort delta aggregation:
    #   weighted_mean — FedAvg's example-weighted mean (single psum)
    #   median | trimmed_mean — coordinate-wise Byzantine-robust
    #   statistics over per-client deltas (unweighted by design; costs
    #   K× the aggregation memory of the psum path)
    #   krum — whole-update selection (Blanchard et al. 2017): keep the
    #   one delta closest to its m−f−2 nearest neighbours
    aggregator: str = "weighted_mean"
    # fraction trimmed from EACH side per coordinate (trimmed_mean only)
    trim_ratio: float = 0.1
    # krum only: assumed number of Byzantine clients f (neighbour count
    # = participants − f − 2, clamped ≥ 1)
    krum_byzantine: int = 0
    # Client-update (uplink) compression applied to each client's delta
    # BEFORE aggregation — simulates communication-constrained FL:
    #   "" (off) | topk (keep top fraction by magnitude per tensor)
    #   | qsgd (unbiased stochastic quantization, Alistarh et al. 2017)
    compression: str = ""
    compression_topk_ratio: float = 0.01
    compression_qsgd_levels: int = 256
    # topk thresholds leaves ≥ 2×65536 coords from a strided sampled
    # quantile (selected count within ±10% of k; see ops/compression.py).
    # True restores the exact full-sort threshold — 10× the training
    # step's device time on ResNet-18-sized models (BASELINE.md r4/r5).
    compression_topk_exact: bool = False
    # Error-feedback compression memory (EF-SGD family — Seide et al.
    # 2014, Stich et al. 2018): each client keeps a persistent
    # params-shaped residual eᵢ in the device-resident per-client state
    # store (same [N, ...] mesh-sharded plumbing as scaffold); per round
    # the upload is C(Δᵢ + eᵢ) and eᵢ⁺ = Δᵢ + eᵢ − C(Δᵢ + eᵢ), which
    # de-biases sparse compressors (every coordinate top-k drops is
    # retried until it ships). Requires `compression`; incompatible with
    # stateful algorithms (one store per run), robust aggregators
    # (history-dependent uploads have no order-statistic semantics),
    # secure_aggregation and client-level DP (the memory breaks the
    # per-round upload norm bound their analyses need). HBM budget =
    # N·|params| at client_state_dtype, sharded over lanes.
    error_feedback: bool = False
    # Clip each client's delta to this L2 norm (whole-tree) before
    # aggregation — the standard heterogeneity stabilizer (and DP-SGD's
    # clipping step without the noise). 0 = off.
    clip_delta_norm: float = 0.0
    # algorithm=fedbuff only: client train durations are 1..S server
    # steps (S = async_max_staleness); the pop-K-earliest-finish queue
    # discipline bounds realized staleness by 2S, which sizes the
    # on-device params-history ring (2S+1 versions). In-flight
    # concurrency = cohort_size × S.
    async_max_staleness: int = 4
    # staleness decay exponent α: aggregation weight × (1+s)^-α
    async_staleness_exponent: float = 0.5
    # fedbuff overload backpressure: cap on the COMPLETED-but-unpopped
    # backlog beyond the K updates each server step absorbs. Under
    # churn, offline clients defer completions and the backlog can
    # spike when a diurnal wave brings a cohort back online; entries
    # beyond the cap are shed per async_overload_policy, re-queued as
    # fresh arrivals at the current version (their in-flight work is
    # discarded — counted in round records and run_summary). 0 = no
    # cap (every completion waits its turn, staleness absorbs the
    # backlog instead).
    async_backlog_cap: int = 0
    # which completions are shed at the cap:
    #   drop_oldest  — shed the STALEST waiting completions (bound the
    #                  staleness tail; the freshest work survives)
    #   reject_newest — shed the most recent completions (FIFO
    #                  admission; the oldest waiters keep their slot)
    async_overload_policy: str = "drop_oldest"  # drop_oldest | reject_newest
    # algorithm=fedbuff only: number of CONCURRENT model versions
    # ("lines"), each with its own in-flight buffer, params trajectory,
    # and 2S+1 history ring. Server steps round-robin over the lines
    # (round r drives line r mod V at line-local version r div V); the
    # availability-aware pop routes each completion to the line it was
    # admitted by, and staleness is accounted per line in line-local
    # steps. 1 (default) = the single-version plane, bitwise-identical
    # to pre-multi-version builds (test-pinned). Line 0 is the primary
    # version: eval, run_summary final loss, and `colearn export` read
    # state["params"].
    async_versions: int = 1
    # Version retirement (async_versions >= 2 only; 0 = never retire).
    # When a line reaches this AGE (line-local server steps since its
    # generation was born) at its turn, the generation retires: the
    # line's params continue as the successor generation, but every
    # completion still in flight against the retired generation is a
    # LATE completion — popped later, it is re-admitted at the oldest
    # live version (staleness clamped to 2S) with its weight decayed by
    # async_readmit_decay, counted (`version_readmitted`) and warned
    # once, rather than dropped. run.strict_versions=true restores a
    # hard reject (RuntimeError) for late completions.
    async_retire_rounds: int = 0
    # retire a line's generation once it has ABSORBED this many updates
    # (whichever of age/updates trips first; 0 = no update threshold)
    async_retire_updates: int = 0
    # weight multiplier applied to a late completion re-admitted after
    # its generation retired (composes with the staleness decay)
    async_readmit_decay: float = 0.5
    # Two-tier edge/core aggregation — see HierarchyConfig.
    hierarchy: HierarchyConfig = field(default_factory=HierarchyConfig)
    # algorithm=feddyn only: the dynamic-regularization coefficient α
    # (both the client proximal pull and the server h-correction scale)
    feddyn_alpha: float = 0.1
    # algorithm=gossip only (decentralized DFedAvg, parallel/gossip.py):
    # every client keeps its OWN replica ([N, ...] mesh-sharded tree);
    # per round all N clients train locally then mix with their ring
    # neighbours — xᵢ ← (1−2γ)xᵢ + γ(xᵢ₋₁+xᵢ₊₁), a halo exchange whose
    # cross-chip traffic is 2·|params| per lane per step regardless of
    # N (vs the centralized psum). γ ∈ (0, 0.5]; 1/3 is the Metropolis
    # ring weight. topology "full" = complete averaging each step
    # (equals centralized uniform FedAvg from a consensus start — the
    # tested oracle). Eval/checkpoint export use the consensus mean.
    gossip_gamma: float = 1.0 / 3.0
    gossip_mixing_steps: int = 1
    gossip_topology: str = "ring"  # ring | full
    # scaffold/feddyn/error_feedback: storage dtype of the device-
    # resident per-client state store (the [N, ...] stacked cᵢ/gᵢ/eᵢ
    # tree, sharded
    # over the mesh's clients axis under run.engine=sharded). The HBM
    # budget is N·|params| at this dtype, divided across lanes.
    # "bfloat16" halves it but rounds the PERSISTENT state at each
    # scatter-back (in-round state math always runs f32); keep
    # "float32" unless the store dominates HBM.
    client_state_dtype: str = "float32"  # float32 | bfloat16
    # Cohort sampling:
    #   uniform  — fixed-size cohort, without replacement (classic).
    #   weighted — fixed-size, p ∝ client shard size (big-data clients
    #              drawn more often; pairs with uniform aggregation
    #              weights — the standard importance-sampling heuristic
    #              for example-weighted FedAvg, exact in the
    #              with-replacement limit).
    #   poisson  — every client independently participates with
    #              q = cohort_size/num_clients; the realized cohort is
    #              VARIABLE, padded to a static cap (≈K + 5σ, lane-
    #              rounded; overflow raises — observable abort whose
    #              exact binomial-tail probability is logged as
    #              dp_delta_abort). This is the sampling under which
    #              the client-level DP accountant's Poisson
    #              subsampled-Gaussian bound is EXACT (VERDICT r4
    #              missing-#3); under uniform/weighted it is an
    #              approximation (see dp_client_epsilon).
    #   adaptive — fixed-size, Oort-style utility-aware draw scored
    #              from the client ledger's periodic snapshots (loss-
    #              utility EMA × participation staleness, exploration
    #              floor, flag-rate suppression — see
    #              AdaptiveSamplerConfig / `server.adaptive`). Requires
    #              run.obs.client_ledger.enabled with log_every >= 1.
    #   streaming — the million-client mode: draws a fixed-size cohort
    #              in O(cohort·log) without ever enumerating the client
    #              universe (no dense [num_clients] probability vector,
    #              no O(N) permutation). Uniform rejection draw until
    #              ledger evidence arrives; with the client ledger on
    #              (log_every >= 1) it scores the SAME Oort-style
    #              formula as "adaptive" over a compact fixed-size
    #              sketch of observed clients plus a closed-form
    #              optimistic unseen pool (server.adaptive.sketch_size
    #              caps the sketch). Schedules are deterministic in
    #              (seed, round, sketch) and resume-replayable, but are
    #              a DIFFERENT deterministic sequence than "uniform"/
    #              "adaptive" produce (different draw algorithm).
    sampling: str = "uniform"  # uniform | weighted | poisson | adaptive | streaming
    # Simulated client dropout: fraction of the sampled cohort whose
    # update is zeroed inside the round function (total failure).
    dropout_rate: float = 0.0
    # Simulated stragglers (partial work, FedProx's motivating case):
    # each round, straggler_rate of the cohort completes only
    # straggler_work of its local steps (mask-truncated; the FedAvg
    # weight shrinks to the work actually done). Unlike dropout_rate,
    # stragglers' partial updates still aggregate.
    straggler_rate: float = 0.0
    straggler_work: float = 0.5
    # Secure aggregation — the masking core of Bonawitz et al. 2017,
    # simulated faithfully at the arithmetic level: each participant's
    # weighted delta is quantized to fixed-point int32 and additively
    # masked with UNIFORM int32 ring masks m(slot) − m(slot+1 mod K)
    # that cancel EXACTLY (mod 2^32) in the aggregate psum, so the
    # server-visible per-client contribution is information-
    # theoretically hidden while the aggregate is exact up to the
    # quantization step. The mask ring is the STATIC full cohort,
    # committed BEFORE training: dropout is discovered only after
    # uploads are collected, and the server then reconstructs each
    # dropped client's mask term m(slot) − m(slot+1) from the recovered
    # mask seed and adds it so the ring still telescopes to zero — the
    # real protocol's post-upload seed-share recovery, with the shared
    # mask key standing in for Shamir reconstruction. The dropped
    # client's data never enters the aggregate. Scope: the
    # key-agreement/secret-sharing layers of the real protocol are out
    # of simulation scope, and the loss/example-count metrics still
    # aggregate in plaintext (as published deployments also do for
    # counts). Requires clip_delta_norm > 0 so |quantized values| are
    # bounded: cohort · max_weight · clip / quant_step must stay < 2^31
    # (enforced at Experiment construction — see secagg_allow_wrap_risk)
    # and per-client values < 2^24 for exact f32 rounding (warned).
    secure_aggregation: bool = False
    # fixed-point quantization step for secure aggregation
    secagg_quant_step: float = 1e-4
    # Mask construction (privacy/secagg_keys.py):
    #   "ring"     — O(K) mask streams from one key; dropout recovery
    #                uses the shared key (arithmetic-exact simulation,
    #                the fast default).
    #   "pairwise" — the Bonawitz et al. 2017 §4-5 protocol shape:
    #                per-pair DH-agreed seeds, t-of-n Shamir recovery of
    #                dropped clients' seeds, round ABORTS below the
    #                threshold. O(K²) mask streams — opt-in; overhead
    #                measured in BASELINE.md r5.
    secagg_mode: str = "ring"
    # Shamir threshold t for pairwise mode: ≥t survivor shares
    # reconstruct a dropped client's seeds, t−1 reveal nothing.
    # 0 = auto (⌊K/2⌋+1, the honest-but-curious majority setting).
    secagg_threshold: int = 0
    # An int32 WRAP in the masked aggregate silently corrupts the round,
    # so a config whose worst-case bound cohort·max_weight·clip/
    # quant_step reaches 2^31 is REJECTED at Experiment construction
    # unless this explicit opt-in is set (the run then only warns).
    # Realized deltas usually sit far below the clip bound — but that is
    # a statistical observation, not a guarantee, hence opt-in.
    secagg_allow_wrap_risk: bool = False
    # Central CLIENT-level DP (DP-FedAvg, McMahan et al. 2018 "Learning
    # Differentially Private Recurrent Language Models"): Gaussian noise
    # with std z·S/K is added ONCE to the aggregated mean delta, where
    # z is this multiplier, S = clip_delta_norm is the per-client L2
    # sensitivity, and K = cohort_size is a FIXED PUBLIC denominator —
    # enabling client DP forces UNIFORM aggregation weights and the
    # fixed denominator, because a data-dependent denominator (realized
    # example counts) is itself private and would invalidate the
    # sensitivity analysis. Protects whole clients rather than single
    # examples (dp.* is example-level local DP-SGD; both can be
    # enabled). Requires clip_delta_norm > 0; composes with
    # secure_aggregation (noise is server-side, post-unmask — the
    # standard deployed stack). ε accounting: the sampled-Gaussian RDP
    # accountant with q = cohort/num_clients per round, reported as
    # dp_client_epsilon in the run log.
    dp_client_noise_multiplier: float = 0.0
    # Simulated downlink (server→client broadcast) compression: QSGD-
    # style unbiased stochastic quantization of the global params each
    # round — clients train FROM the quantized broadcast, deltas are
    # taken against it, the aggregate applies to the server's exact
    # params (ops/compression.py downlink_quantize). Pairs with the
    # uplink `compression` knob for the full comm-constrained story.
    downlink_compression: str = ""  # "" | qsgd
    downlink_qsgd_levels: int = 256
    # Fused server-apply chain (ops/pallas_apply.py): run the round
    # tail — trust/weight scaling → weighted reduction (stacked paths)
    # → server delta apply → optimizer update — as ONE VMEM-resident
    # pallas kernel pass over the flat param vector instead of a chain
    # of full-params XLA ops (each a |params| HBM round trip; the
    # stacked robust/attack paths additionally materialize weighted
    # [K, |params|] intermediates the kernel never writes). On the
    # weighted_mean psum path the in-lane reduction is untouched and
    # the kernel fuses apply+optimizer only; median/trimmed_mean keep
    # their coordinate-wise sorts (not a weighted reduction) and also
    # take the apply-only fusion. Interpret mode (exact, slow) runs the
    # same kernel on non-TPU backends, so CPU CI pins it against the
    # unfused reference per aggregator × reputation × error feedback.
    # Requires optimizer "mean" or "fedavgm" (the kernel's FMA chain);
    # fused ≡ unfused at f32-reassociation tolerance, not bitwise.
    fused_apply: bool = False
    # Reputation-weighted aggregation off the client ledger — see
    # ReputationConfig.
    reputation: ReputationConfig = field(default_factory=ReputationConfig)
    # sampling="adaptive" scoring knobs — see AdaptiveSamplerConfig.
    adaptive: AdaptiveSamplerConfig = field(
        default_factory=AdaptiveSamplerConfig
    )


@dataclass
class DPConfig:
    enabled: bool = False
    l2_clip: float = 1.0
    noise_multiplier: float = 1.0
    delta: float = 1e-5
    # per-example grads are memory-heavy; vmap over microbatches of this size
    microbatch_size: int = 16
    # Clipping strategy (privacy/dp.py — both EXACT, same mechanism):
    #   microbatch — lax.scan over microbatches of vmap(grad); one
    #                backward total but poorly batched (the vmapped
    #                backward can't use full-batch matmuls).
    #   two_pass   — ghost-norm-style: pass 1 computes per-example grad
    #                NORMS only (grads discarded), pass 2 is ONE fully
    #                batched weighted backward whose gradient IS the
    #                clipped sum (grad of the scale-masked mean × Σscale).
    #                Two backwards, but both MXU-batched. Measured on
    #                imagenet_silo_dp: BASELINE.md r5.
    clipping: str = "microbatch"  # microbatch | two_pass


@dataclass
class AttackConfig:
    """Byzantine adversary simulation (server/attacks.py): a
    deterministic ``(run.seed)``-pure set of compromised clients
    attacks during ``fit``, so the robust aggregation stack can be
    MEASURED against a live adversary instead of hand-crafted tensors.

    Threat model — where each attack acts:

    - upload attacks (``sign_flip``/``gauss``/``scale``/``alie``): the
      compromised client controls its wire message; the transform
      applies to its delta after clipping/compression (the honest
      client's update rule) and before aggregation, inside the round
      program (a ``[K]`` byzantine-mask input — no retrace, exact
      sharded↔sequential parity). Under ``algorithm=gossip`` the
      "upload" is the poisoned replica the attacker gossips to its
      ring neighbours (``alie`` is rejected there: it sizes itself
      from cohort statistics a decentralized attacker cannot observe).
    - ``label_flip``: data poisoning — the compromised clients'
      training labels are flipped ``y → (C−1)−y`` host-side before
      corpus placement; the upload is an honest gradient of poisoned
      data. Composes with any engine path (no engine involvement).

    Expected defense behavior (the headline e2e test pins it): plain
    ``weighted_mean`` collapses under ``sign_flip`` at f=2/8 while
    krum / median / trimmed_mean hold their benign accuracy band.

    Pairings rejected by validate() (with reasons): secure_aggregation,
    client-level DP, example-level DP, scaffold/feddyn, fedbuff,
    error_feedback. Upload attacks compose with run.fuse_rounds>1: the
    per-round byzantine masks become a stacked [fuse, K] scan input and
    the attacked delta stack stays private to the fused scan body.
    """

    # "" (off) | sign_flip | gauss | scale | alie | label_flip
    kind: str = ""
    # fraction of the FEDERATION compromised; the id set is
    # round(fraction·num_clients) clients (≥1), drawn deterministically
    # from run.seed — identical across engines, resumes, and reruns
    fraction: float = 0.25
    # sign_flip/scale boost factor: sign_flip uploads −scale·Δ, scale
    # uploads +scale·Δ (model-replacement boosting). 1.0 = pure flip /
    # honest magnitude.
    scale: float = 10.0
    # gauss: per-coordinate noise std (the upload is eps·N(0,I));
    # alie: the z of μ − z·σ (how many honest stds the colluders shift)
    eps: float = 1.0


@dataclass
class ClientLedgerConfig:
    """Per-client forensic ledger (``run.obs.client_ledger``,
    obs/ledger.py): each round program additionally emits a small
    ``[K]``-shaped per-client stats block (upload L2 norm, cosine
    similarity to the aggregated delta, clip/EF residual magnitude,
    post-local-train loss, and a robust median/MAD z-score anomaly
    flag) and scatters it IN-PROGRAM into a device-resident
    ``[num_clients]`` ledger carried across rounds (participation
    count, EMA of each stat, cumulative flagged rounds) — zero extra
    host round-trips, riding the fused scan carry under
    ``run.fuse_rounds`` exactly like the EF residual store. The ledger
    flows out as periodic ``client_ledger`` JSONL records (plus a
    final one on every exit path, aborts included) and powers the
    ``colearn clients <run>`` report: top-k anomalous clients,
    participation histogram, and — when ``attack.kind`` is set —
    detection precision/recall against the ground-truth compromised
    set. Aggregation itself is untouched: a ledger-on run's params
    trajectory is bitwise identical to the same run with the ledger
    off (the stats block reads the upload stack; it never feeds back).

    Rejected pairings (validate(), with reasons): secure_aggregation
    (per-client uploads are exactly what masking hides), client-level
    DP (a per-client statistics channel voids the client-DP release),
    gossip (no server-visible upload stack), and scaffold/feddyn
    (their store plumbing owns the per-client state path; robust/
    attack forensics is rejected there anyway). ``algorithm="fedbuff"``
    is SUPPORTED since the churn PR via per-INSERT stats — each async
    server step computes the stats block over its popped buffer's
    uploads and scatters by true client id (dense ledger only; the
    paged hot set's slot remap stays a synchronous-dispatch feature)."""

    enabled: bool = False
    # EMA coefficient for the per-stat running means: ema_x moves by
    # ema*(x - ema_x) per observed round; a client's first observation
    # seeds the EMA with the value itself
    ema: float = 0.2
    # robust z-score threshold: a participant whose max(z_l2, z_cos)
    # exceeds this is flagged for the round (3.5 is the classic
    # median/MAD outlier cutoff)
    zmax: float = 3.5
    # rounds between periodic client_ledger JSONL snapshots (emitted at
    # metrics-flush boundaries); 0 = only the end-of-fit/abort record
    log_every: int = 0
    # Paged ledger (obs/ledger.py LedgerPager — the million-client
    # mode): 0 keeps the classic dense [num_clients, LEDGER_WIDTH]
    # device store; > 0 keeps only a [hot_capacity, LEDGER_WIDTH]
    # LRU-style HOT set device-resident, scattered by SLOT (the driver
    # remaps cohort ids to slots host-side; the round program is
    # unchanged), with cold rows spilled to an anonymous host mmap.
    # Page-ins ride a tiny async device scatter; an eviction needs one
    # blocking hot-set fetch (counted as ledger_page_syncs in
    # run_summary). Reputation/adaptive selection read exactly the same
    # rows they would from the dense ledger, so paging is invisible to
    # the round program for any cohort that fits the hot set — the
    # merged (hot ∪ cold) ledger is bitwise-equal to the dense run's
    # (test-pinned), and flush/resume behave exactly like today. Must
    # be >= cohort_size × fuse_rounds (checked at construction);
    # values >= num_clients degrade to the dense store. Incompatible
    # with server.error_feedback (the EF store is indexed by true
    # client ids on the same cohort input the pager remaps).
    hot_capacity: int = 0


@dataclass
class PopulationConfig:
    """Federation health observatory (``run.obs.population``,
    obs/population.py): per-metrics-flush-window ``population_health``
    JSONL records covering the data plane the million-client structures
    run on — sampler health (cumulative unique-client coverage via an
    O(1)-memory HLL-style probabilistic counter, exploration/
    exploitation draw split, streaming-sketch occupancy/refresh-age/
    flag-rate coverage, cohort staleness distribution), ledger-pager
    health (per-window hit/miss/page-in/eviction counts + page-sync
    stall ms — the PR 9 run_summary totals as a time series), store I/O
    (bytes gathered, gather wall ms, per-shard touch counts, union-slab
    dedup ratio), and participation fairness (Gini/max-share over a
    bounded top-k participation sketch — never a dense [num_clients]
    histogram). Every tracked structure is O(cohort) per round or
    fixed-size, and every count-based column is a pure function of the
    host-side cohort schedule, so records are engine-parity pinned
    (sharded ≡ sequential ≡ fused) on everything but the ``*_ms``
    wall-clock fields. Purely observational: no device work, no rng
    consumption, params bitwise-unchanged. ``colearn watch <run>``
    renders the live view; ``colearn population <run>`` is the post-hoc
    report; ``colearn summarize`` surfaces the run_summary totals."""

    enabled: bool = False
    # capacity of the bounded top-k participation sketch the fairness
    # stats (gini, max-share, top clients) are computed over
    top_k: int = 64
    # HLL register count = 2**hll_bits (12 → 4096 one-byte registers,
    # ~1.6% relative error on the coverage estimate)
    hll_bits: int = 12
    # bounded last-participation map behind the staleness distribution;
    # cohort members evicted from it count as staleness-unknown
    recency_capacity: int = 8192


@dataclass
class DigestConfig:
    """Determinism flight recorder (``run.obs.digest``, obs/digest.py):
    at each digest boundary the driver computes a canonical,
    dtype/shape-tagged 64-bit digest over the fetched state — params
    (per-top-level-leaf + rolled up), server opt state, the
    ledger/pager hot set, the realized cohort schedule + failure
    counts and wire-byte counters since the previous boundary, and the
    RNG inputs — and emits one ``round_digest`` JSONL record chaining
    ``prev`` → ``self`` (a hash chain: truncated/tampered logs are
    self-evident). The chain head rides every checkpoint and resume
    verifies it against the log before training continues. Purely
    observational: digests are a pure function of fetched state
    (engine-invariant wherever the engines are bitwise) and digest-on
    runs are bitwise-identical to digest-off runs on the same seed
    (test-pinned). ``colearn diff`` bisects two streams to the first
    divergent round + component; ``colearn replay`` re-executes one
    logged round and verifies its digest. Off by default (benches
    never pay the O(P) host fetch)."""

    enabled: bool = False
    # rounds between digest boundaries; the O(params) host-side fetch
    # + hash is amortized by this cadence. Under run.fuse_rounds > 1
    # must be a chunk multiple (boundaries land on chunk ends).
    every: int = 1
    # verify the checkpoint's chain head against the log on resume
    # (warn on mismatch; run.obs.digest.strict aborts instead)
    verify_resume: bool = True
    # escalate a failed resume verification from a logged warning to
    # DigestResumeError (`colearn fit --strict-digest`)
    strict: bool = False


@dataclass
class ObsConfig:
    """Round-lifecycle telemetry (``obs/``): phase spans, comm/device
    counters, and run-health monitoring — the observability layer every
    perf PR measures against. All host-side; the engines are unchanged
    apart from trace annotations."""

    # Time the round lifecycle (host inputs → placement → dispatch →
    # fetch → eval → checkpoint) and log a per-phase `spans` record at
    # every metrics-flush boundary. Off = spans are shared no-ops.
    spans: bool = True
    # Also accumulate Chrome-trace events and write
    # <out_dir>/<name>/trace.json at the end of fit (open in
    # ui.perfetto.dev or chrome://tracing). Requires spans.
    trace: bool = False
    # Cap on accumulated Chrome-trace events: long runs otherwise
    # silently produce multi-GB trace.json files. When the cap is hit
    # the tracer warns ONCE and drops further events (per-phase span
    # aggregates are unaffected); the export also warns once when the
    # written file exceeds the size threshold. 0 = unbounded.
    trace_max_events: int = 1_000_000
    # Per-round communication byte counters (analytic wire model:
    # upload/download, pre/post compression — obs/counters.py) merged
    # into each round's JSONL record.
    counters: bool = True
    # Per-round analytic phase-cost records (obs/roofline.py): FLOPs +
    # HBM bytes per round-program stage (local train / attack /
    # aggregation / server apply / ledger stats), logged as
    # `phase_cost` JSONL records next to the spans and joined by
    # `colearn mfu <run>` into the MFU waterfall. Pure-function model
    # (engine-invariant); requires counters. Centralized rounds only —
    # gossip/fedbuff rounds carry no phase_cost record.
    phase_cost: bool = True
    # Where the local-train step FLOP count comes from:
    #   analytic — dense 6·P·B approximation, zero extra compiles
    #   xla      — XLA's cost model of one scan-free train step (what
    #              bench.py's model_tflops_per_round uses; one extra
    #              compile at fit start, exact for conv models)
    phase_cost_flops: str = "analytic"  # analytic | xla
    # Poll jax device memory stats at flush boundaries and log a
    # `device_memory` record (in-use / peak / limit bytes). Off by
    # default: the gauges are per-process globals, noisy under tests.
    device_memory: bool = False
    # NaN/Inf (+ optional divergence) monitoring over the per-round
    # training loss — free, the loss is fetched anyway at flush.
    health: bool = True
    # Also probe the PARAMS for finiteness at flush boundaries (one
    # device fetch per flush window; run.sanitize does it per round).
    params_check: bool = False
    # 0 = off; otherwise flag `divergence` when a round's loss exceeds
    # factor × the best loss seen so far. Must be > 1 when set.
    divergence_factor: float = 0.0
    # What to do on an unhealthy round:
    #   warn             — log the health event, keep training
    #   abort            — raise HealthAbortError (NOT retried by
    #                      run.max_retries: a NaN run re-NaNs)
    #   checkpoint_abort — save a post-mortem checkpoint first
    on_unhealthy: str = "warn"  # warn | abort | checkpoint_abort
    # Compiled-program observatory (obs/executables.py): route every
    # engine/eval jit through an AOT executable registry and record,
    # per compiled program, XLA's own cost_analysis FLOPs,
    # memory_analysis argument/output/temp bytes, the donation map, a
    # stable fingerprint, and compile wall-ms (`executable_compiled`
    # records), plus per-flush `hbm_watermark` records and `retrace`
    # forensics naming the argument whose shape/dtype/sharding
    # changed. Execution is the SAME lowering jit would produce —
    # bitwise-identical results, test-pinned. Off = jit dispatch
    # untouched, records absent.
    executables: bool = True
    # 0 = off; otherwise any newly compiled program whose predicted
    # peak HBM (argument + output + temp + generated-code bytes,
    # donation-aliased buffers counted once) exceeds this many MiB
    # aborts the fit with HbmBudgetError BEFORE the program executes
    # (deliberately not retried — recompiling predicts the same peak).
    # `colearn preflight` applies the same ceiling without executing
    # anything. Requires executables.
    hbm_budget_mb: int = 0
    # Per-client forensic ledger — see ClientLedgerConfig.
    client_ledger: ClientLedgerConfig = field(
        default_factory=ClientLedgerConfig
    )
    # Federation health observatory — see PopulationConfig.
    population: PopulationConfig = field(default_factory=PopulationConfig)
    # Determinism flight recorder — see DigestConfig.
    digest: DigestConfig = field(default_factory=DigestConfig)


@dataclass
class ShapeBucketsConfig:
    """Heterogeneity-aware round shapes (``run.shape_buckets``): the
    round grid's step count becomes a function of the SAMPLED COHORT,
    not the federation. The federation-max ``steps_per_epoch`` is
    quantized onto a small geometric ladder (top rung = the legacy full
    shape); each round the driver picks the smallest rung covering the
    realized cohort's max capped shard (per CHUNK under
    ``run.fuse_rounds`` > 1, so fused slabs stay rectangular) and
    dispatches through one lazily-compiled executable per realized rung.
    Padded steps are exact algebraic no-ops, so a bucketed run is
    BITWISE-EQUAL to the buckets-off run on the same seed (test-pinned,
    sharded↔sequential and fused↔unfused) — only the mask-zeroed scan
    iterations (real TPU FLOPs under power-law client sizes) disappear.
    Compile budget: ≤ ladder-size retraces per engine, attributed via
    the obs compile listener (``shape_bucket`` events).

    Rejected pairings (validate(), each with its reason): example-level
    DP (per-step noise keys are positional in the padded grid — a
    trimmed grid would shift every noise stream), stragglers (their
    truncation is parameterized on the full-shape step grid),
    fedbuff/gossip (their schedulers own the round shape), and
    ``run.host_pipeline='native'`` (the C++ pipeline builds for one
    fixed shape; ``auto`` falls back to NumPy while buckets are on)."""

    # off = exact-legacy behavior: every round pads to the federation max
    enabled: bool = False
    # geometric ladder ratio between adjacent rungs (> 1)
    base: float = 2.0
    # number of rungs below (and including) the full shape; the realized
    # ladder is deduplicated, so count only bounds it
    count: int = 4


@dataclass
class ChurnConfig:
    """Seed-pure availability/churn model (``run.churn``,
    server/churn.py — the production-traffic plane): per-client diurnal
    availability waves, mid-round dropout hazard, and crash-mid-round
    injection, every draw a pure function of ``(run.seed, round,
    client_id)`` via counter-mode hashing — so schedules are
    resume-replayable with zero checkpoint state and engine-invariant
    (sharded ≡ sequential ≡ prefetch worker, bitwise).

    Where it acts: the uniform and streaming cohort samplers reject
    offline candidates (an unavailable client is simply not drawn);
    any cohort member that still dispatches while offline, draws the
    dropout hazard, or crashes mid-round realizes its failure through
    the existing straggler/dropout machinery (``n_ex`` zeroing and
    mask/spec truncation — partial work still aggregates, weighted by
    the steps actually done). Under ``algorithm="fedbuff"`` offline
    clients additionally DEFER their completions, growing realized
    staleness — the regime the bounded-staleness admission gate
    (``run.strict_staleness``) and the overload backpressure policy
    (``server.async_backlog_cap``) exist for.

    Rejected pairings (validate(), with reasons): gossip (all clients
    train every round — there is no availability-gated cohort draw),
    ``run.shape_buckets`` (crash truncation is parameterized on the
    full-shape step grid, same reason as stragglers), and the
    weighted/poisson/adaptive samplers (static size-weights and the
    Poisson DP-exact ``q`` assume unconditional draws; the dense
    adaptive scores would need availability renormalization — the
    uniform and streaming samplers are the gated pair). ``enabled=
    False`` constructs no model anywhere and is bitwise-identical to
    pre-churn builds (test-pinned with stray knob values)."""

    enabled: bool = False
    # rounds per simulated day: each client's availability follows
    # base + amplitude*sin(2π(round/period + phase_i)) with a fixed
    # hash-derived per-client phase (its "timezone")
    diurnal_period: int = 24
    # peak-to-mean swing of the diurnal wave (0 = flat availability)
    diurnal_amplitude: float = 0.5
    # mean availability probability (the wave's midline)
    base_availability: float = 0.75
    # clip floor for the per-round availability probability: no client
    # is ever permanently unreachable (the exploration-floor principle)
    min_availability: float = 0.05
    # probability a dispatched participant fails mid-round entirely
    # (total failure — weight zeroed, same path as server.dropout_rate)
    dropout_hazard: float = 0.0
    # probability a dispatched participant crashes mid-round at a
    # hash-drawn fraction of its local steps (partial work aggregates,
    # mask-truncated — the straggler path)
    crash_rate: float = 0.0
    # Trace-replay availability: path to a FedScale-style per-client
    # on/off trace (a .npy uint8 bitmap [trace_rounds, trace_rows];
    # `server.churn.build_synthetic_trace` writes one). When set, the
    # diurnal wave is REPLACED by trace playback: client i maps to a
    # stable hash-derived trace row, round r reads row bit
    # [r mod trace_rounds], and the availability probability is the bit
    # clipped to [min_availability, 1] — an off-bit client keeps the
    # exploration-floor probability, with the same seed-pure hash
    # tie-breaking as the analytic wave (schedules stay O(cohort),
    # engine-invariant, and resume-replayable; the trace file is
    # mmap-read, never materialized). diurnal_* knobs are ignored.
    # File existence is checked at Experiment construction.
    trace: str = ""


@dataclass
class RunConfig:
    seed: int = 0
    # sharded: the shard_map/psum round engine (one XLA program per round)
    # sequential: python loop over cohort clients (reference semantics; used
    #             for bit-parity tests and single-device debugging)
    engine: str = "sharded"
    # number of mesh lanes on the "clients" axis; 0 = all visible devices
    num_lanes: int = 0
    # second mesh axis for intra-client batch DP on big silo models; 1 = off
    batch_shards: int = 1
    # clients trained as one vmap block per lane (effective batch =
    # width × batch_size keeps the MXU fed for small models); 1 = pure
    # sequential scan (min memory), 0 = whole lane in one vmap.
    # Ignored under cohort_layout="megabatch" (the layout owns the
    # in-lane batching; an explicit width >= 2 is rejected).
    client_vmap_width: int = 1
    # Cohort layout (parallel/round_engine.py, client/trainer.py):
    #   spatial   — the classic placement: the cohort shards over lanes
    #               and each lane trains its clients in client_vmap_width
    #               blocks. With width 1 every per-chip GEMM is capped at
    #               ONE client's batch — the MXU starves on small models.
    #   megabatch — collapse the cohort axis into the GEMM batch: a lane
    #               owns K_local = cohort/lanes clients and their local
    #               training runs as ONE fused block. The first local
    #               step (all clients still hold the round's identical
    #               broadcast weights) runs as a true megabatch — the
    #               forward and activation-gradient GEMMs contract
    #               [K_local·batch, ...] activations against ONE weight
    #               — and the remaining steps run as a lane-local vmap
    #               over the diverged per-client params (one batched
    #               GEMM per layer instead of K_local sequential
    #               launches). A pure performance knob: the wire shapes
    #               ([K] weights, [K,2] mask specs, the [K,·] upload
    #               stack, psum/robust-reduce aggregation, ledger stats)
    #               are unchanged and megabatch ≡ spatial is parity-
    #               pinned (tests/test_round_engine.py). Rejected
    #               pairings in validate(): stateful algorithms
    #               (scaffold/feddyn own per-client correction trees in
    #               the scan layout), gossip/fedbuff (their engines own
    #               the round shape), and run.batch_shards > 1 (the
    #               flattened [K_local·batch] megabatch rows are exactly
    #               the axis the batch mesh splits). The sequential
    #               engine is layout-free (it IS the oracle).
    cohort_layout: str = "spatial"  # spatial | megabatch
    # Unroll factor for the client's local-step lax.scan (jax's native
    # `unroll=`): >1 trades compile time / code size for fewer loop
    # iterations and cross-step fusion opportunities; lax.scan handles
    # non-dividing step counts itself. 1 = no unrolling.
    scan_unroll: int = 1
    # Multi-round fusion: F rounds compiled as ONE XLA program (a
    # lax.scan over the round body with stacked index tensors and the
    # same per-round rngs — fused ≡ unfused bitwise). Amortizes
    # per-round dispatch, THE dominant cost of tiny-model configs on a
    # relayed chip (BASELINE.md r5). Covers the fedavg/fedprox family
    # including robust aggregators (median/trimmed_mean/krum — the
    # per-client delta stack stays private to the scan body), upload
    # attacks (byzantine masks ride a stacked [fuse, K] scan input),
    # error feedback (the residual store is a donated scan carry), and
    # multi-process meshes (stacked host slabs place through the
    # sharded path). Excluded: scaffold/feddyn/fedbuff/gossip (their
    # state recursions / schedulers cannot ride the carry), secagg
    # (per-round key-protocol host I/O), and data.placement=stream
    # (slabs are built per round). Must divide num_rounds, eval_every
    # and checkpoint_every so evals and saves land on fused-chunk
    # boundaries; a resume at a non-chunk-aligned round runs unfused
    # catch-up rounds to the next boundary (logged) and then re-enters
    # the fused loop. 1 = off.
    fuse_rounds: int = 1
    # Persistent XLA compilation cache directory ("" = off): round-program
    # compiles (~40 s for ResNet, minutes for ViT-B+DP) are reused across
    # processes/restarts — resume, retry-recovery, and repeated bench/CI
    # invocations skip straight to execution.
    compilation_cache_dir: str = ""
    # Failure recovery (SURVEY.md §5): on an unexpected error inside the
    # round loop, reload the latest checkpoint and continue, up to this
    # many times per fit() call. 0 = fail fast. Requires out_dir +
    # checkpoint_every for mid-run restarts (otherwise the retry starts
    # from round 0). KeyboardInterrupt is never retried.
    max_retries: int = 0
    # Device HBM budget in GiB for the construction-time memory
    # pre-flight (PERSISTENT per-device arrays: replicated corpus +
    # params + server-opt state + the N-row client-state / replica
    # stacks divided over lanes + the fedbuff history ring). A config
    # whose persistent footprint exceeds the budget fails FAST with a
    # per-component breakdown and remedies, instead of an opaque
    # RESOURCE_EXHAUSTED minutes into compilation (VERDICT r4
    # missing-#4). 0 = auto (device memory_stats when the backend
    # reports one, else 16 GiB on TPU, else skip on CPU); -1 = disable.
    hbm_gb: float = 0.0
    # Double-buffered host↔device rounds (server/round_driver.py): a
    # host worker thread builds round N+1's inputs AND places them on
    # device (a second in-flight placed-slab buffer keyed like the
    # prefetch futures) while the device executes round N's dispatched
    # compute, so the round.host_inputs / round.placement phases hide
    # under round.dispatch. Inputs are pure in (seed, round[, ledger
    # snapshot]), so buffered ≡ unbuffered BITWISE (test-pinned); the
    # overlap drains itself wherever purity would break — fused-chunk
    # grids built for a different ladder rung are dropped and rebuilt,
    # and the adaptive sampler never prefetches across a ledger-
    # snapshot refresh boundary. stream placement keeps its legacy
    # build-only one-ahead prefetch (a placed slab would double the
    # bounded-memory promise); fedbuff's scheduler is not buffered.
    double_buffer: bool = True
    # Host-side round-input construction (idx/mask/n_ex tensors):
    #   auto   — the C++ threaded pipeline (native/) when the toolchain
    #            builds it, else the NumPy path; prefetches round r+1
    #            while the device executes round r
    #   native — require the C++ pipeline (error if unavailable)
    #   numpy  — single-threaded NumPy construction (data/loader.py)
    # Both are deterministic in (seed, round) but use different
    # permutation RNGs; a resumed run only replays the original batch
    # schedule on the same pipeline kind — pin "native" or "numpy"
    # explicitly if a run may migrate across machines mid-flight.
    host_pipeline: str = "auto"
    # Round control plane (ISSUE 18):
    #   host   — the legacy path: sampler draws, churn realization, and
    #            index-slab construction run in host Python between
    #            dispatches (bitwise-identical to pre-knob builds).
    #   device — the control plane lowers into the round program
    #            (server/device_plane.py): cohort ids come from a tiny
    #            precomputed per-round table, churn gates are evaluated
    #            in-program by a uint32-pair SplitMix64 bitwise-equal
    #            to server/churn.py's host draws, and the index slab is
    #            derived from a device-resident shard table — the host
    #            ships nothing per round, and under fuse_rounds > 1 the
    #            fused scan derives every sub-round's schedule itself,
    #            so host I/O collapses to flush boundaries. Cohort ids
    #            and churn fail stats stay bitwise-equal to host mode;
    #            per-batch example ORDER is the device plane's own
    #            seed-pure rotation discipline (documented in
    #            DESIGN.md). The realized schedule is emitted as a
    #            fetched-at-flush program output. Requires the
    #            fixed/uniform sampler, hbm placement, and the
    #            sharded/sequential engines; samplers that need host
    #            state (adaptive/streaming), fedbuff/gossip/hierarchy,
    #            attacks, secagg, and per-round host protocols are
    #            rejected with reasons (capability matrix
    #            `control_plane_device`).
    control_plane: str = "host"
    # rounds between metric fetches. Dispatch is async; only host fetches
    # pay the device round-trip (~100ms through this sandbox's relay), so
    # the driver buffers per-round metric scalars on device and drains
    # them every N rounds. 1 = fetch every round (debug).
    metrics_flush_every: int = 10
    out_dir: str = "runs"
    # also mirror per-round metrics as TensorBoard scalars under
    # <out_dir>/<name>/tb (JSONL is always written)
    tensorboard: bool = False
    resume: bool = False
    profile_round: int = -1  # round index to wrap in jax.profiler.trace; -1 = off
    sanitize: bool = False  # jax_debug_nans + finite-params assertions
    param_dtype: str = "float32"
    compute_dtype: str = "float32"  # bfloat16 on real TPU configs
    # Mixed-precision local training: cast global params to this dtype
    # ONCE per client at local-training entry ("" = keep param_dtype).
    # With f32 params + bf16 compute, "bfloat16" removes the per-step
    # f32→bf16 parameter conversions (~17% of round time on v5e, see
    # BASELINE.md profile) while server aggregation and the cross-round
    # trajectory stay f32.
    local_param_dtype: str = ""
    # Cohort-shaped step buckets — see ShapeBucketsConfig.
    shape_buckets: ShapeBucketsConfig = field(
        default_factory=ShapeBucketsConfig
    )
    # Seed-pure availability/churn model — see ChurnConfig.
    churn: ChurnConfig = field(default_factory=ChurnConfig)
    # algorithm=fedbuff only: what a staleness-bound violation does.
    # False (default) = the GRACEFUL path: an update whose start
    # version aged out of the 2S+1 history ring trains against the
    # OLDEST RETAINED version instead, its aggregation weight decays at
    # the TRUE staleness (strictly stronger down-weighting), and the
    # event is counted (`staleness_clamped` in round records and
    # run_summary) with a warn-once log — the production behavior
    # under churn, where offline clients legitimately exceed the
    # bound. True = the pre-churn contract: any staleness > 2S raises
    # (ring sizing is then an invariant, not a budget).
    strict_staleness: bool = False
    # algorithm=fedbuff with server.async_versions >= 2 only: what a
    # late completion against a RETIRED version generation does. False
    # (default) = the graceful drain: the completion is re-admitted at
    # the oldest live version with its weight decayed by
    # server.async_readmit_decay, counted (`version_readmitted`) and
    # warned once. True = hard reject: a late completion raises
    # (retirement then asserts the buffer drained before the threshold).
    strict_versions: bool = False
    # Observability block (spans / counters / health) — see ObsConfig.
    obs: ObsConfig = field(default_factory=ObsConfig)


# the federated algorithms the driver implements (validate() + docs)
ALGORITHMS = ("fedavg", "fedprox", "scaffold", "feddyn", "fedbuff", "gossip")


@dataclass
class ExperimentConfig:
    name: str = "mnist_fedavg_2"
    # fedavg | fedprox (prox_mu>0 implied) | scaffold (client control
    # variates, Karimireddy et al. 2020 — needs plain client SGD) |
    # fedbuff (asynchronous buffered aggregation, Nguyen et al. 2022 —
    # clients train on stale versions, staleness-decayed weights)
    algorithm: str = "fedavg"
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    client: ClientConfig = field(default_factory=ClientConfig)
    server: ServerConfig = field(default_factory=ServerConfig)
    dp: DPConfig = field(default_factory=DPConfig)
    attack: AttackConfig = field(default_factory=AttackConfig)
    run: RunConfig = field(default_factory=RunConfig)

    def _effective_local_dtype(self) -> str:
        """The dtype local training actually runs in: local_param_dtype,
        or — when empty — the server param dtype itself."""
        return self.run.local_param_dtype or self.run.param_dtype

    def _stateful_dtype_ok(self) -> bool:
        """Stateful algorithms (scaffold/feddyn) need the WHOLE parameter
        trajectory in f32: local training (w_K feeds the persistent
        state) AND server params/delta accumulators (params must move by
        exactly the deltas the f32 state tracks)."""
        return (
            self._effective_local_dtype() == "float32"
            and self.run.param_dtype == "float32"
        )

    def validate(self) -> "ExperimentConfig":
        if self.server.cohort_size > self.data.num_clients:
            raise ValueError(
                f"cohort_size {self.server.cohort_size} > num_clients {self.data.num_clients}"
            )
        if self.algorithm == "fedprox" and self.client.prox_mu <= 0:
            raise ValueError("fedprox requires client.prox_mu > 0")
        if self.algorithm not in ALGORITHMS:
            raise ValueError(f"unknown algorithm {self.algorithm!r}")
        if self.algorithm == "feddyn":
            if self.client.prox_mu > 0.0:
                # the α/2‖w−w₀‖² term IS feddyn's regularizer; the engine
                # injects prox_mu=feddyn_alpha itself
                raise ValueError("feddyn injects prox_mu=alpha; set prox_mu=0")
            if self.server.feddyn_alpha <= 0.0:
                raise ValueError("feddyn requires server.feddyn_alpha > 0")
            if self.dp.enabled:
                raise ValueError("feddyn is incompatible with dp.enabled")
            if not self._stateful_dtype_ok():
                raise ValueError(
                    "feddyn requires an f32 parameter trajectory "
                    "(run.param_dtype=float32 and f32 local training) — "
                    "the persistent gᵢ/h state tracks exact deltas"
                )
            if self.server.aggregator != "weighted_mean":
                raise ValueError(
                    "feddyn is incompatible with robust server.aggregator "
                    "(the h recursion tracks raw deltas)"
                )
            if self.server.compression or self.server.clip_delta_norm > 0.0:
                raise ValueError(
                    "feddyn is incompatible with compression/clip_delta_norm "
                    "(params would move by modified deltas while gᵢ/h track "
                    "the raw trajectory)"
                )
            if self.server.optimizer != "mean" or self.server.server_lr != 1.0:
                # the engine applies the paper's exact step and bypasses
                # the optax server optimizer — a configured server_lr
                # would be silently ignored, so reject it
                raise ValueError(
                    "feddyn defines its own server update; set "
                    "server.optimizer=mean and server_lr=1.0"
                )
        if self.algorithm == "gossip":
            if self.run.engine != "sharded":
                raise ValueError("gossip requires run.engine=sharded")
            # cohort_size == num_clients: every client trains every
            # round (classic DFedAvg). cohort_size < num_clients (r5):
            # PARTIAL participation — only the sampled cohort trains
            # (in-program gather/train/scatter over the replica stack,
            # O(K) local compute), everyone mixes. The replica stack is
            # O(N·|params|/lanes) either way — run.hbm_gb pre-flights
            # it. Measured N=128 on the real chip: BASELINE.md r5.
            if self.server.optimizer != "mean" or self.server.server_lr != 1.0:
                # there is no server update at all — a configured server
                # optimizer would be silently ignored, so reject it
                raise ValueError(
                    "gossip has no server optimizer; set "
                    "server.optimizer=mean and server_lr=1.0"
                )
            if self.server.sampling != "uniform":
                raise ValueError(
                    "gossip schedules all clients every round; "
                    f"server.sampling={self.server.sampling} is not supported"
                )
            if (self.server.aggregator != "weighted_mean"
                    or self.server.compression
                    or self.server.downlink_compression
                    or self.server.secure_aggregation
                    or self.server.error_feedback
                    or self.server.dp_client_noise_multiplier > 0.0
                    or self.server.clip_delta_norm > 0.0):
                # all of these are server-aggregation concepts; gossip
                # has no server and no uplink — neighbour messages are
                # the full replicas
                raise ValueError(
                    "gossip is incompatible with server-side aggregation "
                    "options (aggregator/compression/downlink_compression/"
                    "secagg/error_feedback/client-DP/clip_delta_norm)"
                )
            if not 0.0 < self.server.gossip_gamma <= 0.5:
                raise ValueError(
                    f"server.gossip_gamma must be in (0, 0.5], "
                    f"got {self.server.gossip_gamma}"
                )
            if self.server.gossip_mixing_steps < 1:
                raise ValueError("server.gossip_mixing_steps must be >= 1")
            if self.server.gossip_topology not in ("ring", "full"):
                raise ValueError(
                    f"unknown server.gossip_topology "
                    f"{self.server.gossip_topology!r}"
                )
            if self.run.batch_shards > 1:
                raise ValueError("gossip is incompatible with run.batch_shards")
            if self.data.placement != "hbm":
                raise ValueError("gossip requires data.placement=hbm")
            if self.client.lr_decay != 1.0:
                # the gossip engine has no server round counter to
                # derive the decay schedule from — a configured decay
                # would be silently ignored, so reject it (same
                # principle as the server-optimizer rejection above)
                raise ValueError(
                    "gossip does not support client.lr_decay"
                )
        if self.algorithm == "fedbuff":
            if self.run.engine != "sharded":
                raise ValueError("fedbuff requires run.engine=sharded")
            if self.server.aggregator != "weighted_mean":
                raise ValueError(
                    "fedbuff is incompatible with robust server.aggregator"
                )
            if self.server.compression:
                raise ValueError("fedbuff is incompatible with server.compression")
            if self.run.batch_shards > 1:
                raise ValueError("fedbuff is incompatible with run.batch_shards")
            if self.server.sampling not in ("uniform", "streaming"):
                # arrivals are drawn per server step: uniform draws, or
                # the O(cohort·log) streaming sketch draw (optionally
                # scored from the per-insert ledger stats — the
                # million-client arrival path). weighted/poisson/
                # adaptive parameterize a synchronous cohort draw the
                # queue scheduler does not make.
                raise ValueError(
                    "fedbuff draws queue arrivals via uniform or "
                    "streaming sampling only; "
                    f"server.sampling={self.server.sampling} is not supported"
                )
            if self.server.async_max_staleness < 1:
                raise ValueError("async_max_staleness must be >= 1")
            if self.server.async_staleness_exponent < 0.0:
                raise ValueError("async_staleness_exponent must be >= 0")
            if self.server.async_backlog_cap < 0:
                raise ValueError("async_backlog_cap must be >= 0")
            if self.server.async_overload_policy not in (
                "drop_oldest", "reject_newest",
            ):
                raise ValueError(
                    f"unknown server.async_overload_policy "
                    f"{self.server.async_overload_policy!r}; expected "
                    f"'drop_oldest' or 'reject_newest'"
                )
            if self.server.async_versions < 1:
                raise ValueError("server.async_versions must be >= 1")
            if self.server.async_versions == 1:
                if (self.server.async_retire_rounds
                        or self.server.async_retire_updates):
                    raise ValueError(
                        "server.async_retire_rounds/async_retire_updates "
                        "require server.async_versions >= 2 (retirement "
                        "rotates version generations; the single-version "
                        "plane has nothing to retire into)"
                    )
                if self.run.strict_versions:
                    raise ValueError(
                        "run.strict_versions requires server."
                        "async_versions >= 2 (there are no version "
                        "generations to enforce on the single-version "
                        "plane)"
                    )
            if self.server.async_retire_rounds < 0:
                raise ValueError("async_retire_rounds must be >= 0")
            if self.server.async_retire_updates < 0:
                raise ValueError("async_retire_updates must be >= 0")
            if not 0.0 < self.server.async_readmit_decay <= 1.0:
                raise ValueError(
                    f"server.async_readmit_decay must be in (0, 1], "
                    f"got {self.server.async_readmit_decay}"
                )
            if self.server.async_versions > 1 and self.run.fuse_rounds > 1:
                raise ValueError(
                    "server.async_versions >= 2 is incompatible with "
                    "run.fuse_rounds (the line scheduler interleaves "
                    "versions across server steps; a fused chunk would "
                    "span lines)"
                )
        else:
            if self.server.async_versions != 1:
                raise ValueError(
                    "server.async_versions requires algorithm='fedbuff' "
                    "(concurrent model versions are an async-buffer "
                    "concept; the synchronous round has exactly one)"
                )
            if self.run.strict_versions:
                raise ValueError(
                    "run.strict_versions requires algorithm='fedbuff' "
                    "with server.async_versions >= 2"
                )
        hier = self.server.hierarchy
        if hier.num_edges < 0:
            raise ValueError("server.hierarchy.num_edges must be >= 0")
        if hier.core_aggregator not in (
            "mean", "median", "trimmed_mean", "krum", "reputation",
        ):
            raise ValueError(
                f"unknown server.hierarchy.core_aggregator "
                f"{hier.core_aggregator!r}"
            )
        if not 0.0 <= hier.core_trim_ratio < 0.5:
            raise ValueError(
                f"server.hierarchy.core_trim_ratio must be in [0, 0.5), "
                f"got {hier.core_trim_ratio}"
            )
        if not 0.0 <= hier.edge_dropout_rate <= 1.0:
            raise ValueError(
                f"server.hierarchy.edge_dropout_rate must be in [0, 1], "
                f"got {hier.edge_dropout_rate}"
            )
        if not 0.0 < hier.core_trust_decay <= 1.0:
            raise ValueError(
                f"server.hierarchy.core_trust_decay must be in (0, 1], "
                f"got {hier.core_trust_decay}"
            )
        if hier.num_edges > 0:
            if self.algorithm == "gossip":
                raise ValueError(
                    "server.hierarchy is incompatible with "
                    "algorithm='gossip' (the decentralized engine has no "
                    "edge/core tiers — its topology IS the aggregation "
                    "structure)"
                )
            if self.algorithm == "fedbuff":
                # the async path: edges group the popped buffer — robust
                # order statistics at the core need the synchronized [E]
                # delta stack the async scheduler never forms
                if hier.core_aggregator not in ("mean", "reputation"):
                    raise ValueError(
                        f"server.hierarchy.core_aggregator="
                        f"{hier.core_aggregator!r} requires the "
                        f"synchronous round program; under "
                        f"algorithm='fedbuff' the async scheduler never "
                        f"forms the synchronized per-edge delta stack "
                        f"order statistics need — use 'mean' or "
                        f"'reputation'"
                    )
            else:
                # the sync path: E invocations of the existing round
                # program per round — everything that assumes exactly
                # one cohort dispatch per round is rejected with its
                # reason
                if self.data.num_clients // hier.num_edges \
                        < self.server.cohort_size:
                    raise ValueError(
                        f"server.hierarchy.num_edges={hier.num_edges}: "
                        f"each edge block holds ~"
                        f"{self.data.num_clients // hier.num_edges} "
                        f"clients but must cover a full cohort of "
                        f"{self.server.cohort_size}"
                    )
                if self.algorithm in ("scaffold", "feddyn"):
                    raise ValueError(
                        "server.hierarchy is incompatible with stateful "
                        "algorithms (scaffold/feddyn scatter per-client "
                        "state once per round; E edge invocations would "
                        "apply E conflicting server-side corrections)"
                    )
                if self.server.error_feedback:
                    raise ValueError(
                        "server.hierarchy is incompatible with "
                        "server.error_feedback (the EF residual store "
                        "rides the single-cohort round program)"
                    )
                if self.server.secure_aggregation:
                    raise ValueError(
                        "server.hierarchy is incompatible with "
                        "server.secure_aggregation (the mask ring is "
                        "committed over ONE round cohort; per-edge "
                        "cohorts would need per-edge key ceremonies)"
                    )
                if self.server.dp_client_noise_multiplier > 0.0:
                    raise ValueError(
                        "server.hierarchy is incompatible with client-"
                        "level DP (noise calibrated for one aggregate "
                        "per round would be added once per edge — E "
                        "times the analyzed mechanism)"
                    )
                if self.dp.enabled:
                    raise ValueError(
                        "server.hierarchy is incompatible with "
                        "dp.enabled (the DP-SGD accountant composes one "
                        "cohort draw per round; E edge cohorts change "
                        "the sampling probability the bound assumes)"
                    )
                if self.run.obs.client_ledger.enabled:
                    raise ValueError(
                        "server.hierarchy is incompatible with "
                        "run.obs.client_ledger (the device-resident "
                        "ledger carry and its paging assume a single "
                        "cohort scatter per round)"
                    )
                if self.server.optimizer != "mean":
                    raise ValueError(
                        "server.hierarchy requires server.optimizer="
                        "'mean' (stateful/adaptive server optimizers "
                        "are not tier-decomposable: each edge would "
                        "evolve its own moment estimates and the core "
                        "delta-space aggregate could not recombine "
                        "them)"
                    )
                if self.server.sampling != "uniform":
                    raise ValueError(
                        f"server.hierarchy draws per-edge cohorts via "
                        f"uniform sampling only; server.sampling="
                        f"{self.server.sampling} is not supported "
                        f"(size weights, Poisson q, adaptive scores, "
                        f"and streaming sketches are parameterized on "
                        f"the GLOBAL population, not per-edge blocks)"
                    )
                if self.data.placement != "hbm":
                    raise ValueError(
                        "server.hierarchy requires data.placement=hbm "
                        "(the stream slab prefetch builds one cohort "
                        "slab per round; per-edge cohorts would race "
                        "it)"
                    )
                if self.run.fuse_rounds > 1:
                    raise ValueError(
                        "server.hierarchy is incompatible with "
                        "run.fuse_rounds (the fused scan compiles one "
                        "cohort per round body; the edge fan-out is a "
                        "host-side loop)"
                    )
                if self.run.shape_buckets.enabled:
                    raise ValueError(
                        "server.hierarchy is incompatible with "
                        "run.shape_buckets (the bucket rung is sized by "
                        "THE round's single sampled cohort; E per-edge "
                        "cohorts would need E rungs per round)"
                    )
                if self.run.host_pipeline == "native":
                    raise ValueError(
                        "server.hierarchy is incompatible with "
                        "run.host_pipeline='native' (the C++ pipeline "
                        "prefetches one cohort per round; use 'auto' or "
                        "'numpy')"
                    )
        if self.algorithm == "scaffold":
            # the option-II control-variate identity cᵢ⁺ = (w₀−w_K)/(K·lr)
            # assumes plain SGD local steps (Karimireddy et al. 2020 §3);
            # momentum breaks it, and DP noise would leak into cᵢ state
            if self.client.optimizer != "sgd" or self.client.momentum != 0.0:
                raise ValueError(
                    "scaffold requires client.optimizer=sgd with momentum=0"
                )
            if self.client.prox_mu > 0.0:
                # the proximal pull μ(w−w₀) is anchored to the ROUND's w₀,
                # so (w₀−w_K)/(K·lr) would bake a round-local term into the
                # persistent cᵢ. (weight_decay is fine: identical across
                # clients, it enters every cᵢ equally and cancels in c−cᵢ.)
                raise ValueError("scaffold is incompatible with client.prox_mu > 0")
            if self.dp.enabled:
                raise ValueError("scaffold is incompatible with dp.enabled")
            if not self._stateful_dtype_ok():
                # cᵢ⁺ divides (w₀−w_K) by K·lr; low-precision anywhere in
                # the trajectory (local w_K OR the server params/delta
                # accumulator) bakes rounding error into the PERSISTENT
                # control variates, which re-enter every local gradient
                raise ValueError(
                    "scaffold requires an f32 parameter trajectory "
                    "(run.param_dtype=float32 and f32 local training)"
                )
            if self.server.aggregator != "weighted_mean":
                # the c update (c += Σδc/N) has no robust equivalent: a
                # poisoned client clipped out of the PARAM update would
                # still poison c_global, which feeds every later round's
                # gradients — the robust aggregator would be a bypassable
                # promise. Forbid rather than mislead.
                raise ValueError(
                    "scaffold is incompatible with robust server.aggregator "
                    "(the control-variate update is a plain mean)"
                )
            if self.server.compression:
                # compressed deltas would move params while cᵢ tracks the
                # UNcompressed trajectory (w₀−w_K)/(K·lr), permanently
                # biasing the corrections
                raise ValueError(
                    "scaffold is incompatible with server.compression"
                )
            if self.server.clip_delta_norm > 0.0:
                # same trajectory-mismatch failure as compression: params
                # move by the CLIPPED delta while cᵢ tracks the raw one
                raise ValueError(
                    "scaffold is incompatible with server.clip_delta_norm"
                )
        if self.run.engine not in ("sharded", "sequential"):
            raise ValueError(f"unknown engine {self.run.engine!r}")
        if self.server.sampling not in (
            "uniform", "weighted", "poisson", "adaptive", "streaming"
        ):
            raise ValueError(f"unknown server.sampling {self.server.sampling!r}")
        if (self.server.sampling == "poisson"
                and self.server.secure_aggregation
                and self.server.secagg_mode == "pairwise"):
            # pairwise secagg's key agreement + Shamir threshold assume a
            # KNOWN cohort that commits keys; Poisson's pad slots are
            # nonexistent clients, which would both skew the threshold
            # semantics (t vs a cap-sized ring) and force per-round
            # recovery work for every unfilled slot. Ring-mode secagg
            # composes fine (pad slots behave as committed-then-dropped).
            raise ValueError(
                "sampling=poisson is incompatible with "
                "secagg_mode='pairwise' (unknown-cohort key agreement); "
                "use secagg_mode='ring'"
            )
        if self.server.aggregator not in (
            "weighted_mean", "median", "trimmed_mean", "krum"
        ):
            raise ValueError(f"unknown server.aggregator {self.server.aggregator!r}")
        if self.server.krum_byzantine < 0:
            raise ValueError(
                f"server.krum_byzantine must be >= 0, "
                f"got {self.server.krum_byzantine}"
            )
        if (self.server.aggregator == "krum"
                and 2 * self.server.krum_byzantine + 2 >= self.server.cohort_size):
            # Blanchard et al. 2017's resilience condition 2f + 2 < n —
            # beyond it Krum provably cannot tolerate f colluders, so a
            # config claiming that defense must not validate
            raise ValueError(
                "krum requires 2*krum_byzantine + 2 < cohort_size "
                "(Blanchard et al. resilience bound)"
            )
        if not 0.0 <= self.server.trim_ratio < 0.5:
            raise ValueError(
                f"server.trim_ratio must be in [0, 0.5), got {self.server.trim_ratio}"
            )
        if self.server.compression not in ("", "topk", "qsgd"):
            raise ValueError(
                f"unknown server.compression {self.server.compression!r}"
            )
        if self.server.error_feedback:
            if not self.server.compression:
                # the memory accumulates what the compressor dropped;
                # with no compressor it is identically zero
                raise ValueError(
                    "server.error_feedback requires server.compression"
                )
            if self.algorithm in ("scaffold", "feddyn", "fedbuff"):
                # scaffold/feddyn own the per-client store (and reject
                # compression outright); fedbuff's async engine has no
                # cohort-synchronous store to scatter into
                raise ValueError(
                    f"server.error_feedback is incompatible with "
                    f"algorithm={self.algorithm!r}"
                )
            if self.server.aggregator != "weighted_mean":
                # EF uploads carry past rounds' residuals — messages of
                # mixed effective timescales with unbounded per-client
                # hidden state; coordinate-wise order statistics over
                # them have no robustness interpretation
                raise ValueError(
                    "server.error_feedback is incompatible with robust "
                    "server.aggregator"
                )
            if self.server.secure_aggregation:
                # secagg's int32 fixed-point range analysis needs the
                # per-round clip bound; C(Δ+e) is not norm-bounded
                raise ValueError(
                    "server.error_feedback is incompatible with "
                    "server.secure_aggregation"
                )
            if self.server.dp_client_noise_multiplier > 0.0:
                # same bound: the DP sensitivity is the clipped delta
                # norm, which the memory term escapes
                raise ValueError(
                    "server.error_feedback is incompatible with "
                    "client-level DP"
                )
        if not 0.0 < self.server.compression_topk_ratio <= 1.0:
            raise ValueError(
                f"server.compression_topk_ratio must be in (0, 1], "
                f"got {self.server.compression_topk_ratio}"
            )
        if self.server.compression_qsgd_levels < 1:
            raise ValueError(
                f"server.compression_qsgd_levels must be >= 1, "
                f"got {self.server.compression_qsgd_levels}"
            )
        if (self.server.compression == "topk"
                and self.server.aggregator != "weighted_mean"):
            # top-k zeroes ~(1-ratio) of each client's coordinates; any
            # coordinate kept by fewer than half the cohort then has a
            # majority of exact zeros in the sorted column, so the
            # coordinate-wise median (and most of the trim window) is 0 —
            # training silently stalls. qsgd (dense, unbiased) composes
            # fine with robust aggregation.
            raise ValueError(
                "server.compression='topk' (sparse) breaks robust "
                "order-statistic aggregators; use qsgd or weighted_mean"
            )
        if self.server.clip_delta_norm < 0.0:
            raise ValueError(
                f"server.clip_delta_norm must be >= 0, "
                f"got {self.server.clip_delta_norm}"
            )
        if self.server.downlink_compression not in ("", "qsgd"):
            raise ValueError(
                f"unknown server.downlink_compression "
                f"{self.server.downlink_compression!r}"
            )
        if self.server.downlink_compression:
            if self.server.downlink_qsgd_levels < 1:
                raise ValueError(
                    f"server.downlink_qsgd_levels must be >= 1, "
                    f"got {self.server.downlink_qsgd_levels}"
                )
            if self.algorithm not in ("fedavg", "fedprox"):
                # scaffold/feddyn's state recursions assume clients
                # received the exact params their c/h corrections track;
                # fedbuff's ring would need per-version quantization
                raise ValueError(
                    "downlink_compression supports fedavg/fedprox only"
                )
        if self.server.dp_client_noise_multiplier < 0.0:
            raise ValueError(
                f"server.dp_client_noise_multiplier must be >= 0, "
                f"got {self.server.dp_client_noise_multiplier}"
            )
        if self.server.dp_client_noise_multiplier > 0.0:
            if self.server.clip_delta_norm <= 0.0:
                # the clip IS the sensitivity bound the noise is
                # calibrated to — without it the guarantee is vacuous
                raise ValueError(
                    "client-level DP requires clip_delta_norm > 0"
                )
            if self.server.aggregator != "weighted_mean":
                # the sensitivity analysis is for the weighted mean;
                # order statistics change the mechanism entirely
                raise ValueError(
                    "client-level DP requires aggregator=weighted_mean"
                )
            if self.server.compression:
                # qsgd's unbiased quantization can inflate a clipped
                # delta's norm past the clip, breaking the sensitivity
                # bound; keep the mechanism sound
                raise ValueError(
                    "client-level DP is incompatible with compression"
                )
            if self.algorithm not in ("fedavg", "fedprox"):
                # stateful trajectories (scaffold/feddyn) would consume
                # noisy aggregates in their c/h recursions; fedbuff's
                # staleness breaks the per-round sampling analysis
                raise ValueError(
                    "client-level DP supports fedavg/fedprox only"
                )
            if self.server.sampling == "weighted":
                # size-proportional sampling raises a big client's
                # per-round inclusion probability above cohort/N, so the
                # accountant's q would understate that client's true
                # RDP spend — the logged ε must be an upper bound for
                # EVERY client (privacy/dp.py contract)
                raise ValueError(
                    "client-level DP requires server.sampling='uniform' "
                    "(weighted sampling breaks the q = cohort/N bound)"
                )
        if self.server.secure_aggregation:
            if self.server.aggregator != "weighted_mean":
                # order statistics need raw per-client deltas — exactly
                # what secure aggregation exists to hide
                raise ValueError(
                    "secure_aggregation is incompatible with robust "
                    "aggregators (they need unmasked per-client deltas)"
                )
            if self.server.compression:
                # masking produces dense uniform int32 — it IS the wire
                # format; sparsity/quantization underneath is meaningless
                raise ValueError(
                    "secure_aggregation is incompatible with "
                    "server.compression"
                )
            if self.algorithm not in ("fedavg", "fedprox"):
                # scaffold/feddyn aggregate per-client state deltas in
                # plaintext (would leak around the masking); fedbuff's
                # buffer membership breaks the per-round participant ring
                raise ValueError(
                    "secure_aggregation supports fedavg/fedprox only"
                )
            if self.server.clip_delta_norm <= 0.0:
                raise ValueError(
                    "secure_aggregation requires clip_delta_norm > 0 "
                    "(bounds the fixed-point range; see ServerConfig)"
                )
            if self.server.secagg_quant_step <= 0.0:
                raise ValueError(
                    f"secagg_quant_step must be > 0, "
                    f"got {self.server.secagg_quant_step}"
                )
            if self.server.secagg_mode not in ("ring", "pairwise"):
                raise ValueError(
                    f"server.secagg_mode must be 'ring' or 'pairwise', "
                    f"got {self.server.secagg_mode!r}"
                )
            t = self.server.secagg_threshold
            if t != 0 and self.server.secagg_mode != "pairwise":
                raise ValueError(
                    "server.secagg_threshold only applies to "
                    "secagg_mode='pairwise'"
                )
            if t != 0 and not 2 <= t <= self.server.cohort_size:
                raise ValueError(
                    f"server.secagg_threshold must be in [2, cohort_size="
                    f"{self.server.cohort_size}], got {t}"
                )
        if not 0.0 <= self.server.straggler_rate <= 1.0:
            raise ValueError(
                f"server.straggler_rate must be in [0, 1], "
                f"got {self.server.straggler_rate}"
            )
        if not 0.0 < self.server.straggler_work <= 1.0:
            raise ValueError(
                f"server.straggler_work must be in (0, 1], "
                f"got {self.server.straggler_work}"
            )
        if self.server.client_state_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"unknown server.client_state_dtype "
                f"{self.server.client_state_dtype!r}"
            )
        if self.run.host_pipeline not in ("auto", "native", "numpy"):
            raise ValueError(f"unknown run.host_pipeline {self.run.host_pipeline!r}")
        if self.run.control_plane not in ("host", "device"):
            raise ValueError(
                f"unknown run.control_plane {self.run.control_plane!r}; "
                f"allowed: host | device"
            )
        if self.run.control_plane == "device":
            # the device plane derives the whole schedule in-program
            # from (seed, round) — anything that injects per-round HOST
            # state into the schedule (adaptive scores, fedbuff queues,
            # secagg key protocols, host_rng failure draws) cannot
            # lower and is rejected with its reason (capability matrix
            # feature `control_plane_device`)
            if self.server.sampling != "uniform":
                raise ValueError(
                    f"run.control_plane='device' requires server."
                    f"sampling='uniform' (got {self.server.sampling!r}: "
                    f"weighted/poisson draw host-RNG shapes and "
                    f"adaptive/streaming need per-round host score "
                    f"state — they stay host-fed)"
                )
            if self.algorithm not in ("fedavg", "fedprox"):
                raise ValueError(
                    f"run.control_plane='device' supports fedavg/"
                    f"fedprox only (got {self.algorithm!r}: scaffold/"
                    f"feddyn thread host-gathered per-client state and "
                    f"the fedbuff/gossip schedulers are host-resident)"
                )
            if self.run.engine not in ("sharded", "sequential"):
                raise ValueError(
                    f"run.control_plane='device' requires run.engine="
                    f"sharded or sequential, got {self.run.engine!r}"
                )
            if self.data.placement != "hbm":
                raise ValueError(
                    "run.control_plane='device' requires data.placement="
                    "'hbm' (stream slabs are built per round on host)"
                )
            if self.server.hierarchy.num_edges > 0:
                raise ValueError(
                    "run.control_plane='device' is incompatible with "
                    "server.hierarchy (edge partitioning is a host "
                    "scheduler)"
                )
            if self.server.secure_aggregation:
                raise ValueError(
                    "run.control_plane='device' is incompatible with "
                    "secure_aggregation (per-round key protocol is "
                    "host I/O)"
                )
            if self.attack.kind:
                raise ValueError(
                    "run.control_plane='device' is incompatible with "
                    "attack simulation (byzantine masks are host-drawn "
                    "per round)"
                )
            if self.server.error_feedback:
                raise ValueError(
                    "run.control_plane='device' is incompatible with "
                    "server.error_feedback (the EF store gathers by "
                    "host-assigned rows)"
                )
            if self.server.straggler_rate > 0 or self.server.dropout_rate > 0:
                raise ValueError(
                    "run.control_plane='device' is incompatible with "
                    "server.straggler_rate/dropout_rate (host-RNG "
                    "failure draws; use run.churn's seed-pure planes "
                    "instead — they lower)"
                )
            if self.run.shape_buckets.enabled:
                raise ValueError(
                    "run.control_plane='device' is incompatible with "
                    "run.shape_buckets (per-round grid re-shaping is a "
                    "host decision; the device program has ONE shape)"
                )
            if self.run.host_pipeline == "native":
                raise ValueError(
                    "run.control_plane='device' is incompatible with "
                    "run.host_pipeline='native' (there is no host slab "
                    "pipeline to accelerate)"
                )
            if self.run.churn.enabled and self.run.churn.trace:
                raise ValueError(
                    "run.control_plane='device' is incompatible with "
                    "run.churn.trace (trace playback reads a host "
                    "memmap; the analytic diurnal planes lower)"
                )
            cl_dev = self.run.obs.client_ledger
            if (cl_dev.enabled and 0 < cl_dev.hot_capacity
                    < self.data.num_clients):
                raise ValueError(
                    "run.control_plane='device' requires the DENSE "
                    "client ledger (hot_capacity=0 or >= num_clients): "
                    "paged slot assignment is a host-stateful remap"
                )
            if self.run.churn.enabled:
                cells = self.server.num_rounds * self.data.num_clients
                if cells > 4_194_304:
                    raise ValueError(
                        f"run.control_plane='device' with churn "
                        f"precomputes a [num_rounds, num_clients] "
                        f"availability-threshold table; {cells} cells "
                        f"exceeds the 4194304 bound — shorten the run, "
                        f"shrink the federation, or use "
                        f"control_plane='host'"
                    )
        if self.run.cohort_layout not in ("spatial", "megabatch"):
            raise ValueError(
                f"unknown run.cohort_layout {self.run.cohort_layout!r}; "
                f"allowed: spatial | megabatch"
            )
        if self.run.cohort_layout == "megabatch":
            if self.algorithm in ("scaffold", "feddyn"):
                # the stateful algorithms thread per-client correction
                # trees (c − cᵢ / −gᵢ) through the per-block vmap; the
                # megabatch block trains the whole lane from ONE shared
                # weight replica at step 0, which has no per-client
                # correction slot — and their f32-trajectory constraints
                # make the layout's bf16 megabatch target moot anyway
                raise ValueError(
                    f"run.cohort_layout='megabatch' is incompatible with "
                    f"algorithm={self.algorithm!r} (stateful per-client "
                    f"correction trees are threaded through the spatial "
                    f"per-block scan)"
                )
            if self.algorithm in ("gossip", "fedbuff"):
                # their engines own the round shape (replica stack /
                # staleness ring) — there is no lane-owned cohort block
                # to megabatch
                raise ValueError(
                    f"run.cohort_layout='megabatch' is incompatible with "
                    f"algorithm={self.algorithm!r} (no lane-owned cohort "
                    f"block; the gossip/fedbuff engines own the round "
                    f"shape)"
                )
            if self.run.batch_shards > 1:
                # the megabatch flattens [K_local, batch] into the GEMM
                # row axis — exactly the axis a batch-sharded mesh
                # splits across chips; the two layouts are rivals for
                # the same dimension
                raise ValueError(
                    "run.cohort_layout='megabatch' is incompatible with "
                    "run.batch_shards > 1 (the megabatch rows are the "
                    "axis the batch mesh shards)"
                )
            if self.run.client_vmap_width >= 2:
                # the layout owns the in-lane batching (whole lane as
                # one block); a narrower explicit width would silently
                # contradict it — reject rather than reinterpret
                raise ValueError(
                    f"run.cohort_layout='megabatch' owns the in-lane "
                    f"batching (the whole lane trains as one block); "
                    f"leave run.client_vmap_width at 1 or 0, got "
                    f"{self.run.client_vmap_width}"
                )
        if self.run.scan_unroll < 1:
            raise ValueError(
                f"run.scan_unroll must be >= 1, got {self.run.scan_unroll}"
            )
        if not 0.0 < self.data.synthetic_template_weight <= 1.0:
            raise ValueError(
                f"data.synthetic_template_weight must be in (0, 1], "
                f"got {self.data.synthetic_template_weight}"
            )
        f = self.run.fuse_rounds
        if f < 1:
            raise ValueError(f"run.fuse_rounds must be >= 1, got {f}")
        if f > 1:
            if self.run.engine != "sharded":
                raise ValueError("fuse_rounds > 1 requires run.engine=sharded")
            if self.algorithm not in ("fedavg", "fedprox"):
                raise ValueError(
                    "fuse_rounds > 1 supports fedavg/fedprox only "
                    "(the scaffold/feddyn c_global recursion and the "
                    "fedbuff/gossip schedulers cannot ride the fused "
                    "scan carry)"
                )
            if self.server.secure_aggregation:
                # the pairwise seed matrix is a per-round host PROTOCOL
                # output (DH agreement + Shamir recovery of the realized
                # dropout set, discovered only after uploads) — it
                # cannot be precomputed into a stacked scan input.
                # Robust aggregators, upload attacks, and error
                # feedback all fuse (the delta stack stays private to
                # the scan body; the EF store rides the scan carry).
                raise ValueError(
                    "fuse_rounds > 1 is incompatible with "
                    "secure_aggregation (per-round key-protocol host "
                    "I/O cannot ride the fused scan)"
                )
            # data.placement="stream" composes since the client-store PR:
            # the fused chunk gathers ONE union slab over its sub-rounds'
            # cohorts (static rows = fuse × slab rows) and remaps the
            # stacked index tensors into it — the engine still sees a
            # single corpus input per dispatch.
            if self.server.num_rounds % f:
                raise ValueError(
                    f"fuse_rounds={f} must divide num_rounds="
                    f"{self.server.num_rounds}"
                )
            for name in ("eval_every", "checkpoint_every"):
                v = getattr(self.server, name)
                if v and v % f:
                    raise ValueError(
                        f"fuse_rounds={f} must divide server.{name}={v} "
                        f"(evals/saves land on chunk boundaries)"
                    )
            if self.run.profile_round >= 0 and self.run.profile_round % f:
                raise ValueError(
                    f"run.profile_round={self.run.profile_round} must be "
                    f"a fuse_rounds={f} chunk boundary (the fit loop "
                    f"steps by chunks; an unaligned value would silently "
                    f"never trigger)"
                )
        sb = self.run.shape_buckets
        if sb.base <= 1.0:
            raise ValueError(
                f"run.shape_buckets.base must be > 1, got {sb.base}"
            )
        if sb.count < 1:
            raise ValueError(
                f"run.shape_buckets.count must be >= 1, got {sb.count}"
            )
        if sb.enabled:
            if self.algorithm in ("fedbuff", "gossip"):
                # fedbuff's in-flight queue and gossip's all-clients
                # round own their own shapes — there is no sampled
                # cohort for the ladder to size against
                raise ValueError(
                    f"run.shape_buckets is incompatible with "
                    f"algorithm={self.algorithm!r} (no sampled cohort "
                    f"to size the step ladder against)"
                )
            if self.dp.enabled:
                # local DP-SGD derives per-step noise keys by POSITION
                # in the padded step grid (split(rng, steps)); trimming
                # padded steps would shift the noise stream of every
                # epoch after the first, breaking the bucketed==full
                # bitwise contract
                raise ValueError(
                    "run.shape_buckets is incompatible with dp.enabled "
                    "(per-step DP noise keys are positional in the "
                    "padded step grid — trimming it shifts the streams)"
                )
            if self.server.straggler_rate > 0.0:
                # straggler truncation cuts at a fraction of the FULL
                # grid's steps; on a trimmed grid the same fraction cuts
                # different examples, so bucketed != full
                raise ValueError(
                    "run.shape_buckets is incompatible with "
                    "server.straggler_rate > 0 (straggler truncation is "
                    "parameterized on the full-shape step grid)"
                )
            if self.run.host_pipeline == "native":
                # the C++ pipeline is constructed for ONE fixed
                # [steps, batch] grid and its own RNG streams; a
                # bucketed run would silently change schedules vs the
                # buckets-off run. 'auto' degrades to the NumPy path.
                raise ValueError(
                    "run.shape_buckets is incompatible with "
                    "run.host_pipeline='native' (the C++ pipeline "
                    "builds one fixed grid); use 'auto' or 'numpy'"
                )
        if self.dp.clipping not in ("microbatch", "two_pass"):
            raise ValueError(
                f"unknown dp.clipping {self.dp.clipping!r}"
            )
        lora = self.model.lora
        if lora.enabled:
            from colearn_federated_learning_tpu.models.lora import (
                LORA_SUPPORTED,
                LORA_TARGETS,
            )

            if self.model.name not in LORA_SUPPORTED:
                raise ValueError(
                    f"model.lora is not supported for model "
                    f"{self.model.name!r}: no transformer-block "
                    f"injection map; supported: "
                    f"{', '.join(LORA_SUPPORTED)}"
                )
            if lora.rank < 1:
                raise ValueError(
                    f"model.lora.rank must be >= 1, got {lora.rank}"
                )
            if lora.alpha <= 0.0:
                raise ValueError(
                    f"model.lora.alpha must be > 0, got {lora.alpha}"
                )
            if lora.target not in LORA_TARGETS:
                raise ValueError(
                    f"unknown model.lora.target {lora.target!r}; "
                    f"allowed: {', '.join(LORA_TARGETS)}"
                )
        atk = self.attack
        if atk.kind:
            from colearn_federated_learning_tpu.server.attacks import (
                ATTACK_KINDS,
                UPLOAD_ATTACKS,
            )

            if atk.kind not in ATTACK_KINDS:
                raise ValueError(
                    f"unknown attack.kind {atk.kind!r}; "
                    f"known: {sorted(ATTACK_KINDS)}"
                )
            if not 0.0 < atk.fraction < 1.0:
                raise ValueError(
                    f"attack.fraction must be in (0, 1), got {atk.fraction}"
                )
            if atk.scale <= 0.0:
                raise ValueError(
                    f"attack.scale must be > 0, got {atk.scale}"
                )
            if atk.eps < 0.0:
                raise ValueError(
                    f"attack.eps must be >= 0, got {atk.eps}"
                )
            # pairing rejections (the _check_engine_compat mirror — each
            # combination is unsound, not merely unimplemented):
            if self.server.secure_aggregation:
                raise ValueError(
                    "attack simulation is incompatible with "
                    "secure_aggregation: masking hides exactly the "
                    "per-client uploads the attack transform acts on, "
                    "and a Byzantine upload breaks the honest-clipping "
                    "int32 range analysis"
                )
            if self.server.dp_client_noise_multiplier > 0.0:
                raise ValueError(
                    "attack simulation is incompatible with client-level "
                    "DP: the sensitivity analysis assumes every upload "
                    "honors the clip bound — a Byzantine upload voids "
                    "the reported dp_client_epsilon"
                )
            if self.dp.enabled:
                raise ValueError(
                    "attack simulation is incompatible with dp.enabled: "
                    "the example-level accountant assumes every client "
                    "runs the DP-SGD mechanism, which a Byzantine client "
                    "does not — the reported dp_epsilon would be "
                    "misleading"
                )
            if self.algorithm in ("scaffold", "feddyn"):
                raise ValueError(
                    f"attack simulation is incompatible with "
                    f"algorithm={self.algorithm!r}: poisoned uploads "
                    f"enter the persistent c/h state through a plain "
                    f"mean the robust stack cannot defend (same "
                    f"reasoning as the robust-aggregator rejection)"
                )
            if self.algorithm == "fedbuff":
                raise ValueError(
                    "attack simulation is incompatible with "
                    "algorithm='fedbuff': the async buffer has no "
                    "per-cohort upload stack to transform, and "
                    "staleness-decayed weights have no Byzantine "
                    "semantics"
                )
            if self.server.error_feedback:
                raise ValueError(
                    "attack simulation is incompatible with "
                    "error_feedback: a Byzantine client's residual "
                    "memory is unbounded hidden state carried across "
                    "rounds"
                )
            if atk.kind == "label_flip" and self.model.num_classes < 2:
                raise ValueError(
                    "attack.kind='label_flip' requires a classification "
                    "label space (model.num_classes >= 2)"
                )
            if atk.kind in UPLOAD_ATTACKS:
                if self.algorithm == "gossip" and atk.kind == "alie":
                    raise ValueError(
                        "attack.kind='alie' is incompatible with "
                        "algorithm='gossip': alie sizes its perturbation "
                        "from cohort-wide statistics a decentralized "
                        "attacker cannot observe"
                    )
        if self.data.synthetic_task not in ("template", "template_pair"):
            raise ValueError(
                f"unknown data.synthetic_task {self.data.synthetic_task!r}"
            )
        if not 0.0 <= self.data.synthetic_label_noise < 1.0:
            raise ValueError(
                f"data.synthetic_label_noise must be in [0, 1), "
                f"got {self.data.synthetic_label_noise}"
            )
        if self.data.placement not in ("hbm", "stream"):
            raise ValueError(f"unknown data.placement {self.data.placement!r}")
        # dtype strings are resolved through a fixed table deep in the
        # driver — without this check a typo ("bf16") surfaces as an
        # opaque KeyError/jnp.dtype error far from the config
        _DTYPE_NAMES = ("float32", "bfloat16", "float16")
        for f in ("param_dtype", "compute_dtype"):
            if getattr(self.run, f) not in _DTYPE_NAMES:
                raise ValueError(
                    f"unknown run.{f} {getattr(self.run, f)!r}; "
                    f"allowed: {', '.join(_DTYPE_NAMES)}"
                )
        if self.run.local_param_dtype not in ("",) + _DTYPE_NAMES:
            raise ValueError(
                f"unknown run.local_param_dtype "
                f"{self.run.local_param_dtype!r}; allowed: '' (inherit "
                f"run.param_dtype), {', '.join(_DTYPE_NAMES)}"
            )
        if self.server.fused_apply:
            if self.server.optimizer not in ("mean", "fedavgm"):
                # the kernel's one-pass FMA chain is exactly
                # sgd(+momentum); fedadam/fedyogi second-moment state
                # has no single-pass expression
                raise ValueError(
                    "server.fused_apply supports server.optimizer="
                    "'mean' or 'fedavgm' only (the pallas kernel "
                    "implements the sgd(+momentum) update); got "
                    f"{self.server.optimizer!r}"
                )
            if self.algorithm in ("scaffold", "feddyn", "gossip"):
                # scaffold/feddyn interleave their c/h state recursions
                # with the apply (feddyn bypasses the server optimizer
                # entirely); gossip has no server apply at all
                raise ValueError(
                    f"server.fused_apply is incompatible with "
                    f"algorithm={self.algorithm!r} (stateful algorithms "
                    f"own the server step; gossip has no server apply)"
                )
        obs = self.run.obs
        if obs.on_unhealthy not in ("warn", "abort", "checkpoint_abort"):
            raise ValueError(
                f"unknown run.obs.on_unhealthy {obs.on_unhealthy!r}; "
                f"expected warn | abort | checkpoint_abort"
            )
        if obs.divergence_factor != 0.0 and obs.divergence_factor <= 1.0:
            # a factor in (0, 1] would flag every round at or above the
            # best loss — i.e. immediately and forever
            raise ValueError(
                f"run.obs.divergence_factor must be 0 (off) or > 1, "
                f"got {obs.divergence_factor}"
            )
        if obs.trace and not obs.spans:
            raise ValueError(
                "run.obs.trace=true requires run.obs.spans=true (the "
                "trace is built from the spans)"
            )
        if obs.trace_max_events < 0:
            raise ValueError(
                f"run.obs.trace_max_events must be >= 0, "
                f"got {obs.trace_max_events}"
            )
        if obs.phase_cost_flops not in ("analytic", "xla"):
            raise ValueError(
                f"unknown run.obs.phase_cost_flops "
                f"{obs.phase_cost_flops!r}; expected 'analytic' or 'xla'"
            )
        if obs.hbm_budget_mb < 0:
            raise ValueError(
                f"run.obs.hbm_budget_mb must be >= 0, "
                f"got {obs.hbm_budget_mb}"
            )
        if obs.hbm_budget_mb > 0 and not obs.executables:
            raise ValueError(
                "run.obs.hbm_budget_mb requires run.obs.executables "
                "(the budget check reads the registry's predicted peaks)"
            )
        dg = obs.digest
        if dg.every < 1:
            raise ValueError(
                f"run.obs.digest.every must be >= 1, got {dg.every}"
            )
        if (dg.enabled and self.run.fuse_rounds > 1
                and dg.every % self.run.fuse_rounds):
            # digest boundaries force a metrics flush; the fit loop
            # steps by chunks, so an unaligned cadence would silently
            # never fire (same contract as eval_every/checkpoint_every)
            raise ValueError(
                f"run.obs.digest.every ({dg.every}) must be a "
                f"fuse_rounds={self.run.fuse_rounds} multiple (digest "
                f"boundaries land on chunk ends)"
            )
        pop = obs.population
        if not 4 <= pop.hll_bits <= 18:
            raise ValueError(
                f"run.obs.population.hll_bits must be in [4, 18], "
                f"got {pop.hll_bits}"
            )
        if pop.top_k < 1:
            raise ValueError(
                f"run.obs.population.top_k must be >= 1, got {pop.top_k}"
            )
        if pop.recency_capacity < 1:
            raise ValueError(
                f"run.obs.population.recency_capacity must be >= 1, "
                f"got {pop.recency_capacity}"
            )
        cl = obs.client_ledger
        if not 0.0 < cl.ema <= 1.0:
            raise ValueError(
                f"run.obs.client_ledger.ema must be in (0, 1], got {cl.ema}"
            )
        if cl.zmax <= 0.0:
            raise ValueError(
                f"run.obs.client_ledger.zmax must be > 0, got {cl.zmax}"
            )
        if cl.log_every < 0:
            raise ValueError(
                f"run.obs.client_ledger.log_every must be >= 0, "
                f"got {cl.log_every}"
            )
        if cl.hot_capacity < 0:
            raise ValueError(
                f"run.obs.client_ledger.hot_capacity must be >= 0, "
                f"got {cl.hot_capacity}"
            )
        if cl.enabled and cl.hot_capacity > 0 and self.server.error_feedback:
            # the EF residual store is indexed by TRUE client ids and
            # shares the engines' cohort-id input with the ledger — the
            # pager's slot remap would scatter residuals to wrong rows
            raise ValueError(
                "run.obs.client_ledger.hot_capacity > 0 is incompatible "
                "with server.error_feedback (the EF store is indexed by "
                "true client ids on the same cohort-id input the paged "
                "ledger remaps to hot-set slots)"
            )
        if cl.enabled:
            if self.server.secure_aggregation:
                # the ledger computes per-client upload statistics —
                # exactly the information secure aggregation exists to
                # hide from the server
                raise ValueError(
                    "run.obs.client_ledger is incompatible with "
                    "secure_aggregation (per-client upload statistics "
                    "are what masking hides)"
                )
            if self.server.dp_client_noise_multiplier > 0.0:
                # client-level DP releases only the noised aggregate;
                # a per-client statistics side channel voids it
                raise ValueError(
                    "run.obs.client_ledger is incompatible with "
                    "client-level DP (per-client statistics are a "
                    "disclosure channel the DP analysis does not cover)"
                )
            if self.algorithm == "gossip":
                raise ValueError(
                    "run.obs.client_ledger is incompatible with "
                    "algorithm='gossip' (no server-visible upload "
                    "stack to compute stats over — neighbour messages "
                    "are whole replicas)"
                )
            if self.algorithm == "fedbuff" and cl.hot_capacity > 0:
                # per-INSERT stats over each server step's popped
                # buffer feed the dense ledger fine (fedbuff × ledger
                # is supported since the churn PR); the pager's
                # id→hot-slot remap is wired into the synchronous
                # dispatch paths only
                raise ValueError(
                    "run.obs.client_ledger.hot_capacity > 0 (paged "
                    "ledger) is not supported with algorithm='fedbuff' "
                    "— the async scheduler ships true client ids; use "
                    "the dense ledger (hot_capacity=0)"
                )
            if self.algorithm in ("scaffold", "feddyn"):
                raise ValueError(
                    f"run.obs.client_ledger is incompatible with "
                    f"algorithm={self.algorithm!r} (the stateful "
                    f"engines own the per-client state path; the "
                    f"attack/robust stacks the ledger audits are "
                    f"rejected there anyway)"
                )
        rep = self.server.reputation
        if not 0.0 < rep.floor < 1.0:
            raise ValueError(
                f"server.reputation.floor must be in (0, 1), got {rep.floor}"
            )
        if rep.strength <= 0.0:
            raise ValueError(
                f"server.reputation.strength must be > 0, "
                f"got {rep.strength}"
            )
        if rep.z_gain < 0.0:
            raise ValueError(
                f"server.reputation.z_gain must be >= 0, got {rep.z_gain}"
            )
        if rep.enabled and not cl.enabled:
            # trust weights are a pure function of the ledger rows; the
            # ledger's own pairing rejections above (secure aggregation,
            # client-level DP, gossip/fedbuff, scaffold/feddyn) therefore
            # exclude exactly the combinations that would be unsound for
            # reputation too — its stats channel IS the ledger's
            raise ValueError(
                "server.reputation requires run.obs.client_ledger."
                "enabled (trust weights are computed from the "
                "device-resident ledger rows; enabling the ledger also "
                "applies its pairing exclusions — secagg, client-level "
                "DP, gossip, stateful algorithms)"
            )
        if self.server.sampling in ("adaptive", "streaming"):
            ad = self.server.adaptive
            if not 0.0 < ad.explore <= 1.0:
                raise ValueError(
                    f"server.adaptive.explore must be in (0, 1], "
                    f"got {ad.explore}"
                )
            if ad.staleness_gain < 0.0:
                raise ValueError(
                    f"server.adaptive.staleness_gain must be >= 0, "
                    f"got {ad.staleness_gain}"
                )
            if ad.flag_suppress < 0.0:
                raise ValueError(
                    f"server.adaptive.flag_suppress must be >= 0, "
                    f"got {ad.flag_suppress}"
                )
            if ad.sketch_size < 1:
                raise ValueError(
                    f"server.adaptive.sketch_size must be >= 1, "
                    f"got {ad.sketch_size}"
                )
        if self.server.sampling == "streaming" and cl.enabled and cl.log_every >= 1:
            # ledger evidence flows into the streaming sketch at the
            # same snapshot-refresh boundaries as "adaptive" — the same
            # schedule-purity constraints apply (the cohort must be a
            # pure function of (seed, round, sketch) so prefetch/resume
            # replay it; the prefetch worker drains itself at refresh
            # boundaries, which is why placement=stream IS allowed here)
            if self.run.fuse_rounds > 1 and cl.log_every % self.run.fuse_rounds:
                raise ValueError(
                    f"server.sampling='streaming' with run.fuse_rounds="
                    f"{self.run.fuse_rounds} requires client_ledger."
                    f"log_every ({cl.log_every}) to be a fuse_rounds "
                    f"multiple (sketch refreshes must land on fused-"
                    f"chunk boundaries)"
                )
            if self.run.shape_buckets.enabled:
                raise ValueError(
                    "server.sampling='streaming' with ledger-fed "
                    "sketches is incompatible with run.shape_buckets "
                    "(the bucket rung must be a pure function of "
                    "(seed, round); sketch-scored cohorts depend on "
                    "the ledger snapshot)"
                )
            if self.run.host_pipeline == "native":
                raise ValueError(
                    "server.sampling='streaming' with ledger-fed "
                    "sketches is incompatible with run.host_pipeline="
                    "'native' (the C++ pipeline prefetches future "
                    "cohorts ahead of sketch refreshes); use 'auto' or "
                    "'numpy'"
                )
        ch = self.run.churn
        if ch.diurnal_period < 1:
            raise ValueError(
                f"run.churn.diurnal_period must be >= 1, "
                f"got {ch.diurnal_period}"
            )
        if not 0.0 <= ch.diurnal_amplitude <= 1.0:
            raise ValueError(
                f"run.churn.diurnal_amplitude must be in [0, 1], "
                f"got {ch.diurnal_amplitude}"
            )
        if not 0.0 < ch.base_availability <= 1.0:
            raise ValueError(
                f"run.churn.base_availability must be in (0, 1], "
                f"got {ch.base_availability}"
            )
        if not 0.0 < ch.min_availability <= 1.0:
            raise ValueError(
                f"run.churn.min_availability must be in (0, 1], "
                f"got {ch.min_availability}"
            )
        if not 0.0 <= ch.dropout_hazard < 1.0:
            raise ValueError(
                f"run.churn.dropout_hazard must be in [0, 1), "
                f"got {ch.dropout_hazard}"
            )
        if not 0.0 <= ch.crash_rate < 1.0:
            raise ValueError(
                f"run.churn.crash_rate must be in [0, 1), "
                f"got {ch.crash_rate}"
            )
        if ch.trace and not ch.enabled:
            raise ValueError(
                "run.churn.trace requires run.churn.enabled (trace "
                "replay is an availability model; enabled=false must "
                "construct nothing)"
            )
        if ch.enabled:
            if self.algorithm == "gossip":
                raise ValueError(
                    "run.churn is incompatible with algorithm='gossip' "
                    "(every client trains every round — there is no "
                    "availability-gated cohort draw; gossip's own "
                    "dropout_rate models link failure)"
                )
            if self.run.shape_buckets.enabled:
                # same reason as the straggler rejection: crash
                # truncation cuts at a fraction of the FULL grid's
                # steps; a trimmed grid would cut different examples
                raise ValueError(
                    "run.churn is incompatible with run.shape_buckets "
                    "(crash-mid-round truncation is parameterized on "
                    "the full-shape step grid, like stragglers)"
                )
            if self.server.sampling in ("weighted", "poisson", "adaptive"):
                raise ValueError(
                    f"run.churn gates the uniform and streaming cohort "
                    f"samplers only; server.sampling="
                    f"{self.server.sampling} is not supported (static "
                    f"size weights and the Poisson DP-exact q assume "
                    f"unconditional draws; dense adaptive scores would "
                    f"need availability renormalization)"
                )
        st = self.data.store
        if st.gather_workers < 0:
            raise ValueError(
                f"data.store.gather_workers must be >= 0 (0 = auto), "
                f"got {st.gather_workers}"
            )
        if st.eval_buffer_mb < 1:
            raise ValueError(
                f"data.store.eval_buffer_mb must be >= 1, "
                f"got {st.eval_buffer_mb}"
            )
        if st.dir:
            if self.attack.kind == "label_flip":
                raise ValueError(
                    "attack.kind='label_flip' is incompatible with "
                    "data.store (label poisoning mutates training labels "
                    "host-side; the store's records are a read-only mmap)"
                )
            if self.run.host_pipeline == "native":
                raise ValueError(
                    "data.store is incompatible with run.host_pipeline="
                    "'native' (the C++ pipeline materializes the full "
                    "per-client index lists the store exists to avoid); "
                    "use 'auto' or 'numpy'"
                )
        if self.server.sampling == "adaptive":
            if not cl.enabled or cl.log_every < 1:
                # the sampler's scores refresh from the periodic ledger
                # snapshots; without a cadence they would stay frozen at
                # the all-unseen prior forever
                raise ValueError(
                    "server.sampling='adaptive' requires "
                    "run.obs.client_ledger.enabled with log_every >= 1 "
                    "(utility scores refresh from the periodic ledger "
                    "snapshots; the ledger's pairing exclusions apply)"
                )
            if self.run.fuse_rounds > 1 and cl.log_every % self.run.fuse_rounds:
                # the ledger only materializes at chunk boundaries under
                # fusion; a mid-chunk refresh round would have nothing
                # deterministic to fetch
                raise ValueError(
                    f"server.sampling='adaptive' with run.fuse_rounds="
                    f"{self.run.fuse_rounds} requires client_ledger."
                    f"log_every ({cl.log_every}) to be a fuse_rounds "
                    f"multiple (snapshot refreshes must land on fused-"
                    f"chunk boundaries)"
                )
            if self.data.placement != "hbm":
                # the stream-mode prefetch worker builds round r+1's
                # inputs while round r runs; a snapshot refresh between
                # build and consumption would sample a cohort a resumed
                # run could not replay
                raise ValueError(
                    "server.sampling='adaptive' requires "
                    "data.placement=hbm (the stream prefetch worker "
                    "races the ledger-snapshot refresh, breaking the "
                    "(seed, round, snapshot)-pure schedule)"
                )
            if self.run.shape_buckets.enabled:
                # the bucket ladder's contract is that the cohort (and
                # hence the rung) is a pure function of (seed, round) —
                # adaptive cohorts additionally depend on the ledger
                raise ValueError(
                    "server.sampling='adaptive' is incompatible with "
                    "run.shape_buckets (the bucket rung must be a pure "
                    "function of (seed, round); adaptive cohorts depend "
                    "on the ledger snapshot)"
                )
            if self.run.host_pipeline == "native":
                # the C++ pipeline prefetches FUTURE rounds' cohorts and
                # treats resubmission as a no-op — a snapshot refresh
                # between prefetch and dispatch would silently serve
                # tensors for a stale cohort ('auto' degrades to NumPy)
                raise ValueError(
                    "server.sampling='adaptive' is incompatible with "
                    "run.host_pipeline='native' (the C++ pipeline "
                    "prefetches future cohorts ahead of snapshot "
                    "refreshes); use 'auto' or 'numpy'"
                )
        return self

    # ---- serialization ------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ExperimentConfig":
        def build(dc_cls, sub):
            fields = {f.name: f for f in dataclasses.fields(dc_cls)}
            kwargs = {}
            for k, v in sub.items():
                if k not in fields:
                    raise KeyError(f"unknown config key {k!r} for {dc_cls.__name__}")
                f = fields[k]
                if dataclasses.is_dataclass(f.type) or f.name in _NESTED:
                    kwargs[k] = build(_NESTED[f.name], v)
                else:
                    kwargs[k] = v
            return dc_cls(**kwargs)

        _NESTED = {
            "model": ModelConfig,
            "data": DataConfig,
            "client": ClientConfig,
            "server": ServerConfig,
            "dp": DPConfig,
            "attack": AttackConfig,
            "run": RunConfig,
            "obs": ObsConfig,  # nested under run
            "shape_buckets": ShapeBucketsConfig,  # nested under run
            "churn": ChurnConfig,  # nested under run
            "client_ledger": ClientLedgerConfig,  # nested under run.obs
            "population": PopulationConfig,  # nested under run.obs
            "reputation": ReputationConfig,  # nested under server
            "adaptive": AdaptiveSamplerConfig,  # nested under server
            "hierarchy": HierarchyConfig,  # nested under server
            "store": StoreConfig,  # nested under data
            "lora": LoRAConfig,  # nested under model
        }
        return build(cls, d)

    @classmethod
    def from_yaml(cls, path: str) -> "ExperimentConfig":
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    def to_yaml(self, path: str) -> None:
        with open(path, "w") as f:
            yaml.safe_dump(self.to_dict(), f, sort_keys=False)

    def apply_overrides(self, overrides: Dict[str, Any]) -> "ExperimentConfig":
        """Apply dotted-path overrides like {'server.num_rounds': 5}.

        Paths may descend into dict-typed fields (``model.kwargs.seq_len``).
        """
        for dotted, value in overrides.items():
            obj = self
            *head, last = dotted.split(".")
            for part in head:
                if isinstance(obj, dict):
                    obj = obj[part]
                elif hasattr(obj, part):
                    obj = getattr(obj, part)
                else:
                    # unknown section must fail the same clean way as an
                    # unknown leaf (CLI turns KeyError into exit 2)
                    raise KeyError(f"unknown config path {dotted!r}")
            if isinstance(obj, dict):
                obj[last] = value
                continue
            if not hasattr(obj, last):
                raise KeyError(f"unknown config path {dotted!r}")
            current = getattr(obj, last)
            if current is not None and not isinstance(current, dict):
                value = type(current)(value) if not isinstance(value, type(current)) else value
            setattr(obj, last, value)
        return self


# ---------------------------------------------------------------------------
# The five named BASELINE configs (BASELINE.json:7-11)
# ---------------------------------------------------------------------------


def _mnist_fedavg_2() -> ExperimentConfig:
    """BASELINE config #1: FedAvg, 2 clients, LeNet-5 on MNIST (CPU smoke)."""
    return ExperimentConfig(
        name="mnist_fedavg_2",
        algorithm="fedavg",
        model=ModelConfig(name="lenet5", num_classes=10),
        data=DataConfig(name="mnist", num_clients=2, partition="iid"),
        client=ClientConfig(local_epochs=1, batch_size=32, lr=0.1),
        server=ServerConfig(num_rounds=20, cohort_size=2),
    )


def _cifar10_fedavg_100() -> ExperimentConfig:
    """BASELINE config #2: FedAvg, 100 clients, ResNet-18 on CIFAR-10 Dirichlet.

    The headline-metric config (BASELINE.json:2): FL rounds/sec and
    client-updates/sec/chip are measured here.
    """
    return ExperimentConfig(
        name="cifar10_fedavg_100",
        algorithm="fedavg",
        model=ModelConfig(name="resnet18", num_classes=10),
        data=DataConfig(
            name="cifar10",
            num_clients=100,
            partition="dirichlet",
            dirichlet_alpha=0.5,
            max_examples_per_client=512,
        ),
        client=ClientConfig(local_epochs=1, batch_size=64, lr=0.05),
        server=ServerConfig(num_rounds=500, cohort_size=16, eval_every=10),
        # megabatch cohort layout (r12): on one chip the whole cohort-16
        # block trains as one fused step — the shared-weight first step
        # feeds the MXU [16·64 = 1024]-row GEMMs where the spatial scan
        # capped every matmul at one client's 64 — the structural answer
        # to the 41.4% MFU plateau (BENCH_r01–r05; ROADMAP item 1)
        run=RunConfig(compute_dtype="bfloat16", local_param_dtype="bfloat16",
                      cohort_layout="megabatch"),
    )


def _cifar10_fedavg_1000() -> ExperimentConfig:
    """The NORTH-STAR scale config (BASELINE.json:5): FedAvg, 1000 clients,
    ResNet-18 on CIFAR-10 Dirichlet non-IID, cohort 64.

    Same per-client workload as the headline ``cifar10_fedavg_100`` so
    the two are directly comparable; only the federation size (1000
    shards over the full 50k-example corpus — real CIFAR-10's
    cardinality, mirrored by the synthetic fallback) and the cohort
    (64) change. At ~50 examples/client the Dirichlet shards are small
    and skewed; ``max_examples_per_client=128`` bounds the static pad
    without truncating any but the largest shards."""
    return ExperimentConfig(
        name="cifar10_fedavg_1000",
        algorithm="fedavg",
        model=ModelConfig(name="resnet18", num_classes=10),
        data=DataConfig(
            name="cifar10",
            num_clients=1000,
            partition="dirichlet",
            dirichlet_alpha=0.5,
            synthetic_train_size=50_000,
            synthetic_test_size=2_000,
            max_examples_per_client=128,
        ),
        client=ClientConfig(local_epochs=1, batch_size=64, lr=0.05),
        server=ServerConfig(num_rounds=1000, cohort_size=64, eval_every=20),
        run=RunConfig(compute_dtype="bfloat16", local_param_dtype="bfloat16",
                      cohort_layout="megabatch"),
    )


def _femnist_fedprox_500() -> ExperimentConfig:
    """BASELINE config #3: FedProx, 500 clients, MobileNetV2 on FEMNIST (LEAF)."""
    return ExperimentConfig(
        name="femnist_fedprox_500",
        algorithm="fedprox",
        model=ModelConfig(name="mobilenetv2", num_classes=62, kwargs={"width_mult": 1.0}),
        data=DataConfig(
            name="femnist",
            num_clients=500,
            partition="natural",
            max_examples_per_client=256,
        ),
        client=ClientConfig(local_epochs=1, batch_size=32, lr=0.03, prox_mu=0.01),
        # cohort 32 adopted from the r5 sweep: 281→337→396→448
        # updates/s/chip at cohorts 8/16/32/64 — MobileNetV2@28 is
        # memory-bound so gains are shallow; 32 takes the +17% without
        # an extreme participation ratio (BASELINE.md r5)
        server=ServerConfig(num_rounds=500, cohort_size=32, eval_every=10),
        run=RunConfig(compute_dtype="bfloat16", local_param_dtype="bfloat16",
                      cohort_layout="megabatch"),
    )


def _shakespeare_fedavg() -> ExperimentConfig:
    """BASELINE config #4: FedAvg, BERT-tiny next-token LM on Shakespeare (LEAF)."""
    return ExperimentConfig(
        name="shakespeare_fedavg",
        algorithm="fedavg",
        model=ModelConfig(
            name="bert_tiny",
            num_classes=0,
            kwargs={"vocab_size": 90, "seq_len": 80},
        ),
        data=DataConfig(
            name="shakespeare",
            num_clients=128,
            partition="natural",
            max_examples_per_client=256,
        ),
        client=ClientConfig(local_epochs=1, batch_size=16, lr=0.5),
        # cohort 32 + fuse 10 adopted from the r5 sweep (VERDICT r4
        # weak-#2): 381→560→722→793 updates/s/chip at cohorts 8/16/32/
        # 64, and multi-round fusion stacks another ~11% on the
        # dispatch-dominated wall clock — 32+fuse measured 801
        # updates/s/chip vs the old config's 381, a 2.1× improvement at
        # a sane 25% participation ratio (BASELINE.md r5). fuse=10
        # divides num_rounds and eval_every (chunk-boundary cadence).
        server=ServerConfig(num_rounds=200, cohort_size=32, eval_every=10),
        # megabatch layout (r12) supersedes the r4 client_vmap_width=0
        # adoption: the whole-lane vmap was worth 7.0 → 6.24 ms/round
        # (BASELINE.md r4); the layout keeps that batched-GEMM shape for
        # the diverged steps AND runs the shared-weight first step as a
        # true [K_local·16]-row megabatch against unbatched weights.
        run=RunConfig(compute_dtype="bfloat16", local_param_dtype="bfloat16",
                      cohort_layout="megabatch", fuse_rounds=10),
    )


def _imagenet_silo_dp() -> ExperimentConfig:
    """BASELINE config #5: cross-silo FedAvg + DP-SGD, ViT-B/16, 32 silos."""
    return ExperimentConfig(
        name="imagenet_silo_dp",
        algorithm="fedavg",
        model=ModelConfig(
            name="vit_b16", num_classes=1000, kwargs={"image_size": 224}
        ),
        data=DataConfig(
            name="imagenet_federated",
            num_clients=32,
            partition="silo",
            max_examples_per_client=1024,
        ),
        client=ClientConfig(local_epochs=1, batch_size=64, lr=0.003, optimizer="adamw"),
        server=ServerConfig(num_rounds=100, cohort_size=32, eval_every=5),
        # microbatch 16: measured ~5% faster than 8 on v5e at 224px; 32 is
        # marginally faster still but near the compile/memory ceiling
        dp=DPConfig(enabled=True, l2_clip=1.0, noise_multiplier=0.8, microbatch_size=16),
        run=RunConfig(compute_dtype="bfloat16", local_param_dtype="bfloat16"),
    )


def _cifar10_gossip_16() -> ExperimentConfig:
    """Beyond-reference: decentralized DFedAvg (algorithm=gossip) at the
    headline workload — 16 clients, ResNet-18 on CIFAR-10 Dirichlet,
    ring topology. Every client trains every round from its OWN replica
    and mixes with its two ring neighbours (a halo exchange on the
    mesh); eval runs on the consensus mean and the consensus distance
    is logged per round. Same per-client workload as
    ``cifar10_fedavg_100`` so the serverless round cost is directly
    comparable to the centralized one."""
    return ExperimentConfig(
        name="cifar10_gossip_16",
        algorithm="gossip",
        model=ModelConfig(name="resnet18", num_classes=10),
        data=DataConfig(
            name="cifar10",
            num_clients=16,
            partition="dirichlet",
            dirichlet_alpha=0.5,
            max_examples_per_client=512,
        ),
        client=ClientConfig(local_epochs=1, batch_size=64, lr=0.05),
        server=ServerConfig(num_rounds=500, cohort_size=16, eval_every=10),
        run=RunConfig(compute_dtype="bfloat16", local_param_dtype="bfloat16"),
    )


def _cifar10_krum_byzantine() -> ExperimentConfig:
    """Beyond-reference: the adversarial workload — the headline
    CIFAR-10 federation under a live sign-flipping adversary (attack.*,
    server/attacks.py) defended by Krum. 2/16 cohort slots are expected
    Byzantine in steady state (fraction 0.125 of 100 clients ≈ 12
    compromised, cohort 16 uniform), matching the krum_byzantine=2
    defense assumption within the Blanchard 2f+2 < n resilience bound.
    The per-round ``byzantine_count`` metric logs the realized count."""
    return ExperimentConfig(
        name="cifar10_krum_byzantine",
        algorithm="fedavg",
        model=ModelConfig(name="resnet18", num_classes=10),
        data=DataConfig(
            name="cifar10",
            num_clients=100,
            partition="dirichlet",
            dirichlet_alpha=0.5,
            max_examples_per_client=512,
        ),
        client=ClientConfig(local_epochs=1, batch_size=64, lr=0.05),
        server=ServerConfig(
            num_rounds=500, cohort_size=16, eval_every=10,
            aggregator="krum", krum_byzantine=2,
        ),
        attack=AttackConfig(kind="sign_flip", fraction=0.125, scale=10.0),
        # megabatch composes with the attacked krum path (the wire stack
        # and robust selection see identical [K, ·] shapes either way —
        # parity-pinned in tests/test_round_engine.py)
        run=RunConfig(compute_dtype="bfloat16", local_param_dtype="bfloat16",
                      cohort_layout="megabatch"),
    )


def _bert_lora_federated() -> ExperimentConfig:
    """Beyond-reference (ROADMAP item 3): million-user-shaped
    transformer federation on adapter uploads — BERT-tiny on the LEAF
    Shakespeare task, 1024 natural-partition clients drawn by the
    O(cohort·log) streaming sampler, with rank-2 attention LoRA so the
    per-client wire message is the adapter factors only (~136× fewer
    upload bytes than the full-delta twin at this geometry; the
    analytic counters log the exact ``wire_reduction_vs_full``). The
    base transformer stays frozen at its seed-derived init; clients
    train only the qkv/attention-output adapters at a hot adapter
    learning rate (adapter-space steps move a ~3k-coordinate subspace,
    so the stable lr sits well above the full-model config's 0.5).
    Scale this up with `colearn store build` + ``data.store.dir`` +
    ``data.placement=stream`` — the bench ships ``bert_lora_1m``, the
    10⁶-client store-backed twin."""
    return ExperimentConfig(
        name="bert_lora_federated",
        algorithm="fedavg",
        model=ModelConfig(
            name="bert_tiny",
            num_classes=0,
            kwargs={"vocab_size": 90, "seq_len": 80},
            lora=LoRAConfig(enabled=True, rank=2, alpha=8.0,
                            target="attention"),
        ),
        data=DataConfig(
            name="shakespeare",
            num_clients=1024,
            partition="natural",
            max_examples_per_client=128,
        ),
        client=ClientConfig(local_epochs=1, batch_size=16, lr=2.0),
        server=ServerConfig(
            num_rounds=200, cohort_size=32, eval_every=10,
            sampling="streaming",
        ),
        # megabatch (r12) supersedes client_vmap_width=0: under LoRA the
        # adapters ARE the params, so the shared-weight first step
        # megabatches the whole frozen-base forward at [K_local·16] rows
        run=RunConfig(compute_dtype="bfloat16", local_param_dtype="bfloat16",
                      cohort_layout="megabatch"),
    )


def _vit_lora_dp() -> ExperimentConfig:
    """Beyond-reference (ROADMAP item 3 follow-up): the cross-silo ViT
    workload on ADAPTER uploads with example-level DP — ``vit_b16``'s
    LoRA injection map (models/lora.py ``LORA_SUPPORTED``) finally
    exercised by a named config. Each of 32 silos trains rank-4
    attention adapters over the frozen ViT-B/16 base under DP-SGD
    (per-example clipping + noise act on the ADAPTER gradients — the
    released coordinates are the ~590k-coordinate adapter subspace
    instead of the 86M-param full model, which both shrinks the wire
    message and concentrates the privacy budget on what actually
    ships). Two-pass clipping keeps the per-example backward
    MXU-batched at 224px. Layout stays spatial: DP's per-example
    gradients multiply activation memory by the microbatch, so a
    cohort-wide megabatch block would trade the MXU win for an HBM
    cliff on this model."""
    return ExperimentConfig(
        name="vit_lora_dp",
        algorithm="fedavg",
        model=ModelConfig(
            name="vit_b16", num_classes=1000, kwargs={"image_size": 224},
            lora=LoRAConfig(enabled=True, rank=4, alpha=8.0,
                            target="attention"),
        ),
        data=DataConfig(
            name="imagenet_federated",
            num_clients=32,
            partition="silo",
            max_examples_per_client=1024,
        ),
        # adamw on the factor pair (the Hu et al. recipe); adapter-space
        # steps move a small subspace, so the lr sits above the
        # full-model silo config's 0.003
        client=ClientConfig(local_epochs=1, batch_size=64, lr=0.01,
                            optimizer="adamw"),
        server=ServerConfig(num_rounds=100, cohort_size=32, eval_every=5),
        dp=DPConfig(enabled=True, l2_clip=1.0, noise_multiplier=0.8,
                    microbatch_size=16, clipping="two_pass"),
        run=RunConfig(compute_dtype="bfloat16", local_param_dtype="bfloat16"),
    )


_NAMED = {
    "mnist_fedavg_2": _mnist_fedavg_2,
    "cifar10_fedavg_100": _cifar10_fedavg_100,
    "cifar10_fedavg_1000": _cifar10_fedavg_1000,
    "femnist_fedprox_500": _femnist_fedprox_500,
    "shakespeare_fedavg": _shakespeare_fedavg,
    "imagenet_silo_dp": _imagenet_silo_dp,
    "cifar10_gossip_16": _cifar10_gossip_16,
    "cifar10_krum_byzantine": _cifar10_krum_byzantine,
    "bert_lora_federated": _bert_lora_federated,
    "vit_lora_dp": _vit_lora_dp,
}


def get_named_config(name: str) -> ExperimentConfig:
    try:
        return _NAMED[name]().validate()
    except KeyError:
        raise KeyError(f"unknown named config {name!r}; known: {sorted(_NAMED)}") from None


def list_named_configs():
    return sorted(_NAMED)


def resolve_config(name_or_path: str, overrides: Optional[Dict[str, Any]] = None) -> ExperimentConfig:
    """Resolve a config by registry name or YAML path, then apply overrides."""
    if name_or_path in _NAMED:
        cfg = get_named_config(name_or_path)
    elif name_or_path.endswith((".yaml", ".yml")) or "/" in name_or_path:
        cfg = ExperimentConfig.from_yaml(name_or_path)
    else:
        raise KeyError(
            f"unknown config {name_or_path!r}; known named configs: "
            f"{sorted(_NAMED)} (or pass a .yaml path)"
        )
    if overrides:
        cfg.apply_overrides(overrides)
    return cfg.validate()
