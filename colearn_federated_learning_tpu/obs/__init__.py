"""Observability layer (round-lifecycle telemetry).

Three pillars, each its own module, all host-side and engine-agnostic:

- :mod:`spans` — a low-overhead context-manager tracer for the round
  lifecycle (host inputs → placement → dispatch → fetch → eval →
  checkpoint, plus engine sub-phases), with per-phase aggregation into
  the metrics JSONL and an optional Chrome-trace/Perfetto export.
  Retraces are attributed via ``jax.monitoring`` compile hooks.
- :mod:`counters` — per-round communication byte accounting (pre/post
  compression, uplink + downlink) and device-memory polling.
- :mod:`health` — NaN/Inf + divergence monitoring over the per-round
  loss with configurable abort / checkpoint-and-abort actions.
- :mod:`ledger` — the per-client forensic ledger
  (``run.obs.client_ledger``): in-program cohort statistics + anomaly
  flags scattered into a device-resident per-client store, periodic
  ``client_ledger`` JSONL records, and the ``colearn clients``
  attack-attribution report.
- :mod:`roofline` — the performance observatory: an analytic per-phase
  FLOP/HBM-byte cost model (``phase_cost`` JSONL records, engine-
  parity-pinned like the wire counters), the ``colearn mfu`` waterfall
  that decomposes headline MFU into padding/host/non-matmul/residual
  components, and the ``colearn bench-report`` trajectory gates over
  ``BENCH_r*.json`` + the checked-in ``BENCH_BUDGETS.json``.
- :mod:`population` — the federation health observatory
  (``run.obs.population``): population/data-plane telemetry for the
  million-client structures — HLL-style unique-client coverage,
  exploration/exploitation draw split, cohort staleness, ledger-pager
  and store-I/O health, participation fairness — as per-flush-window
  ``population_health`` records (count columns engine-parity pinned),
  plus the pure-host ``colearn watch`` live tailer and ``colearn
  population`` report.

Everything is configured through the ``run.obs`` config block
(:class:`~colearn_federated_learning_tpu.config.ObsConfig`); the
``colearn summarize`` CLI subcommand (:mod:`summary`) aggregates a
run's JSONL into a per-phase timing/throughput table.
"""

from colearn_federated_learning_tpu.obs.counters import (  # noqa: F401
    device_memory_stats,
    gossip_round_bytes,
    round_comm_bytes,
    round_host_input_bytes,
    round_shape_stats,
)
from colearn_federated_learning_tpu.obs.health import (  # noqa: F401
    HealthAbortError,
    HealthMonitor,
)
from colearn_federated_learning_tpu.obs.ledger import (  # noqa: F401
    LEDGER_COLS,
    LEDGER_WIDTH,
    STAT_COLS,
    client_round_stats,
    update_ledger,
)
from colearn_federated_learning_tpu.obs.population import (  # noqa: F401
    HLLCounter,
    PopulationTracker,
    SpaceSavingSketch,
)
from colearn_federated_learning_tpu.obs.roofline import (  # noqa: F401
    MXU_TILE_ROWS,
    PEAK_BF16_FLOPS,
    PEAK_F32_FLOPS,
    PEAK_HBM_BYTES_PER_SEC,
    analytic_lora_step_flops,
    analytic_step_flops,
    layout_gemm_rows,
    mfu_basis,
    mxu_tile_pad_fraction,
    round_phase_costs,
    waterfall,
)
from colearn_federated_learning_tpu.obs.spans import Tracer  # noqa: F401
