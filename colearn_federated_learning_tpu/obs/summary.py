"""Run-summary aggregation: metrics JSONL → per-phase timing table.

``colearn summarize <run>`` makes a finished (or in-flight) run
inspectable without TensorBoard or a trace viewer: it folds every
``spans`` record into one per-phase table (count / total / mean / max /
share of the round loop), totals the communication counters, and
surfaces health/retry/profile events. Pure stdlib — importable (and
fast) without touching a jax backend, so the CLI wires it up before
device initialization.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List


def resolve_metrics_path(run: str, out_dir: str = "runs") -> str:
    """Resolve a ``summarize`` argument to a metrics JSONL path: a file
    path as-is, a directory's newest ``*.metrics.jsonl``, else
    ``<out_dir>/<run>.metrics.jsonl`` (the logger's layout)."""
    if os.path.isfile(run):
        return run
    if os.path.isdir(run):
        hits = sorted(
            glob.glob(os.path.join(run, "*.metrics.jsonl")),
            key=os.path.getmtime,
        )
        if not hits:
            raise FileNotFoundError(f"no *.metrics.jsonl under {run!r}")
        return hits[-1]
    cand = os.path.join(out_dir, f"{run}.metrics.jsonl")
    if os.path.isfile(cand):
        return cand
    raise FileNotFoundError(
        f"cannot resolve run {run!r}: not a file, not a directory, and "
        f"{cand!r} does not exist"
    )


def load_records(path: str) -> List[Dict[str, Any]]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # a torn tail line from a crashed run is expected
    return records


_COUNTER_KEYS = (
    "upload_bytes", "upload_bytes_raw", "download_bytes",
    "download_bytes_raw",
)


def summarize_records(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a run's records into one summary dict (see format_summary).

    Totals come from the end-of-fit ``run_summary`` record when one
    exists (the driver logs it on every exit path — aborts included —
    exactly so consumers don't have to re-aggregate the whole JSONL);
    per-round counter summation runs only as the fallback for logs
    that predate it. ``summary["source"]`` records which path was
    taken, and the rendered table prints it."""
    # the authoritative totals record lives at the tail of the log —
    # scan from the end so the fast path stays fast on long logs
    run_sum = next(
        (r for r in reversed(records) if r.get("event") == "run_summary"),
        None,
    )
    phases: Dict[str, Dict[str, float]] = {}
    counters: Dict[str, int] = {}
    health: Dict[str, int] = {}
    events: Dict[str, int] = {}
    rounds = 0
    rps: List[float] = []
    last_eval: Dict[str, float] = {}
    precision: Dict[str, Any] = {}
    executables: Dict[str, Dict[str, Any]] = {}
    retraces: List[Dict[str, Any]] = []
    hbm_peak_bytes = 0
    hbm_peak_program = None
    dropped = stragglers = byzantine = 0
    for rec in records:
        ev = rec.get("event")
        if ev:
            events[ev] = events.get(ev, 0) + 1
        if ev == "executable_compiled":
            # the registry's per-program compile ledger (PR 20); a
            # preflight rehearsal's compiles are not this run's
            if rec.get("preflight"):
                continue
            cur = executables.setdefault(str(rec.get("name", "?")), {
                "compiles": 0, "compile_ms": 0.0, "flops": None,
                "peak_bytes": None,
            })
            cur["compiles"] += 1
            cur["compile_ms"] += float(rec.get("compile_ms") or 0.0)
            if rec.get("flops") is not None:
                cur["flops"] = float(rec["flops"])
            if rec.get("peak_bytes") is not None:
                cur["peak_bytes"] = int(rec["peak_bytes"])
            continue
        if ev == "retrace":
            retraces.append({
                "round": rec.get("round"),
                "name": rec.get("name"),
                "changed": rec.get("changed") or [],
            })
            continue
        if ev == "hbm_watermark":
            wb = int(rec.get("watermark_bytes") or 0)
            if wb > hbm_peak_bytes:
                hbm_peak_bytes = wb
                hbm_peak_program = rec.get("program")
            continue
        if ev == "precision":
            # dtype/fusion provenance logged at fit start — surfaced so
            # a throughput read-off carries its compute_dtype column
            precision = {
                k: rec.get(k) for k in (
                    "param_dtype", "compute_dtype", "local_param_dtype",
                    "fused_apply", "double_buffer",
                ) if k in rec
            }
            continue
        if ev == "spans":
            for name, agg in (rec.get("phases") or {}).items():
                cur = phases.setdefault(
                    name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
                )
                cur["count"] += int(agg.get("count", 0))
                cur["total_ms"] += float(agg.get("total_ms", 0.0))
                cur["max_ms"] = max(cur["max_ms"], float(agg.get("max_ms", 0.0)))
            continue
        if ev == "health":
            kind = rec.get("kind", "?")
            health[kind] = health.get(kind, 0) + 1
            continue
        if ev is None and "round" in rec:
            rounds = max(rounds, int(rec["round"]))
            if "rounds_per_sec" in rec:
                rps.append(float(rec["rounds_per_sec"]))
            if run_sum is None:
                # fallback only: pre-run_summary logs re-aggregate the
                # per-round counters; newer logs take the totals from
                # the authoritative record below
                for k in _COUNTER_KEYS:
                    if k in rec:
                        counters[k] = counters.get(k, 0) + int(rec[k])
            dropped += int(rec.get("dropped_clients", 0))
            stragglers += int(rec.get("straggler_clients", 0))
            byzantine += int(rec.get("byzantine_count", 0))
            for k in ("eval_loss", "eval_acc"):
                if k in rec:
                    last_eval[k] = float(rec[k])
    out: Dict[str, Any] = {
        "rounds": rounds,
        "phases": phases,
        "events": events,
        "source": "run_summary" if run_sum is not None else "reaggregated",
    }
    if rps:
        out["rounds_per_sec_mean"] = sum(rps) / len(rps)
    if executables:
        out["executables"] = executables
    if retraces:
        out["retraces"] = retraces
    if run_sum is not None:
        out["rounds"] = max(rounds, int(run_sum.get("rounds", 0)))
        if "wall_time_sec" in run_sum:
            out["wall_time_sec"] = float(run_sum["wall_time_sec"])
        if "compiles" in run_sum:
            out["compiles"] = int(run_sum["compiles"])
        # the run_summary HBM peak (driver-tracked across the whole
        # run) is authoritative over the per-flush watermarks
        if run_sum.get("hbm_peak_bytes") is not None:
            hbm_peak_bytes = int(run_sum["hbm_peak_bytes"])
            hbm_peak_program = run_sum.get("hbm_peak_program")
        counters = {
            k: int(run_sum[k]) for k in _COUNTER_KEYS if k in run_sum
        }
        # paged-ledger accounting (PR 9 recorded these; now rendered):
        # evictions are cold spills, page_syncs the blocking hot-set
        # fetches they forced
        paging = {
            k: int(run_sum[k])
            for k in ("ledger_evictions", "ledger_page_syncs")
            if k in run_sum
        }
        if paging:
            out["ledger_paging"] = paging
        # population totals (run.obs.population): lifetime coverage /
        # participation, overall pager hit rate, store gather bytes
        population = {
            k: run_sum[k]
            for k in ("population_unique_clients",
                      "population_coverage_pct",
                      "population_participations", "pager_hit_rate",
                      "store_gather_bytes")
            if k in run_sum
        }
        if population:
            out["population"] = population
        # async-plane totals (fedbuff runs): absorbed-update throughput
        # and the REALIZED staleness quantiles next to the configured
        # bound — the numbers a staleness-bound claim is checked against
        async_stats = {
            k: run_sum[k]
            for k in ("async_updates_absorbed", "async_updates_per_sec",
                      "async_staleness_bound", "async_staleness_p50",
                      "async_staleness_p90", "async_staleness_max")
            if k in run_sum
        }
        if async_stats:
            out["async"] = async_stats
        # multi-version absorption split (server.async_versions > 1):
        # which model line each absorbed update landed on
        if isinstance(run_sum.get("async_per_version"), dict):
            out["async_per_version"] = {
                str(k): int(v)
                for k, v in run_sum["async_per_version"].items()
            }
        # two-tier wire accounting: core-link upload bytes ride the
        # wire-totals line so hierarchy runs read both tiers at once
        if "hier_core_upload_bytes" in run_sum:
            out["hier_core_upload_bytes"] = int(
                run_sum["hier_core_upload_bytes"]
            )
    if hbm_peak_bytes:
        out["hbm_peak"] = {"bytes": hbm_peak_bytes,
                           "program": hbm_peak_program}
    if counters:
        out["comm"] = counters
    if dropped or stragglers or byzantine:
        out["failures"] = {
            "dropped_clients": dropped,
            "straggler_clients": stragglers,
            "byzantine_sampled": byzantine,
        }
    if health:
        out["health"] = health
    if last_eval:
        out["final_eval"] = last_eval
    if precision:
        out["precision"] = precision
    return out


def _desc_short(d) -> str:
    """Compact render of a registry leaf descriptor (("a", shape,
    dtype, weak, sharding) after a JSON round-trip) for the retrace
    table; anything unrecognized prints truncated, never raises."""
    if isinstance(d, (list, tuple)) and len(d) >= 3 and d[0] == "a":
        try:
            return f"{tuple(d[1])}:{d[2]}"
        except TypeError:
            pass
    return "absent" if d is None else str(d)[:48]


def _fmt_bytes(n: int) -> str:
    v = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if v < 1024.0 or unit == "TiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{int(v)} B"
        v /= 1024.0


def format_summary(summary: Dict[str, Any], path: str = "") -> str:
    """Render the summary as an aligned text table."""
    lines = []
    head = f"run: {path}" if path else "run summary"
    head += f"  rounds: {summary['rounds']}"
    if "rounds_per_sec_mean" in summary:
        head += f"  rounds/sec (window mean): {summary['rounds_per_sec_mean']:.3f}"
    lines.append(head)
    # which totals path produced the numbers below — the run_summary
    # record when the log carries one, per-round re-aggregation only
    # for logs that predate it
    src = summary.get("source", "reaggregated")
    src_line = (
        "totals: run_summary record" if src == "run_summary"
        else "totals: re-aggregated per-round (log predates run_summary)"
    )
    if "wall_time_sec" in summary:
        src_line += f"  wall: {summary['wall_time_sec']:.1f}s"
    lines.append(src_line)
    prec = summary.get("precision")
    if prec:
        bits = [
            f"compute={prec.get('compute_dtype', '?')}",
            f"params={prec.get('param_dtype', '?')}",
            f"local={prec.get('local_param_dtype', '?')}",
        ]
        if prec.get("fused_apply"):
            bits.append("fused_apply")
        if prec.get("double_buffer"):
            bits.append("double_buffer")
        lines.append("precision: " + "  ".join(bits))
    phases = summary.get("phases") or {}
    if phases:
        # share is relative to the "round" parent span when present,
        # else to the largest phase — nested children overlap, so the
        # column reads "fraction of the round loop", not "sums to 100%"
        base = phases.get("round", {}).get("total_ms") or max(
            (p["total_ms"] for p in phases.values()), default=0.0
        )
        lines.append("")
        lines.append(
            f"{'phase':<24}{'count':>8}{'total s':>11}{'mean ms':>10}"
            f"{'max ms':>10}{'share':>8}"
        )
        for name in sorted(phases, key=lambda n: -phases[n]["total_ms"]):
            p = phases[name]
            mean = p["total_ms"] / p["count"] if p["count"] else 0.0
            share = p["total_ms"] / base if base else 0.0
            lines.append(
                f"{name:<24}{p['count']:>8}{p['total_ms'] / 1000.0:>11.3f}"
                f"{mean:>10.2f}{p['max_ms']:>10.2f}{share:>7.0%} "
            )
    else:
        lines.append("no span records (run.obs.spans was off, or pre-obs run)")
    execs = summary.get("executables")
    if execs:
        # the per-executable compile ledger (registry records): what
        # compiled, how often, how long, and the HLO-derived flops —
        # this table supersedes the bare compile-count line below
        lines.append("")
        lines.append(
            f"{'executable':<24}{'compiles':>9}{'wall ms':>10}"
            f"{'flops':>16}{'peak MiB':>10}"
        )
        for name in sorted(execs, key=lambda n: -execs[n]["compile_ms"]):
            e = execs[name]
            flops = ("n/a" if e["flops"] is None
                     else format(int(e["flops"]), ","))
            peak = ("n/a" if e["peak_bytes"] is None
                    else f"{e['peak_bytes'] / 2**20:.1f}")
            lines.append(
                f"{name:<24}{e['compiles']:>9}{e['compile_ms']:>10.1f}"
                f"{flops:>16}{peak:>10}"
            )
        hbm = summary.get("hbm_peak")
        if hbm:
            lines.append(
                f"hbm peak: {hbm['bytes'] / 2**20:.1f} MiB "
                f"({hbm.get('program') or 'n/a'})"
            )
    elif "compiles" in summary:
        # pre-PR-20 log: the run_summary compile count is all there is
        lines.append(
            f"compiles: {summary['compiles']} (per-executable table "
            "n/a — log predates the executable registry)"
        )
    rets = summary.get("retraces")
    if rets:
        lines.append("")
        lines.append("retraces (recompiles of a seen program — each "
                     "names the argument that changed):")
        lines.append(f"{'round':>6}  {'executable':<22}changed")
        for r in rets[:20]:
            changed = "; ".join(
                f"{c.get('arg', '?')}: {_desc_short(c.get('before'))}"
                f" -> {_desc_short(c.get('after'))}"
                for c in (r.get("changed") or [])
            ) or "n/a"
            lines.append(
                f"{r.get('round') or 0:>6}  {str(r.get('name', '?')):<22}"
                f"{changed}"
            )
        if len(rets) > 20:
            lines.append(f"  ... {len(rets) - 20} more retraces")
    comm = summary.get("comm")
    if comm:
        lines.append("")
        comm_line = (
            "comm: upload "
            f"{_fmt_bytes(comm.get('upload_bytes', 0))} wire / "
            f"{_fmt_bytes(comm.get('upload_bytes_raw', 0))} raw, download "
            f"{_fmt_bytes(comm.get('download_bytes', 0))} wire / "
            f"{_fmt_bytes(comm.get('download_bytes_raw', 0))} raw"
        )
        if "hier_core_upload_bytes" in summary:
            comm_line += (
                ", hier core upload "
                f"{_fmt_bytes(summary['hier_core_upload_bytes'])}"
            )
        lines.append(comm_line)
    paging = summary.get("ledger_paging")
    if paging:
        lines.append(
            f"ledger paging: {paging.get('ledger_evictions', 0)} "
            f"evictions, {paging.get('ledger_page_syncs', 0)} page syncs"
        )
    pop = summary.get("population")
    if pop:
        bits = []
        if "population_unique_clients" in pop:
            bits.append(
                f"{pop['population_unique_clients']} unique clients"
                + (f" ({pop['population_coverage_pct']:.1f}% coverage)"
                   if "population_coverage_pct" in pop else "")
            )
        if "population_participations" in pop:
            bits.append(
                f"{pop['population_participations']} participations"
            )
        if "pager_hit_rate" in pop:
            bits.append(
                f"pager hit rate {100.0 * pop['pager_hit_rate']:.1f}%"
            )
        if "store_gather_bytes" in pop:
            bits.append(
                f"store gathered {_fmt_bytes(pop['store_gather_bytes'])}"
            )
        lines.append("population: " + "  ".join(bits))
    a = summary.get("async")
    if a:
        bits = []
        if "async_updates_absorbed" in a:
            bits.append(f"{a['async_updates_absorbed']} updates absorbed")
        if "async_updates_per_sec" in a:
            bits.append(f"{a['async_updates_per_sec']:.1f}/s")
        if "async_staleness_p50" in a:
            bits.append(
                "staleness p50/p90/max "
                f"{a.get('async_staleness_p50')}/"
                f"{a.get('async_staleness_p90')}/"
                f"{a.get('async_staleness_max')}"
                + (f" (bound {a['async_staleness_bound']})"
                   if "async_staleness_bound" in a else "")
            )
        lines.append("async: " + "  ".join(bits))
    apv = summary.get("async_per_version")
    if apv:
        split = "  ".join(
            f"v{k}: {v}" for k, v in sorted(apv.items(), key=lambda i: i[0])
        )
        lines.append(f"async per-version absorption: {split}")
    fails = summary.get("failures")
    if fails:
        lines.append(
            f"failures: {fails['dropped_clients']} dropped, "
            f"{fails['straggler_clients']} stragglers, "
            f"{fails['byzantine_sampled']} byzantine-sampled"
        )
    health = summary.get("health")
    if health:
        kinds = ", ".join(f"{k}×{v}" for k, v in sorted(health.items()))
        lines.append(f"health events: {kinds}")
    ev = summary.get("final_eval")
    if ev:
        parts = ", ".join(f"{k}={v:.4f}" for k, v in sorted(ev.items()))
        lines.append(f"final eval: {parts}")
    return "\n".join(lines)


def summarize_path(path: str) -> str:
    return format_summary(summarize_records(load_records(path)), path)
