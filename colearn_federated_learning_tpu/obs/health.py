"""Run-health monitoring: NaN/Inf and divergence detection.

The monitor is a pure observer — it inspects the per-round loss (which
the driver fetches anyway at flush boundaries, so the checks are free)
and the optional params finiteness probe, and returns structured
``health`` event records. POLICY stays in the driver: it logs the
events and, per ``run.obs.on_unhealthy``, continues (``warn``), raises
:class:`HealthAbortError` (``abort``), or saves a checkpoint first
(``checkpoint_abort`` — the post-mortem artifact: the last healthy
params plus the poisoned trajectory's provenance in the JSONL).

:class:`HealthAbortError` is deliberately NOT retried by the driver's
``run.max_retries`` failure recovery: a diverged/NaN run restored from
its own checkpoint re-diverges — retrying would burn the retry budget
hiding the signal the monitor exists to surface.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional


class HealthAbortError(RuntimeError):
    """The health monitor's configured action was to abort the run."""


class HealthMonitor:
    """Tracks the loss trajectory and flags unhealthy rounds.

    - ``non_finite_loss``: the round's training loss is NaN/Inf.
    - ``divergence``: ``divergence_factor > 0`` and the loss exceeds
      ``factor × best-so-far`` (best is the running minimum, so a noisy
      warmup cannot permanently raise the bar).
    - ``non_finite_params``: reported by the driver's params probe
      (``run.obs.params_check`` / ``run.sanitize``).
    """

    def __init__(self, divergence_factor: float = 0.0):
        self.divergence_factor = float(divergence_factor)
        self._best: Optional[float] = None

    def observe_loss(self, round_idx: int, loss: float) -> Optional[Dict[str, Any]]:
        """Feed one round's training loss; returns a ``health`` event
        record when the round is unhealthy, else None."""
        if not math.isfinite(loss):
            return {
                "event": "health",
                "kind": "non_finite_loss",
                "round": int(round_idx),
                "loss": repr(loss),
            }
        if self.divergence_factor > 0.0 and self._best is not None:
            bound = self.divergence_factor * self._best
            if loss > bound:
                return {
                    "event": "health",
                    "kind": "divergence",
                    "round": int(round_idx),
                    "loss": loss,
                    "best_loss": self._best,
                    "factor": self.divergence_factor,
                }
        if self._best is None or loss < self._best:
            self._best = loss
        return None

    def observe_params_finite(self, round_idx: int,
                              finite: bool) -> Optional[Dict[str, Any]]:
        if finite:
            return None
        return {
            "event": "health",
            "kind": "non_finite_params",
            "round": int(round_idx),
        }
