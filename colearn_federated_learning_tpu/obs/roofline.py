"""Per-phase FLOP/HBM-byte cost model, MFU waterfall, and the bench
regression observatory.

The headline bench emits ONE number (``mfu_pct``) and ROADMAP item 2
asks where the other ~59% goes. This module turns that scalar into an
attributable breakdown using the same discipline as the wire-byte
counters (obs/counters.py): an **analytic cost model** — a pure
function of the config and the round's realized shapes, never a
measurement — joined with measured span timings. Purity is what makes
the sharded and sequential engines agree bit-for-bit on every
``phase_cost`` record (pinned by ``tests/test_roofline.py``), and what
lets ``colearn mfu`` decompose a finished run from its JSONL alone.

Three layers, all pure stdlib (the CLI imports this before any jax
backend initialization):

1. **Cost model** — :func:`round_phase_costs`: analytic FLOPs and
   HBM bytes moved per round-program stage (local train fwd/bwd,
   attack transform, aggregation, server apply incl. the Pallas fused
   path, ledger stats). The local-train FLOP count reuses the bench's
   ``model_tflops_per_round`` machinery: either XLA's cost analysis of
   one scan-free train step (``run.obs.phase_cost_flops="xla"``) or
   the dense 6·P·B approximation (default — no extra compile).
2. **Waterfall** — :func:`waterfall`: headline MFU decomposed into
   effective compute, padding loss (``padded_step_fraction`` dead
   steps), non-matmul compute (the cost model's non-train phases at
   roofline speed), host-exposed time (spans not hidden under
   ``round.dispatch``), and residual kernel inefficiency. The
   components sum to 100% of wall time within
   :data:`WATERFALL_TOL_PCT` — the waterfall identity — and
   ``effective + padding == headline`` by the same tolerance.
3. **Observatory** — :func:`load_bench_history` /
   :func:`bench_report`: the ``BENCH_r*.json`` trajectory with
   per-phase deltas vs best-so-far and budget gates from a checked-in
   baseline file (``BENCH_BUDGETS.json``), generalizing bench.py's
   scalar device-ms ``_gate`` to per-phase budgets so the next plateau
   is localized to a phase the moment it appears. Historical entries
   that predate a field render ``n/a`` — never a KeyError.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
from typing import Any, Dict, List, Optional, Sequence

# ---------------------------------------------------------------------------
# peaks (single source of truth — bench.py imports these)
# ---------------------------------------------------------------------------

# Dense bf16 peak of one TPU v5e (v5 lite) chip; MFU = achieved / peak.
PEAK_BF16_FLOPS = 197e12
# The MXU retires f32 products at no better than half the bf16 rate, so
# bf16/2 is the conventional (and still optimistic) stand-in for the
# unpublished v5e f32 peak. `mfu_basis` records which denominator
# produced every number — a bf16 measurement silently compared against
# an f32 peak is the exact hygiene failure the basis exists to stop.
PEAK_F32_FLOPS = PEAK_BF16_FLOPS / 2
# HBM bandwidth of one v5e chip — the roof the memory-bound phases hit.
PEAK_HBM_BYTES_PER_SEC = 819e9

# Waterfall identity tolerance, in MFU percentage points: the
# components are computed from three independent record streams
# (analytic phase costs, measured spans, measured rounds/sec), so the
# identity holds only up to their rounding (record fields are rounded
# to 3-4 decimals at log time).
WATERFALL_TOL_PCT = 0.5

# The cost-model phase taxonomy, in round-program order. Matches the
# engines' jax.named_scope annotations (round_local_train,
# round_attack_transform, round_aggregate, round_server_apply /
# round_fused_reduce_apply, round_client_ledger) so device profiles
# join with the analytic model by name.
PHASES = (
    "local_train",
    "attack_transform",
    "aggregation",
    "server_apply",
    "ledger_stats",
)

# Span phases that do NOT count as host-exposed time: `round` is the
# parent bracket, `round.dispatch` is where async device execution is
# buried, and `compile` fires INSIDE the dispatch call that triggered
# it (counting it again would double-book that wall). The executable
# registry's own spans (`obs.executables` AOT lower+compile and
# `obs.preflight`) bracket compile work the `compile` listener already
# books — counting them would charge each compilation twice. Every
# other span (host_inputs, placement, fetch, eval, checkpoint,
# stream_slab, ...) is host time the device sits idle through.
_NON_HOST_EXPOSED_SPANS = ("round", "round.dispatch", "compile",
                           "obs.executables", "obs.preflight")

# Attribution sub-spans nested INSIDE an already-counted host span: the
# parent's bracket (`round.host_inputs`) contains their wall time, so
# summing both would double-book the host wall. They exist so `colearn
# mfu` can split the host-exposed line into named control-plane
# sub-lines (sampler / churn / slot-assign / slab-build), not to add
# to the total.
_SUBSPAN_PREFIXES = ("round.host_inputs.",)


def _is_host_exposed(name: str) -> bool:
    return (name not in _NON_HOST_EXPOSED_SPANS
            and not name.startswith(_SUBSPAN_PREFIXES))


def host_exposed_pct(phase_ms: Dict[str, float], wall_s: float) -> Optional[float]:
    """Fraction of a timed region's wall clock the device sat idle
    behind host work, as a percentage: the sum of every span that is
    NOT dispatch/compile (same `_NON_HOST_EXPOSED_SPANS` rule the
    waterfall uses) over the wall. bench.py stamps this into every
    result's extras and `bench_report` gates it against
    ``host_exposed_pct_max`` — the budget that keeps host-side
    accounting (ledger stats, population windows, digest fetches) from
    quietly eating the round loop. ``None`` when the wall is
    unmeasured, so historical entries render n/a, never divide by 0."""
    if not wall_s or wall_s <= 0:
        return None
    host_ms = sum(
        ms for name, ms in (phase_ms or {}).items()
        if _is_host_exposed(name)
    )
    return 100.0 * (host_ms / 1000.0) / float(wall_s)

# Byte-model pass counts (documented constants, not magic numbers):
# local train touches the params 4× per step (fwd read, bwd read, grad
# write, local-SGD update write) — activation traffic is workload-
# dependent and excluded, so local-train bytes are a floor (harmless:
# the phase is compute-bound by orders of magnitude anyway).
LOCAL_TRAIN_PARAM_PASSES = 4
# Unfused server apply is a chain of separate XLA ops (trust/weight
# scale → reduction output materialized → delta apply → optimizer),
# each re-reading its operands from HBM: read delta, read params, read
# momentum, write momentum, write params, plus the materialized
# intermediate — 6 params-sized passes.
SERVER_APPLY_PASSES_UNFUSED = 6
# The Pallas fused path (ops/pallas_apply.py) runs the same chain as
# ONE VMEM-resident pass: read params + momentum, write params +
# momentum — 4 passes, and the mean-delta intermediate (1 write + 1
# re-read in `aggregation`) never touches HBM at all.
SERVER_APPLY_PASSES_FUSED = 4


def mfu_basis(compute_dtype: str, local_param_dtype: Optional[str],
              param_dtype: str) -> tuple:
    """(basis name, peak FLOP/s) from the effective compute precision:
    the matmuls run bf16 when either the model compute dtype or the
    effective local-param dtype is bfloat16. Pure so bench.py and the
    driver's ``phase_cost_model`` record derive the identical basis."""
    eff_local = local_param_dtype or param_dtype
    if "bfloat16" in (compute_dtype, eff_local):
        return "bf16_peak", PEAK_BF16_FLOPS
    return "f32_peak", PEAK_F32_FLOPS


def peak_for_basis(basis: str) -> float:
    return PEAK_BF16_FLOPS if basis == "bf16_peak" else PEAK_F32_FLOPS


def analytic_step_flops(n_coords: int, batch_units: int) -> int:
    """Dense fwd+bwd FLOPs of one train step: 2·P per unit forward,
    2× that backward — the standard 6·P·B approximation. ``batch_units``
    is examples × tokens-per-example for sequence models. Under-counts
    convolutional re-use (a conv layer applies its kernel per spatial
    position); the XLA-counted alternative (``phase_cost_flops="xla"``)
    is exact but costs one extra compile per run."""
    return 6 * int(n_coords) * int(batch_units)


def analytic_lora_step_flops(full_coords: int, adapter_coords: int,
                             batch_units: int) -> int:
    """Adapter-step FLOPs under a frozen LoRA base (``model.lora``):
    the forward and the backward's activation-gradient chain still
    traverse the FULL merged model (2·P_full·B each — gradients must
    propagate through frozen layers to reach earlier adapters), but
    weight-gradient contractions exist only for the trainable factors
    (2·P_adapter·B). Total ``4·P_full·B + 2·P_adapter·B`` — vs full
    training's ``6·P_full·B`` and vs the naive adapter-only count
    ``6·P_adapter·B``, which understates a LoRA step by ~P_full/P_adapter.
    Modeling either endpoint would mis-attribute the MFU waterfall for
    every adapter config; this is the honest middle the frozen-base
    structure actually executes."""
    return (4 * int(full_coords) + 2 * int(adapter_coords)) * int(batch_units)


# ---------------------------------------------------------------------------
# cohort-layout GEMM geometry (run.cohort_layout)
# ---------------------------------------------------------------------------

# The MXU retires 128×128 tiles; a GEMM whose row count (the activation/
# batch dim, M) is not a tile multiple pads the last tile with dead rows.
MXU_TILE_ROWS = 128

COHORT_LAYOUTS = ("spatial", "megabatch")


def layout_gemm_rows(cohort_layout: str, clients_per_lane: int,
                     batch: int, lora_all_steps: bool = False) -> int:
    """The M rows a shared-weight train-step GEMM feeds the MXU under a
    cohort layout. ``spatial`` trains clients as separate (or batched)
    per-client GEMMs — batched dot dimensions do NOT merge into M, so
    every GEMM's rows are ONE client's batch regardless of
    ``client_vmap_width``; that cap is exactly why the layout, not the
    width, is the structural lever. ``megabatch`` flattens the lane's
    whole client chunk into the row axis: M = K_local·batch.

    ``lora_all_steps``: megabatch × frozen-base LoRA via the decomposed
    apply (models/lora.py ``apply_decomposed``). The row count is the
    same M = K_local·batch, but its COVERAGE changes: without the flag
    the un-batched-weight GEMMs exist only in the shared-weight step-0
    phase (params diverge from step 1 and every base GEMM re-batches);
    with it the frozen base contracts the flattened megabatch in EVERY
    local step — only the rank-r adapter factors batch. Spatial has no
    decomposed path, so the pairing is rejected rather than silently
    annotated."""
    if cohort_layout not in COHORT_LAYOUTS:
        raise ValueError(
            f"unknown cohort_layout {cohort_layout!r}; "
            f"allowed: {', '.join(COHORT_LAYOUTS)}"
        )
    if lora_all_steps and cohort_layout != "megabatch":
        raise ValueError(
            "lora_all_steps GEMM geometry exists only under "
            "cohort_layout='megabatch' (the decomposed LoRA apply is a "
            "megabatch-layout optimization)"
        )
    if cohort_layout == "megabatch":
        return int(clients_per_lane) * int(batch)
    return int(batch)


def mxu_tile_pad_fraction(gemm_rows: int, tile: int = MXU_TILE_ROWS) -> float:
    """Fraction of the MXU's row-tile slots wasted on padding when a
    GEMM with ``gemm_rows`` rows is tiled: ``1 − rows/(⌈rows/tile⌉·tile)``.
    Batch 32 under the spatial layout wastes 0.75 of every row tile;
    a 16-client megabatch at the same batch (512 rows) wastes 0.0 —
    the tile-level attribution of the layout's MFU win (`colearn mfu`
    prints it next to the waterfall)."""
    rows = int(gemm_rows)
    if rows <= 0:
        raise ValueError(f"gemm_rows must be > 0, got {gemm_rows}")
    tiles = -(-rows // int(tile))
    return 1.0 - rows / float(tiles * int(tile))


# ---------------------------------------------------------------------------
# the analytic per-phase cost model
# ---------------------------------------------------------------------------


def round_phase_costs(*, k: int, steps: int, batch: int, n_coords: int,
                      compute_bytes: int, step_flops: int,
                      aggregator: str = "weighted_mean",
                      attack: bool = False, ledger: bool = False,
                      reputation: bool = False,
                      fused_apply: bool = False,
                      host_input_bytes: int = 0) -> Dict[str, Dict[str, int]]:
    """Analytic FLOPs + HBM bytes per round-program stage for one
    centralized round on the **padded** ``steps × batch`` grid (the
    same grid headline MFU counts — padding waste is attributed by the
    waterfall, not hidden here).

    Same honesty contract as :func:`~colearn_federated_learning_tpu.
    obs.counters.round_comm_bytes`: these are the FLOPs/bytes the
    configured round program WOULD execute/move — a pure function of
    the config and the realized grid, identical across the sharded,
    sequential, and fused engines by construction.

    Only phases the config actually runs appear in the result. Wire
    stacks and aggregation intermediates are f32 (4 B); server params/
    momentum are f32 master; local-train compute traffic moves at
    ``compute_bytes`` (2 under bf16 compute).
    """
    k, steps, batch = int(k), int(steps), int(batch)
    n, cb = int(n_coords), int(compute_bytes)
    out: Dict[str, Dict[str, int]] = {}

    # local train: the matmul phase. step_flops is fwd+bwd of ONE batch.
    out["local_train"] = {
        "flops": int(step_flops) * steps * k,
        "bytes": (steps * k * LOCAL_TRAIN_PARAM_PASSES * n * cb
                  + int(host_input_bytes)),
    }

    if attack:
        # elementwise transform over the [K, n] wire stack (sign flip /
        # scale / noise add): 2 flops/coord, read + write at f32
        out["attack_transform"] = {
            "flops": 2 * k * n,
            "bytes": 2 * k * n * 4,
        }

    if aggregator == "krum":
        # pairwise squared distances over the stack: K(K-1)/2 ordered
        # pairs × (sub, mul, add)/coord; each pair reads two vectors
        pairs = k * (k - 1) // 2
        agg_flops = 3 * pairs * n
        agg_bytes = 2 * pairs * n * 4
        # + the winner's delta materialized (one-hot weighted reduce)
        agg_flops += 2 * k * n
        agg_bytes += k * n * 4
    elif aggregator in ("median", "trimmed_mean"):
        # coordinate-wise sort network over K values: ~K·ceil(log2 K)
        # compare-exchanges per coordinate, stack read + sorted write
        agg_flops = k * max(1, math.ceil(math.log2(max(k, 2)))) * n
        agg_bytes = 2 * k * n * 4
    else:  # weighted_mean
        # multiply-accumulate over the stack (or the psum-equivalent)
        agg_flops = 2 * k * n
        agg_bytes = k * n * 4
    if reputation:
        # trust enters as one extra multiply per stack coordinate
        agg_flops += k * n
    if not (fused_apply and aggregator in ("weighted_mean", "krum")):
        # the mean delta materializes to HBM and server_apply re-reads
        # it; under the fused Pallas path the reduction output stays in
        # VMEM, so these two passes are exactly the fused saving
        agg_bytes += 2 * n * 4
    out["aggregation"] = {"flops": agg_flops, "bytes": agg_bytes}

    # server apply: delta scale + momentum update + param apply —
    # elementwise over the f32 master params
    fused = fused_apply and aggregator in ("weighted_mean", "krum")
    passes = (SERVER_APPLY_PASSES_FUSED if fused
              else SERVER_APPLY_PASSES_UNFUSED)
    out["server_apply"] = {
        "flops": 4 * n,
        "bytes": passes * n * 4,
    }

    if ledger:
        # per-client stats over the wire stack (obs/ledger.py): L2 norm
        # (2·n), dot with the mean delta (2·n), residual norm (2·n) per
        # client; the stack is re-read once and the mean delta K times
        # in principle but streams — counted once per client
        out["ledger_stats"] = {
            "flops": 6 * k * n,
            "bytes": 2 * k * n * 4,
        }
    return out


def phase_time_s(cost: Dict[str, int], peak_flops: float,
                 peak_bw: float = PEAK_HBM_BYTES_PER_SEC) -> float:
    """Roofline execution-time floor of one phase: whichever roof —
    compute or memory — binds."""
    return max(cost["flops"] / peak_flops, cost["bytes"] / peak_bw)


def classify_phase(cost: Dict[str, int], peak_flops: float,
                   peak_bw: float = PEAK_HBM_BYTES_PER_SEC) -> str:
    """``compute`` vs ``memory`` bound: arithmetic intensity
    (flops/byte) against the ridge point of the configured roofline."""
    if cost["bytes"] <= 0:
        return "compute"
    ridge = peak_flops / peak_bw
    return "compute" if cost["flops"] / cost["bytes"] >= ridge else "memory"


# ---------------------------------------------------------------------------
# the MFU waterfall
# ---------------------------------------------------------------------------

WATERFALL_COMPONENTS = (
    "effective_compute",
    "padding",
    "non_matmul",
    "host_exposed",
    "residual",
)


def waterfall(phase_costs: Dict[str, Dict[str, int]],
              rounds_per_sec: float, peak_flops: float, n_chips: int = 1,
              padded_step_fraction: float = 0.0,
              host_exposed_ms_per_round: float = 0.0,
              peak_bw: float = PEAK_HBM_BYTES_PER_SEC) -> Dict[str, Any]:
    """Decompose headline MFU into the waterfall components, each in
    percent of wall time (so they sum to 100).

    - ``headline_mfu_pct`` — the bench's number: padded-grid local-
      train FLOPs × rounds/sec ÷ peak.
    - ``effective_compute`` + ``padding`` — the headline split by
      ``padded_step_fraction`` (dead scan steps burn full-step FLOPs).
    - ``non_matmul`` — the cost model's non-train phases at roofline
      speed (each phase's max(compute, memory) floor).
    - ``host_exposed`` — measured span time NOT hidden under
      ``round.dispatch`` (host inputs, placement, fetch, eval,
      checkpoint, compile), per round.
    - ``residual`` — whatever wall time remains: kernel inefficiency,
      pipeline bubbles, and every un-modeled stall. Negative residual
      beyond :data:`WATERFALL_TOL_PCT` means the model over-accounts
      the measured wall and is surfaced, never clamped away.
    """
    if rounds_per_sec <= 0:
        raise ValueError("rounds_per_sec must be > 0 for a waterfall")
    wall_s = 1.0 / rounds_per_sec
    chips = max(1, int(n_chips))
    train_flops = phase_costs.get("local_train", {}).get("flops", 0)
    headline = 100.0 * train_flops / (wall_s * peak_flops * chips)
    padding = headline * float(padded_step_fraction)
    effective = headline - padding
    non_matmul_s = sum(
        phase_time_s(c, peak_flops, peak_bw) / chips
        for name, c in phase_costs.items() if name != "local_train"
    )
    non_matmul = 100.0 * non_matmul_s / wall_s
    host = 100.0 * (host_exposed_ms_per_round / 1000.0) / wall_s
    residual = 100.0 - headline - non_matmul - host
    return {
        "headline_mfu_pct": headline,
        "components": {
            "effective_compute": effective,
            "padding": padding,
            "non_matmul": non_matmul,
            "host_exposed": host,
            "residual": residual,
        },
        "wall_ms_per_round": wall_s * 1000.0,
    }


def check_waterfall_identity(wf: Dict[str, Any],
                             tol: float = WATERFALL_TOL_PCT) -> List[str]:
    """The documented identity, as violations (empty = holds):
    components sum to 100% of wall, effective + padding reconstructs
    the headline, and no component over-accounts (residual may be
    negative only within tolerance)."""
    comp = wf["components"]
    problems = []
    total = sum(comp[k] for k in WATERFALL_COMPONENTS)
    if abs(total - 100.0) > tol:
        problems.append(f"components sum to {total:.3f}%, not 100%")
    if abs(comp["effective_compute"] + comp["padding"]
           - wf["headline_mfu_pct"]) > tol:
        problems.append("effective + padding != headline MFU")
    if comp["residual"] < -tol:
        problems.append(
            f"residual {comp['residual']:.3f}% < 0: the analytic model "
            f"over-accounts the measured wall time"
        )
    return problems


# ---------------------------------------------------------------------------
# `colearn mfu <run>` — the report over a run's JSONL records
# ---------------------------------------------------------------------------


def mfu_report(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Join a run's ``phase_cost_model`` / ``phase_cost`` / ``spans`` /
    round records into the waterfall + roofline report. Raises
    ValueError with an actionable message when the run predates the
    observatory (no phase_cost records)."""
    model = None
    costs_sum: Dict[str, Dict[str, float]] = {}
    costs_n = 0
    span_ms: Dict[str, float] = {}
    rps: List[float] = []
    padded: List[float] = []
    rounds = 0
    exec_recs: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        ev = rec.get("event")
        if ev == "phase_cost_model":
            model = rec
        elif ev == "executable_compiled":
            # the registry's HLO-derived truth (latest compile per
            # program wins — retraces refresh the measured flops);
            # preflight compiles are abstract rehearsals, not the run
            if not rec.get("preflight"):
                exec_recs[str(rec.get("name"))] = rec
        elif ev == "phase_cost":
            costs_n += 1
            for name, c in (rec.get("phases") or {}).items():
                cur = costs_sum.setdefault(name, {"flops": 0.0, "bytes": 0.0})
                cur["flops"] += float(c.get("flops", 0))
                cur["bytes"] += float(c.get("bytes", 0))
        elif ev == "spans":
            for name, agg in (rec.get("phases") or {}).items():
                span_ms[name] = span_ms.get(name, 0.0) + float(
                    agg.get("total_ms", 0.0)
                )
        elif ev is None and "round" in rec:
            rounds = max(rounds, int(rec["round"]))
            if "rounds_per_sec" in rec:
                rps.append(float(rec["rounds_per_sec"]))
            if "padded_step_fraction" in rec:
                padded.append(float(rec["padded_step_fraction"]))
    if model is None or not costs_n:
        raise ValueError(
            "no phase_cost records in this log (run.obs.phase_cost was "
            "off, or the run predates the performance observatory)"
        )
    if not rps:
        raise ValueError(
            "no rounds_per_sec in this log (no completed flush window) "
            "— cannot anchor the waterfall to wall time"
        )
    # mean analytic cost per round (varies only with bucket rungs /
    # realized participation)
    costs = {
        name: {"flops": int(c["flops"] / costs_n),
               "bytes": int(c["bytes"] / costs_n)}
        for name, c in costs_sum.items()
    }
    peak = float(model.get("peak_flops") or
                 peak_for_basis(model.get("mfu_basis", "bf16_peak")))
    peak_bw = float(model.get("peak_hbm_bytes_per_sec")
                    or PEAK_HBM_BYTES_PER_SEC)
    n_chips = int(model.get("n_chips", 1))
    host_ms = sum(
        ms for name, ms in span_ms.items()
        if _is_host_exposed(name)
    ) / max(1, rounds)
    # control-plane attribution: the named children of the host-input
    # span (sampler / churn / slot-assign / slab-build), per round —
    # excluded from the host_exposed SUM above (their parent bracket
    # already holds their wall), surfaced here as waterfall sub-lines
    host_sub_ms = {}
    for name in sorted(span_ms):
        for pref in _SUBSPAN_PREFIXES:
            if name.startswith(pref):
                host_sub_ms[name[len(pref):]] = (
                    span_ms[name] / max(1, rounds)
                )
    # measured-vs-analytic drift: the XLA cost_analysis flops of the
    # dominant round program (per round — fused programs carry
    # rounds_per_call) against the analytic model's per-round total.
    # A pre-PR-20 log has no executable_compiled records: the section
    # is None and every consumer renders n/a, never a KeyError.
    analytic_round = sum(c["flops"] for c in costs.values())
    round_progs: Dict[str, float] = {}
    for name, rec in exec_recs.items():
        fl = rec.get("flops")
        if fl is None or not name.startswith("round."):
            continue
        per_call = max(1, int(rec.get("rounds_per_call") or 1))
        round_progs[name] = float(fl) / per_call
    measured = None
    if round_progs:
        prog = max(round_progs, key=lambda n: round_progs[n])
        m_flops = round_progs[prog]
        measured = {
            "programs": {n: round_progs[n] for n in sorted(round_progs)},
            "round_program": prog,
            "round_flops_measured": m_flops,
            "round_flops_analytic": float(analytic_round),
            "flop_model_drift_pct": (
                100.0 * (m_flops - analytic_round) / analytic_round
                if analytic_round else None
            ),
        }
    rps_mean = sum(rps) / len(rps)
    wf = waterfall(
        costs, rps_mean, peak, n_chips=n_chips,
        padded_step_fraction=(sum(padded) / len(padded)) if padded else 0.0,
        host_exposed_ms_per_round=host_ms, peak_bw=peak_bw,
    )
    roofline = {
        name: {
            **costs[name],
            # None (not inf) when the phase moves no modeled bytes, so
            # the --json output stays strict JSON
            "intensity": (costs[name]["flops"] / costs[name]["bytes"]
                          if costs[name]["bytes"] else None),
            "bound": classify_phase(costs[name], peak, peak_bw),
            "time_us_at_peak": phase_time_s(costs[name], peak, peak_bw)
            / max(1, n_chips) * 1e6,
        }
        for name in PHASES if name in costs
    }
    return {
        "rounds": rounds,
        "rounds_per_sec": rps_mean,
        "mfu_basis": model.get("mfu_basis", "n/a"),
        "flop_source": model.get("flop_source", "n/a"),
        "peak_tflops": peak / 1e12,
        "peak_hbm_gbs": peak_bw / 1e9,
        "n_chips": n_chips,
        "waterfall": wf,
        "identity_violations": check_waterfall_identity(wf),
        "roofline": roofline,
        "host_exposed_ms_per_round": host_ms,
        "host_exposed_sub_ms_per_round": host_sub_ms,
        "measured": measured,
        # cohort-layout attribution (runs predating the layout fields
        # render n/a — never a KeyError)
        "layout": {
            "cohort_layout": model.get("cohort_layout"),
            "clients_per_lane": model.get("clients_per_lane"),
            "gemm_rows": model.get("gemm_rows"),
            "lora_all_steps": model.get("lora_all_steps"),
            "mxu_tile_pad_fraction": model.get("mxu_tile_pad_fraction"),
        },
    }


_WF_LABELS = {
    "effective_compute": "effective compute",
    "padding": "padding (dead steps)",
    "non_matmul": "non-matmul compute",
    "host_exposed": "host-exposed time",
    "residual": "residual kernel inefficiency",
}


def format_mfu_report(report: Dict[str, Any], path: str = "") -> str:
    wf = report["waterfall"]
    lines = []
    head = f"run: {path}" if path else "mfu report"
    lines.append(
        f"{head}  rounds: {report['rounds']}  "
        f"wall/round: {wf['wall_ms_per_round']:.1f} ms  "
        f"basis: {report['mfu_basis']} "
        f"({report['peak_tflops']:.1f} TF/s, "
        f"{report['peak_hbm_gbs']:.0f} GB/s HBM, "
        f"{report['n_chips']} chip(s), {report['flop_source']} flops)"
    )
    lines.append(f"headline MFU: {wf['headline_mfu_pct']:.2f}%")
    lay = report.get("layout") or {}
    if lay.get("cohort_layout"):
        pad = lay.get("mxu_tile_pad_fraction")
        rows_note = (
            " all steps (lora decomposed)" if lay.get("lora_all_steps")
            else ""
        )
        lines.append(
            f"cohort layout: {lay['cohort_layout']}  "
            f"(K_local {_na(lay.get('clients_per_lane'))}, "
            f"gemm rows {_na(lay.get('gemm_rows'))}{rows_note}, "
            f"mxu row-tile padding "
            f"{_na(None if pad is None else 100.0 * pad, '{:.1f}%')})"
        )
    lines.append("")
    lines.append(f"waterfall (% of wall time, sums to 100 "
                 f"± {WATERFALL_TOL_PCT}):")
    subs = report.get("host_exposed_sub_ms_per_round") or {}
    wall_ms = wf["wall_ms_per_round"]
    for name in WATERFALL_COMPONENTS:
        lines.append(
            f"  {_WF_LABELS[name]:<30}{wf['components'][name]:>8.2f}%"
        )
        if name == "host_exposed" and subs:
            # control-plane split of the line above (span children of
            # round.host_inputs — attribution, not additional time)
            for sub in sorted(subs):
                pct = (100.0 * (subs[sub] / wall_ms)) if wall_ms else 0.0
                lines.append(
                    f"    · {sub:<26}{pct:>8.2f}%"
                    f"  ({subs[sub]:.3f} ms/round)"
                )
    for v in report["identity_violations"]:
        lines.append(f"  WARNING: {v}")
    roof = report.get("roofline") or {}
    meas = report.get("measured") or {}
    if roof:
        lines.append("")
        lines.append(
            f"{'phase':<18}{'flops/round':>14}{'bytes/round':>14}"
            f"{'flops/byte':>12}{'bound':>9}{'us@peak':>10}{'measured':>13}"
        )
        for name in PHASES:
            if name not in roof:
                continue
            r = roof[name]
            inten = ("inf" if r["intensity"] is None
                     else f"{r['intensity']:.1f}")
            # measured flops exist at PROGRAM granularity (XLA fuses
            # the whole round into one executable), so phase rows carry
            # the analytic model and the join lands on the total row
            lines.append(
                f"{name:<18}{r['flops']:>14.3g}{r['bytes']:>14.3g}"
                f"{inten:>12}{r['bound']:>9}{r['time_us_at_peak']:>10.1f}"
                f"{'n/a':>13}"
            )
        if meas:
            drift = meas.get("flop_model_drift_pct")
            lines.append(
                f"{'round total':<18}"
                f"{meas['round_flops_analytic']:>14.3g}"
                f"{'':>14}{'':>12}{'':>9}{'':>10}"
                f"{meas['round_flops_measured']:>13.3g}"
            )
            lines.append(
                f"measured vs analytic flops/round "
                f"({meas['round_program']}, XLA cost_analysis): "
                f"drift {_na(drift, '{:+.2f}%')}"
            )
        else:
            lines.append(
                "measured flops: n/a (no executable_compiled records — "
                "run predates the executable registry or "
                "run.obs.executables was off)"
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# bench regression observatory (`colearn bench-report`)
# ---------------------------------------------------------------------------


def _na(v, fmt="{}"):
    return "n/a" if v is None else fmt.format(v)


def load_bench_history(bench_dir: str) -> List[Dict[str, Any]]:
    """Parse the ``BENCH_r*.json`` trajectory in ``bench_dir`` into
    normalized entries, tolerant of every historical shape: entries
    missing ``parsed`` (a failed bench run), and extras that predate
    ``mfu_basis`` / ``compute_dtype`` / ``phase_ms`` / ``timed_rounds``
    get ``None`` fields (rendered ``n/a``), never a KeyError."""
    paths = sorted(
        glob.glob(os.path.join(bench_dir, "BENCH_r*.json")),
        key=lambda p: (
            int(m.group(1)) if (m := re.search(r"_r(\d+)", p)) else 0, p
        ),
    )
    entries = []
    for p in paths:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            entries.append({"file": os.path.basename(p), "n": None,
                            "value": None, "error": "unreadable"})
            continue
        parsed = doc.get("parsed") or {}
        extra = parsed.get("extra") or {}
        timed = extra.get("timed_rounds")
        phase_ms = extra.get("phase_ms")
        phase_ms_per_round = None
        if isinstance(phase_ms, dict) and timed:
            phase_ms_per_round = {
                k: float(v) / float(timed) for k, v in phase_ms.items()
            }
        entries.append({
            "file": os.path.basename(p),
            "n": doc.get("n"),
            "value": parsed.get("value"),
            "vs_baseline": parsed.get("vs_baseline"),
            "mfu_pct": extra.get("mfu_pct"),
            "effective_mfu_pct": extra.get("effective_mfu_pct"),
            "mfu_basis": extra.get("mfu_basis"),
            "compute_dtype": extra.get("compute_dtype"),
            "device_ms_per_round": extra.get("device_ms_per_round"),
            "timed_rounds": timed,
            "phase_ms_per_round": phase_ms_per_round,
            "padded_step_fraction": extra.get("padded_step_fraction"),
            # the n_chips axis (weak-scaling bench): historical entries
            # that predate it render n/a like every other field
            "n_chips": extra.get("n_chips"),
            "updates_per_sec_per_chip": extra.get(
                "client_updates_per_sec_per_chip"
            ),
            "cohort_layout": extra.get("cohort_layout"),
            # control-plane mode (run.control_plane, ISSUE 18): entries
            # predating the knob (r01–r05) render n/a
            "control_plane": extra.get("control_plane"),
            "host_exposed_pct": extra.get("host_exposed_pct"),
            # measured-vs-analytic flop drift (executable registry,
            # ISSUE 20): r01–r19 entries predate the extra → n/a
            "flop_model_drift_pct": extra.get("flop_model_drift_pct"),
            "weak_scale": _tail_weak_scale_records(doc, parsed),
            "async_throughput": _tail_async_records(doc, parsed),
            "store_gather": _tail_store_records(doc, parsed),
        })
    return entries


def _tail_store_records(doc, parsed) -> List[Dict[str, Any]]:
    """Store-backed bench records carrying the ``store_gather_mbps``
    extra in one BENCH_r*.json — the file's own parsed entry or extra
    ``--matrix`` tail lines, like the async/weak-scale scans. These
    feed the ``store_gather_mbps_min`` gate; entries predating the
    data-plane extras (r01–r18) are simply absent, never an error."""
    candidates: List[Dict[str, Any]] = []
    for line in str(doc.get("tail") or "").splitlines():
        line = line.strip()
        if not (line.startswith("{") and "store_gather_mbps" in line):
            continue
        try:
            candidates.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    if (parsed.get("extra") or {}).get("store_gather_mbps") is not None:
        candidates.append(parsed)
    records: List[Dict[str, Any]] = []
    seen = set()
    for rec in candidates:
        extra = rec.get("extra") or {}
        mbps = extra.get("store_gather_mbps")
        if mbps is None:
            continue
        name = str(rec.get("config") or rec.get("metric") or "store")
        if name in seen:
            continue
        seen.add(name)
        records.append({
            "name": name,
            "store_gather_mbps": float(mbps),
            "gather_workers": extra.get("gather_workers"),
        })
    return records


def _tail_async_records(doc, parsed) -> List[Dict[str, Any]]:
    """``async_throughput_*`` bench records carried by one
    BENCH_r*.json — the file's own parsed entry or extra ``--matrix``
    tail lines, exactly like the weak-scale scan. Normalized to the
    fields the async-throughput gate reads; anything unparsable or
    missing them is skipped (the r01+ history predates async entries
    and must keep loading clean)."""
    candidates: List[Dict[str, Any]] = []
    for line in str(doc.get("tail") or "").splitlines():
        line = line.strip()
        if not (line.startswith("{") and (
            "async_throughput" in line or "hier_async" in line
        )):
            continue
        try:
            candidates.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    if (
        str(parsed.get("config") or "").startswith(
            ("async_throughput", "hier_async")
        )
        or (parsed.get("extra") or {}).get("staleness_bound") is not None
    ):
        candidates.append(parsed)
    records = []
    seen = set()
    for rec in candidates:
        extra = rec.get("extra") or {}
        ups = rec.get("value")
        bound = extra.get("staleness_bound")
        if ups is None or bound is None:
            continue
        name = str(rec.get("config") or rec.get("metric") or "async")
        if name in seen:
            continue
        seen.add(name)
        records.append({
            "name": name,
            "updates_per_sec": float(ups),
            "staleness_bound": int(bound),
            "max_realized_staleness": extra.get("max_realized_staleness"),
            "staleness_clamped": extra.get("staleness_clamped"),
            "backpressure_shed": extra.get("backpressure_shed"),
            # hierarchical multi-version entries (hier_async_*) carry
            # the per-tier breakdown the staleness-bound gate prints
            "hier_edges": extra.get("hier_edges"),
            "async_versions": extra.get("async_versions"),
            "per_version_absorbed": extra.get("per_version_absorbed"),
            "per_edge_absorbed": extra.get("per_edge_absorbed"),
        })
    return records


def _tail_weak_scale_records(doc, parsed) -> List[Dict[str, Any]]:
    """weak_scale_* bench records carried by one BENCH_r*.json — either
    the file's own parsed entry (a dedicated weak-scale run) or extra
    JSON lines in its raw ``tail`` (a ``--matrix`` run prints one line
    per config; ``parsed`` keeps only the last). Normalized to the few
    fields the weak-scaling report needs; anything unparsable or
    missing fields is skipped, never a KeyError — the r01+ history
    predates weak scaling entirely and must keep loading clean."""
    records = []
    candidates: List[Dict[str, Any]] = []
    for line in str(doc.get("tail") or "").splitlines():
        line = line.strip()
        if not (line.startswith("{") and "weak_scale" in line):
            continue
        try:
            candidates.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    if (
        "weak_scale" in str(parsed.get("metric") or "")
        or str(parsed.get("config") or "").startswith("weak_scale")
        # a direct `bench.py --config weak_scale_*` record carries no
        # `config` key and its metric reads "weak scaling: ..." — the
        # per-chip-cohort extra is the reliable marker
        or (parsed.get("extra") or {}).get("weak_scale_per_chip_cohort")
        is not None
    ):
        candidates.append(parsed)
    seen = set()
    for rec in candidates:
        extra = rec.get("extra") or {}
        per_chip = extra.get("weak_scale_per_chip_cohort")
        name = rec.get("config") or extra.get("weak_scale_name") or (
            f"weak_scale_{per_chip}" if per_chip is not None
            else rec.get("metric")
        )
        ups = extra.get("client_updates_per_sec_per_chip")
        chips = extra.get("n_chips")
        if name is None or ups is None or chips is None:
            continue
        key = (str(name), int(chips))
        if key in seen:
            continue
        seen.add(key)
        records.append({
            "name": str(name),
            "n_chips": int(chips),
            "per_chip_cohort": per_chip,
            "cohort_size": extra.get("cohort_size"),
            "updates_per_sec_per_chip": float(ups),
            "cohort_layout": extra.get("cohort_layout"),
        })
    return records


DEFAULT_PHASE_REGRESSION_FACTOR = 1.25


def bench_report(entries: Sequence[Dict[str, Any]],
                 budgets: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Trajectory deltas + budget gates over a bench history.

    ``budgets`` is the checked-in baseline (``BENCH_BUDGETS.json``):

    - ``rounds_per_sec_min`` / ``mfu_pct_min`` — floors on the LATEST
      entry (the scalar gates, generalized from bench.py's ``_gate``).
    - ``phase_budget_ms`` — explicit per-phase ms/round ceilings.
    - ``phase_regression_factor`` — for phases with no explicit budget,
      the ceiling is best-so-far (earlier entries) × factor.

    Gates never fire on ``n/a`` (a missing field is a provenance gap,
    not a regression); they fire the moment the field exists and
    exceeds its budget, naming the offending phase.
    """
    budgets = budgets or {}
    factor = float(budgets.get("phase_regression_factor",
                               DEFAULT_PHASE_REGRESSION_FACTOR))
    explicit = budgets.get("phase_budget_ms") or {}
    # best-so-far per phase over all but the latest measurable entry
    measurable = [e for e in entries if e.get("value") is not None]
    latest = measurable[-1] if measurable else None
    best_phase: Dict[str, float] = {}
    best_value = None
    for e in measurable[:-1]:
        if e.get("value") is not None:
            best_value = max(best_value or 0.0, e["value"])
        for ph, ms in (e.get("phase_ms_per_round") or {}).items():
            if ph not in best_phase or ms < best_phase[ph]:
                best_phase[ph] = ms
    violations: List[str] = []
    if latest is not None:
        rps_min = budgets.get("rounds_per_sec_min")
        if rps_min is not None and latest["value"] < float(rps_min):
            violations.append(
                f"rounds_per_sec {latest['value']:.3f} < budget floor "
                f"{float(rps_min):.3f} ({latest['file']})"
            )
        mfu_min = budgets.get("mfu_pct_min")
        if (mfu_min is not None and latest.get("mfu_pct") is not None
                and latest["mfu_pct"] < float(mfu_min)):
            violations.append(
                f"mfu_pct {latest['mfu_pct']:.2f} < budget floor "
                f"{float(mfu_min):.2f} ({latest['file']})"
            )
        # host-exposed ceiling: the observability tax budget — fires
        # only when the entry carries the field (histories predating it
        # render n/a, never a gate), so BENCH_r01+ keeps passing
        host_max = budgets.get("host_exposed_pct_max")
        if (host_max is not None
                and latest.get("host_exposed_pct") is not None
                and latest["host_exposed_pct"] > float(host_max)):
            violations.append(
                f"host_exposed_pct {latest['host_exposed_pct']:.1f} "
                f"> budget ceiling {float(host_max):.1f} "
                f"({latest['file']})"
            )
        # measured-vs-analytic flop drift ceiling: the cost-model truth
        # gate — |drift| over budget means the analytic phase model and
        # the XLA cost_analysis of the compiled round program no longer
        # agree. Fires only when the entry carries the extra (r01–r19
        # histories render n/a, never a gate)
        drift_max = budgets.get("flop_drift_pct_max")
        if (drift_max is not None
                and latest.get("flop_model_drift_pct") is not None
                and abs(latest["flop_model_drift_pct"]) > float(drift_max)):
            violations.append(
                f"flop_model_drift_pct "
                f"{latest['flop_model_drift_pct']:+.2f} exceeds "
                f"± budget ceiling {float(drift_max):.2f} "
                f"({latest['file']})"
            )
        for ph, ms in (latest.get("phase_ms_per_round") or {}).items():
            if ph in explicit:
                budget = float(explicit[ph])
                src = "explicit budget"
            elif ph in best_phase:
                budget = best_phase[ph] * factor
                src = f"best-so-far {best_phase[ph]:.2f} ms × {factor}"
            else:
                continue  # first appearance of the phase: becomes the pin
            if ms > budget:
                violations.append(
                    f"phase {ph}: {ms:.2f} ms/round exceeds "
                    f"{budget:.2f} ms ({src})"
                )
    # async-throughput floor (the promoted FedBuff plane): gate the
    # NEWEST history entry that carries an async_throughput record —
    # histories that predate the entry never fire (n/a, not a gate)
    ups_min = budgets.get("async_updates_per_sec_min")
    if ups_min is not None:
        with_async = [e for e in entries if e.get("async_throughput")]
        if with_async:
            for rec in with_async[-1]["async_throughput"]:
                if rec["updates_per_sec"] < float(ups_min):
                    violations.append(
                        f"async updates/sec {rec['updates_per_sec']:.1f} "
                        f"< budget floor {float(ups_min):.1f} "
                        f"({rec['name']}, {with_async[-1]['file']})"
                    )
    # hierarchical-async staleness ceiling: the hier_async_* entries
    # gate on BOTH axes — the shared throughput floor above AND the
    # realized-staleness bound here, so trading staleness for
    # throughput cannot pass the report
    # store-gather throughput floor (the store data plane): gate the
    # NEWEST entry carrying store_gather records — histories that
    # predate the extras never fire (n/a is a provenance gap, not a
    # regression)
    mbps_min = budgets.get("store_gather_mbps_min")
    if mbps_min is not None:
        with_store = [e for e in entries if e.get("store_gather")]
        if with_store:
            for rec in with_store[-1]["store_gather"]:
                if rec["store_gather_mbps"] < float(mbps_min):
                    violations.append(
                        f"store gather {rec['store_gather_mbps']:.1f} "
                        f"MiB/s < budget floor {float(mbps_min):.1f} "
                        f"({rec['name']}, {with_store[-1]['file']})"
                    )
    stale_max = budgets.get("hier_async_staleness_bound")
    if stale_max is not None:
        with_async = [e for e in entries if e.get("async_throughput")]
        if with_async:
            for rec in with_async[-1]["async_throughput"]:
                if "hier_async" not in rec["name"]:
                    continue
                ms = rec.get("max_realized_staleness")
                if ms is not None and int(ms) > int(stale_max):
                    violations.append(
                        f"hier async realized staleness {int(ms)} "
                        f"> budget bound {int(stale_max)} "
                        f"({rec['name']}, {with_async[-1]['file']})"
                    )
    return {
        "entries": list(entries),
        "latest": latest,
        "best_phase_ms": best_phase,
        "violations": violations,
        "weak_scaling": weak_scaling_report(entries),
    }


def weak_scaling_report(entries: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Weak-scaling efficiency over the history's ``weak_scale_*``
    records: updates/sec/chip at each chip count relative to the same
    per-chip-cohort workload's 1-chip pin (ideal weak scaling holds
    efficiency at 1.0 as chips × cohort grow together). Groups by
    per-chip cohort; when no 1-chip measurement exists yet the
    smallest-chip-count record becomes the pin (recorded as
    ``pin_n_chips`` so the readout stays honest). Empty list when the
    history carries no weak_scale entries — the r01+ era — which
    formats as ``n/a``, never an error."""
    groups: Dict[Any, List[Dict[str, Any]]] = {}
    for e in entries:
        for r in e.get("weak_scale") or []:
            key = r.get("per_chip_cohort")
            if key is None:
                key = r.get("name")
            groups.setdefault(key, []).append(dict(r, file=e.get("file")))
    out: List[Dict[str, Any]] = []
    for key in sorted(groups, key=str):
        recs = groups[key]
        pins = [r for r in recs if r.get("n_chips") == 1]
        pin = pins[-1] if pins else min(recs, key=lambda r: r["n_chips"])
        pin_ups = pin["updates_per_sec_per_chip"]
        for r in sorted(recs, key=lambda r: (r["n_chips"], str(r.get("file")))):
            out.append({
                "group": key,
                "name": r.get("name"),
                "file": r.get("file"),
                "n_chips": r["n_chips"],
                "cohort_size": r.get("cohort_size"),
                "updates_per_sec_per_chip": r["updates_per_sec_per_chip"],
                "cohort_layout": r.get("cohort_layout"),
                "pin_n_chips": pin["n_chips"],
                "efficiency": (
                    r["updates_per_sec_per_chip"] / pin_ups
                    if pin_ups else None
                ),
            })
    return out


def format_bench_report(report: Dict[str, Any], bench_dir: str = "") -> str:
    entries = report["entries"]
    lines = [
        f"bench trajectory"
        + (f" ({bench_dir})" if bench_dir else "")
        + f": {len(entries)} entries"
    ]
    lines.append(
        f"{'entry':<18}{'r/s':>8}{'vs_base':>9}{'mfu%':>8}"
        f"{'basis':>11}{'dtype':>10}{'dev ms':>8}"
        f"{'chips':>7}{'upd/s/chip':>12}{'host%':>7}{'mode':>8}"
    )
    for e in entries:
        lines.append(
            f"{e['file']:<18}"
            f"{_na(e.get('value'), '{:.3f}'):>8}"
            f"{_na(e.get('vs_baseline'), '{:.3f}'):>9}"
            f"{_na(e.get('mfu_pct'), '{:.2f}'):>8}"
            f"{_na(e.get('mfu_basis')):>11}"
            f"{_na(e.get('compute_dtype')):>10}"
            f"{_na(e.get('device_ms_per_round'), '{:.1f}'):>8}"
            f"{_na(e.get('n_chips')):>7}"
            f"{_na(e.get('updates_per_sec_per_chip'), '{:.1f}'):>12}"
            f"{_na(e.get('host_exposed_pct'), '{:.1f}'):>7}"
            f"{_na(e.get('control_plane')):>8}"
        )
    latest = report.get("latest")
    phases = (latest or {}).get("phase_ms_per_round")
    if phases:
        best = report.get("best_phase_ms") or {}
        lines.append("")
        lines.append(f"{'phase (latest)':<24}{'ms/round':>10}"
                     f"{'best':>10}{'Δ vs best':>11}")
        for ph in sorted(phases, key=lambda p: -phases[p]):
            b = best.get(ph)
            delta = ("n/a" if b is None or b == 0
                     else f"{100.0 * (phases[ph] - b) / b:+.0f}%")
            lines.append(
                f"{ph:<24}{phases[ph]:>10.2f}"
                f"{_na(b, '{:.2f}'):>10}{delta:>11}"
            )
    elif latest is not None:
        lines.append("")
        lines.append("per-phase ms: n/a (history predates phase_ms extras)")
    ws = report.get("weak_scaling") or []
    lines.append("")
    if ws:
        lines.append("weak scaling (updates/sec/chip vs the pin):")
        for r in ws:
            eff = _na(r.get("efficiency"), "{:.2f}")
            note = (
                "" if r.get("pin_n_chips") == 1
                else f"  [pin: {r['pin_n_chips']}-chip]"
            )
            lines.append(
                f"  {str(r.get('name')):<22}{r['n_chips']:>3} chip(s)"
                f"{r['updates_per_sec_per_chip']:>12.1f} upd/s/chip"
                f"   eff {eff}{note}"
            )
    else:
        lines.append(
            "weak scaling: n/a (no weak_scale_* entries in this history)"
        )
    lines.append("")
    if report["violations"]:
        lines.append("GATE FAILURES:")
        lines.extend(f"  {v}" for v in report["violations"])
    else:
        lines.append("gates: PASS")
    return "\n".join(lines)
