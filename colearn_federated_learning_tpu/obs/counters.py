"""Per-round communication + device-memory counters.

The byte accounting is an **analytic wire model**, not a measurement:
the simulator never serializes client messages, so the honest number is
the bytes the configured protocol WOULD move — a pure function of the
config, the model size, and the round's realized participation. That
purity is what makes the sharded and sequential engines agree bit-for-
bit on the counters (pinned by ``tests/test_obs.py``), and what lets
``summarize`` report a run's total traffic without replaying it.

Model, per participating client:

- uplink raw: one params-sized delta at the server param dtype.
- uplink wire: ``secure_aggregation`` ships dense int32 (4 B/coord —
  masking IS the wire format); ``topk`` ships k (value, index) pairs at
  8 B each; ``qsgd`` ships ~(1 sign + ⌈log2 levels⌉) bits/coord (the
  per-tensor norm scalars are noise at model scale and ignored);
  otherwise the raw delta.
- downlink raw: one params-sized broadcast per client that STARTED the
  round (dropouts downloaded before failing; stragglers too).
- downlink wire: ``downlink_compression='qsgd'`` quantizes the
  broadcast the same way; otherwise raw.

Gossip has no server: per mixing sweep each client exchanges its
boundary replica rows with two ring neighbours (or everything under
``full``), so the modeled traffic is symmetric — reported as equal
upload/download halves of the sweep volume.

The performance observatory (:mod:`~colearn_federated_learning_tpu.
obs.roofline`) extends the same analytic-purity discipline from wire
bytes to FLOPs/HBM bytes per round-program phase; its ``local_train``
byte floor consumes :func:`round_host_input_bytes`, and the waterfall's
padding component consumes :func:`round_shape_stats`'s
``padded_step_fraction`` gauge.
"""

from __future__ import annotations

import math
from typing import Dict


def _qsgd_bits(levels: int) -> int:
    # sign bit + level index; levels=1 degenerates to sign-only
    return 1 + max(1, math.ceil(math.log2(max(levels, 2))))


def round_comm_bytes(server, n_participants: int, n_downloads: int,
                     n_coords: int, param_bytes: int) -> Dict[str, int]:
    """Wire/raw upload+download bytes for one centralized round.

    ``server`` is a :class:`~colearn_federated_learning_tpu.config.
    ServerConfig`; ``n_participants`` is the number of clients whose
    update actually aggregates (dropouts excluded), ``n_downloads`` the
    number that received the broadcast (the real — non-pad — cohort).
    """
    if server.secure_aggregation:
        up_wire = n_coords * 4  # dense int32 masked fixed-point
    elif server.compression == "topk":
        k = max(1, int(round(server.compression_topk_ratio * n_coords)))
        up_wire = k * 8  # 4 B value + 4 B index per kept coordinate
    elif server.compression == "qsgd":
        up_wire = math.ceil(
            n_coords * _qsgd_bits(server.compression_qsgd_levels) / 8
        )
    else:
        up_wire = param_bytes
    if server.downlink_compression == "qsgd":
        down_wire = math.ceil(
            n_coords * _qsgd_bits(server.downlink_qsgd_levels) / 8
        )
    else:
        down_wire = param_bytes
    return {
        "upload_bytes": int(n_participants) * up_wire,
        "upload_bytes_raw": int(n_participants) * param_bytes,
        "download_bytes": int(n_downloads) * down_wire,
        "download_bytes_raw": int(n_downloads) * param_bytes,
    }


def gossip_round_bytes(num_clients: int, mixing_steps: int, topology: str,
                       param_bytes: int) -> Dict[str, int]:
    """Symmetric neighbour-exchange traffic for one gossip round: under
    ``ring`` each client sends its replica to 2 neighbours per sweep;
    under ``full`` every sweep is an all-to-all average (modeled as one
    replica broadcast per client per sweep — the allreduce-equivalent
    volume, not N² point-to-point)."""
    fan_out = 2 if topology == "ring" else 1
    vol = int(num_clients) * fan_out * int(mixing_steps) * param_bytes
    return {
        "upload_bytes": vol,
        "upload_bytes_raw": vol,
        "download_bytes": vol,
        "download_bytes_raw": vol,
    }


def round_host_input_bytes(k: int, steps: int, batch: int,
                           on_device_mask: bool) -> int:
    """Analytic host→device wire bytes for one round's index inputs:
    the ``[K, steps, batch]`` int32 gather indices, the validity-mask
    input — the full ``[K, steps, batch]`` float32 slab on the legacy
    path, the ``[K, 2]`` int32 spec when the engine rebuilds the mask
    on device — and the ``[K]`` float32 FedAvg weights. Same
    pure-function honesty contract as :func:`round_comm_bytes`: this is
    what the configured input format WOULD move, so removing the mask
    slab shows up as exactly its byte count."""
    idx_b = int(k) * int(steps) * int(batch) * 4
    mask_b = int(k) * 2 * 4 if on_device_mask else idx_b
    return idx_b + mask_b + int(k) * 4


def round_shape_stats(spec, steps: int, batch: int,
                      local_epochs: int) -> Dict[str, float]:
    """Padded-step / wasted-FLOP gauges for one round's ``[K, 2]`` mask
    spec on a ``steps × batch`` grid.

    - ``padded_step_fraction``: fraction of the cohort's scan steps
      that are complete no-ops (no real example) — each costs a full
      training step of device FLOPs on the padded grid.
    - ``padded_example_fraction``: fraction of grid POSITIONS that are
      padding (counts partially-filled tail batches too — the
      mask-weighted FLOP waste, the complement of effective MFU).
    """
    import numpy as np

    spec = np.asarray(spec)
    k = len(spec)
    if k == 0 or steps == 0:
        return {"padded_step_fraction": 0.0, "padded_example_fraction": 0.0}
    spe = max(1, steps // max(1, local_epochs))
    n = spec[:, 0].astype(np.int64)
    vsteps = spec[:, 1].astype(np.int64)
    real_steps = np.zeros(k, np.int64)
    real_examples = np.zeros(k, np.int64)
    for e in range(local_epochs):
        avail = np.clip(vsteps - e * spe, 0, spe)
        real_steps += np.minimum(-(-n // batch), avail)
        real_examples += np.minimum(n, avail * batch)
    total_steps = k * steps
    total_examples = total_steps * batch
    return {
        "padded_step_fraction": round(
            1.0 - float(real_steps.sum()) / total_steps, 4
        ),
        "padded_example_fraction": round(
            1.0 - float(real_examples.sum()) / total_examples, 4
        ),
    }


def device_memory_stats() -> Dict[str, int]:
    """Current device-memory gauges from ``jax`` memory stats, or ``{}``
    when the backend reports none (CPU, older runtimes)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
    except Exception:
        return {}
    out = {}
    for src, dst in (
        ("bytes_in_use", "hbm_in_use_bytes"),
        ("peak_bytes_in_use", "hbm_peak_bytes"),
        ("bytes_limit", "hbm_limit_bytes"),
    ):
        if src in stats:
            out[dst] = int(stats[src])
    return out
